"""deepspeed_tpu — a TPU-native training & inference framework with DeepSpeed's capabilities.

This package re-implements the capability surface of DeepSpeed (reference:
``deepspeed/__init__.py``) idiomatically for TPU: JAX/XLA for the compute path,
GSPMD sharding (``jax.sharding``) for ZeRO/TP/EP/SP/PP, Pallas for hot kernels,
and plain host Python/C++ for the runtime around it.

The top-level API mirrors ``deepspeed.initialize()`` (reference
``deepspeed/__init__.py:69``): the user keeps their model and training loop and
receives an engine that subsumes optimizer, mixed precision, distributed
communication, and checkpointing.
"""

__version__ = "0.1.0"

from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu import comm as dist


# reference deepspeed/__init__.py:25-48 export surface, resolved lazily so
# `import deepspeed_tpu` stays cheap (no jax/flax import until first use)
_LAZY_EXPORTS = {
    "DeepSpeedEngine": ("deepspeed_tpu.runtime.engine", "DeepSpeedEngine"),
    "DeepSpeedHybridEngine": ("deepspeed_tpu.runtime.hybrid_engine",
                              "DeepSpeedHybridEngine"),
    "PipelineEngine": ("deepspeed_tpu.runtime.pipe.engine", "PipelineEngine"),
    "PipelineModule": ("deepspeed_tpu.runtime.pipe.module", "PipelineModule"),
    "InferenceEngine": ("deepspeed_tpu.inference.engine", "InferenceEngine"),
    "DeepSpeedInferenceConfig": ("deepspeed_tpu.inference.config",
                                 "DeepSpeedInferenceConfig"),
    "DeepSpeedTransformerLayer": ("deepspeed_tpu.ops.transformer",
                                  "DeepSpeedTransformerLayer"),
    "DeepSpeedTransformerConfig": ("deepspeed_tpu.ops.transformer",
                                   "DeepSpeedTransformerConfig"),
    "init_distributed": ("deepspeed_tpu.comm.comm", "init_distributed"),
    "get_accelerator": ("deepspeed_tpu.accelerator.real_accelerator",
                        "get_accelerator"),
    "log_dist": ("deepspeed_tpu.utils.logging", "log_dist"),
    "logger": ("deepspeed_tpu.utils.logging", "logger"),
    "zero": ("deepspeed_tpu.runtime.zero", None),
    "OnDevice": ("deepspeed_tpu.utils", "OnDevice"),
    "add_tuning_arguments": ("deepspeed_tpu.runtime.lr_schedules",
                             "add_tuning_arguments"),
    "checkpointing": ("deepspeed_tpu.runtime.activation_checkpointing."
                      "checkpointing", None),
    "DeepSpeedConfigError": ("deepspeed_tpu.runtime.config",
                             "DeepSpeedConfigError"),
    "ADAM_OPTIMIZER": ("deepspeed_tpu.runtime.engine", "ADAM_OPTIMIZER"),
    "LAMB_OPTIMIZER": ("deepspeed_tpu.runtime.engine", "LAMB_OPTIMIZER"),
    "is_compile_supported": ("deepspeed_tpu.runtime.compiler",
                             "is_compile_supported"),
    "replace_transformer_layer": ("deepspeed_tpu.module_inject",
                                  "replace_transformer_layer"),
    "revert_transformer_layer": ("deepspeed_tpu.module_inject",
                                 "revert_transformer_layer"),
    "module_inject": ("deepspeed_tpu.module_inject", None),
}


def __getattr__(name):
    entry = _LAZY_EXPORTS.get(name)
    if entry is not None:
        import importlib
        module = importlib.import_module(entry[0])
        return module if entry[1] is None else getattr(module, entry[1])
    raise AttributeError(f"module 'deepspeed_tpu' has no attribute {name!r}")


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               distributed_port=29500,
               mesh=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               rng=None):
    """Initialize the DeepSpeed-TPU engine.

    Mirrors ``deepspeed.initialize`` (reference ``deepspeed/__init__.py:69``):
    returns a tuple of ``(engine, optimizer, training_dataloader, lr_scheduler)``.

    Arguments:
        args: an object whose ``deepspeed_config`` attribute (if present) names a
            JSON config file, as in the reference CLI glue.
        model: the model to wrap. Either a ``flax.linen.Module`` whose
            ``__call__(params-batch)`` returns a scalar loss, or a pure callable
            ``fn(params, batch, rng) -> loss``. See
            ``deepspeed_tpu.runtime.engine.DeepSpeedEngine`` for the contract.
        optimizer: optional user optimizer *name or callable factory* overriding
            the config's ``optimizer`` section (reference allows a torch optimizer
            instance; here the functional equivalent is a factory).
        model_parameters: the initial parameter pytree (fp32). If ``None`` the
            model must be a flax module and ``training_data`` must be provided so
            the engine can initialize parameters from the first batch shape.
        training_data: optional dataset (anything indexable / iterable of numpy
            batches) wrapped into a ``DeepSpeedDataLoader``.
        lr_scheduler: optional schedule name/callable overriding config.
        mesh: optional ``jax.sharding.Mesh``; by default one is built from the
            config's parallel sizes over all visible devices.
        mpu: optional model-parallel-unit object (reference
            ``deepspeed/__init__.py:69`` Megatron interop): its
            ``get_model_parallel_world_size()`` seeds the mesh's ``tp`` axis
            when the config carries no ``tensor_parallel`` section. On TPU
            the mesh IS the process-group topology, so only the size is
            consumed — group handles are compiler-managed.
        config: dict or path to a DeepSpeed-style JSON config.
        config_params: legacy alias for ``config`` (reference
            ``deepspeed/__init__.py:125``).
        rng: optional ``jax.random.PRNGKey`` seed or key for dropout etc.

    Returns:
        tuple of (engine, optimizer_shim, training_dataloader, lr_scheduler_shim)
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config"):
        config = args.deepspeed_config

    if mpu is not None and not isinstance(config, DeepSpeedConfig):
        import copy
        import json as _json
        if isinstance(config, str):     # JSON config file path
            with open(config) as f:
                config = _json.load(f)
        # deep-copy: never mutate the caller's (possibly reused) dict
        config = copy.deepcopy(config or {})
        tp = int(mpu.get_model_parallel_world_size())
        config.setdefault("tensor_parallel", {}).setdefault("tp_size", tp)

    # engine selection (reference deepspeed/__init__.py:166-206): hybrid
    # engine for RLHF configs, else the standard engine (PipelineEngine is
    # selected by passing a PipelineModule to deepspeed_tpu.pipe)
    ds_config = config if isinstance(config, DeepSpeedConfig) else DeepSpeedConfig(config)
    engine_cls = DeepSpeedEngine
    if ds_config.hybrid_engine_enabled:
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine
        engine_cls = DeepSpeedHybridEngine
    config = ds_config

    engine = engine_cls(config=config,
                             model=model,
                             optimizer=optimizer,
                             model_parameters=model_parameters,
                             training_data=training_data,
                             lr_scheduler=lr_scheduler,
                             mesh=mesh,
                             collate_fn=collate_fn,
                             rng=rng)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, params=None, **kwargs):
    """Build an inference engine (mirrors ``deepspeed.init_inference``,
    reference ``deepspeed/__init__.py:273``). ``params`` is the parameter
    pytree (TPU analog of the reference's already-loaded torch module
    weights); remaining kwargs overlay the config."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    cfg = DeepSpeedInferenceConfig.from_dict(config or {}, **kwargs)
    return InferenceEngine(model, cfg, params=params)


def add_config_arguments(parser):
    """Add the DeepSpeed CLI flags to an argparse parser (reference
    ``deepspeed/__init__.py:250``): ``--deepspeed`` enable flag and
    ``--deepspeed_config <json>`` consumed by :func:`initialize` via
    ``args.deepspeed_config``."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user scripts)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed json configuration file.")
    import argparse
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse.SUPPRESS)
    return parser
