"""Elastic execution agent — relaunch-on-membership-change supervision.

Reference ``elasticity/elastic_agent.py:32`` (``DSElasticAgent``) delegates to
torch.distributed.elastic: workers are monitored, and on failure or
membership change the whole gang is restarted with a recomputed environment.
The TPU-native agent supervises the launcher's worker processes directly:

- spawns one process per host (the launcher's env contract, plus
  ``DS_ELASTIC_WORLD_SIZE`` so engines resolve the elastic micro-batch);
- on any worker failure, kills the gang, re-reads the hostfile (membership
  may have changed — preempted/healed hosts), validates the new world size
  against the elastic-compatible set (``compute_elastic_config``), and
  relaunches, up to ``max_restarts`` times;
- the new gang resumes from the latest checkpoint (universal checkpoints make
  the state topology-independent — checkpoint/universal.py).
"""

import collections
import os
import subprocess
import sys
import time

from deepspeed_tpu.elasticity.elasticity import (ElasticityError,
                                                 compute_elastic_config)
from deepspeed_tpu.launcher.runner import (build_ssh_command, node_env,
                                           parse_hostfile)
from deepspeed_tpu.resilience import (EXIT_CLEAN_PREEMPTION,
                                      EXIT_RESHARD_SLICE_LOSS)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.utils.retry import BackoffPolicy, retry_call

_LOCAL_HOSTS = ("localhost", "127.0.0.1", "::1")


class DSElasticAgent:
    """Supervise an elastic multi-host gang (reference elastic_agent.py:32).

    Exit-code contract (docs/RESILIENCE.md): a worker exiting with
    :data:`EXIT_CLEAN_PREEMPTION` (83) performed a clean preemption
    hand-off — state is checkpointed — so the relaunch does NOT count
    against ``max_restarts``. A worker exiting
    :data:`EXIT_RESHARD_SLICE_LOSS` (84) detected a reshardable slice loss
    and saved an emergency universal checkpoint — the agent **shrinks**:
    hard-crashed hosts are excluded and the survivors are relaunched at the
    reduced world size, also budget-free (the fault is the platform's).
    Excluded hosts are **re-admitted** — the expand leg — when the
    membership source changes content (operator healed the hostfile) or an
    injectable ``host_probe(host)`` reports them healthy again. Any other
    non-zero exit is a failure and burns restart budget. Relaunch delays
    follow the shared exponential backoff + full jitter policy
    (utils/retry.py) instead of a fixed sleep, so a flapping resource isn't
    hammered in lock-step.
    """

    def __init__(self, user_script, user_args=(), ds_config=None,
                 hostfile=None, hosts=None, master_addr="127.0.0.1",
                 master_port=29500, max_restarts=3, launcher="local",
                 restart_backoff=1.0, backoff=None, allow_reshard=True,
                 host_probe=None, reshard_grace=10.0):
        assert (hostfile is None) != (hosts is None), \
            "pass exactly one of hostfile / hosts"
        self.user_script = user_script
        self.user_args = list(user_args)
        self.ds_config = ds_config or {}
        self.hostfile = hostfile
        self.static_hosts = list(hosts) if hosts else None
        self.master_addr = master_addr
        self.master_port = master_port
        self.max_restarts = max_restarts
        self.launcher = launcher
        self.restart_backoff = restart_backoff
        # restart_backoff seeds the exponential ladder's base so existing
        # callers keep their knob; tests inject a jitter-free policy
        self.backoff = backoff if backoff is not None else BackoffPolicy(
            base=restart_backoff, factor=2.0,
            max_delay=max(restart_backoff, 30.0), jitter="full")
        self.allow_reshard = allow_reshard
        self.host_probe = host_probe  # injectable: host -> bool (healthy?)
        self.reshard_grace = reshard_grace  # s to let survivors flag exit 84
        self.restarts = 0       # failures charged against max_restarts
        self.preemptions = 0    # clean preemptions (budget-free relaunches)
        self.reshards = 0       # slice-loss reshards (budget-free)
        self.restart_reasons = []
        self.restart_counts = collections.Counter()
        self.world_history = []
        self._excluded = []        # hosts dropped by a shrink, launch order
        self._excluded_sig = None  # membership snapshot at exclusion time

    # -- membership ------------------------------------------------------
    def _host_pool(self):
        if self.static_hosts is not None:
            return list(self.static_hosts)
        return list(parse_hostfile(self.hostfile))

    def current_hosts(self):
        """The live membership: the host pool minus shrink-excluded hosts.
        Re-admission (the expand leg of the shrink/expand state machine):
        a changed pool CONTENT (the operator rewrote the hostfile after
        healing the slice) clears all exclusions; otherwise each excluded
        host is individually re-probed via ``host_probe`` when provided."""
        pool = self._host_pool()
        if self._excluded:
            if tuple(pool) != self._excluded_sig:
                logger.info(f"elastic agent: membership changed; re-admitting "
                            f"{self._excluded}")
                self._excluded = []
            elif self.host_probe is not None:
                healed = [h for h in self._excluded if self.host_probe(h)]
                if healed:
                    logger.info(f"elastic agent: probe healed {healed}; "
                                f"re-admitting")
                    self._excluded = [h for h in self._excluded
                                      if h not in healed]
        # exclusions are by launch position, not name: local drills reuse
        # "localhost" aliases, so drop by identity in pool order
        hosts = list(pool)
        for h in self._excluded:
            if h in hosts:
                hosts.remove(h)
        return hosts

    def _validate_world(self, n_hosts):
        ec = self.ds_config.get("elasticity", {})
        if not ec.get("enabled", False):
            return None  # non-elastic config: any world size goes
        final_batch, valid, mbs = compute_elastic_config(
            self.ds_config, world_size=n_hosts, return_microbatch=True)
        return {"final_batch": final_batch, "micro_batch": mbs}

    # -- gang lifecycle --------------------------------------------------
    def _spawn(self, hosts, resolved):
        program = [sys.executable, self.user_script] + self.user_args
        procs = []
        for rank, host in enumerate(hosts):
            env = node_env(rank, len(hosts), self.master_addr,
                           self.master_port)
            env["DS_ELASTIC_WORLD_SIZE"] = str(len(hosts))
            env["DS_ELASTIC_RESTART_COUNT"] = str(self.restarts)
            env["DS_ELASTIC_RESHARD_COUNT"] = str(self.reshards)
            if resolved:
                env["DS_ELASTIC_MICRO_BATCH"] = str(resolved["micro_batch"])
                env["DS_ELASTIC_FINAL_BATCH"] = str(resolved["final_batch"])
            if self.launcher == "ssh" and host not in _LOCAL_HOSTS:
                cmd = build_ssh_command(host, env, program)
                # -tt: allocate a tty so killing the ssh client HUPs the
                # remote worker — otherwise a relaunched gang collides with
                # survivors of the old one (port/TPU lock already held)
                cmd.insert(1, "-tt")
                spawn = lambda c=cmd: subprocess.Popen(c)
            else:
                spawn = lambda e=env: subprocess.Popen(
                    program, env=dict(os.environ, **e))
            # the ssh/exec itself can fail transiently (host still
            # rebooting after preemption) — retry with backoff+jitter
            procs.append(retry_call(spawn, retries=2, base_delay=0.5,
                                    max_delay=5.0, retry_on=(OSError,)))
        return procs

    @staticmethod
    def _kill(procs):
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 10
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()

    def run(self):
        """Supervise until the gang exits cleanly or restarts are exhausted.
        Returns the final exit code."""
        while True:
            hosts = self.current_hosts()
            try:
                resolved = self._validate_world(len(hosts))
            except ElasticityError as e:
                logger.error(f"elastic agent: world size {len(hosts)} invalid: {e}")
                return 1
            self.world_history.append(len(hosts))
            logger.info(f"elastic agent: launching gang of {len(hosts)} "
                        f"(attempt {self.restarts + 1}, "
                        f"resolved={resolved})")
            procs = self._spawn(hosts, resolved)

            bad = []
            while True:
                alive = [p for p in procs if p.poll() is None]
                done = [p for p in procs if p.poll() is not None]
                bad = [p.returncode for p in done if p.returncode != 0]
                if bad:
                    break
                if not alive:
                    return 0  # clean gang exit
                time.sleep(0.2)

            # a hard death races the survivors' own detection of the slice
            # loss: the SIGKILL'd hosts are observed first, while the
            # survivors are still timing out their collectives. Give
            # still-running workers a short grace window to flag the
            # reshard themselves (exit 84) before the gang is torn down —
            # otherwise every partial crash looks unflagged and burns
            # restart budget instead of shrinking.
            if self.allow_reshard and \
                    any(rc not in (0, EXIT_CLEAN_PREEMPTION) for rc in bad):
                deadline = time.time() + self.reshard_grace
                while time.time() < deadline and \
                        any(p.poll() is None for p in procs):
                    time.sleep(0.1)
            # classify each host's fate BEFORE killing the gang — kill
            # overwrites the return codes the state machine keys on
            rcs = [p.poll() for p in procs]
            self._kill(procs)
            # hard = hosts that actually died with the slice (SIGKILL /
            # crash); flagged = survivors that DETECTED the loss, saved an
            # emergency universal checkpoint, and exited 84 asking to be
            # relaunched on the shrunken gang
            hard = [h for h, rc in zip(hosts, rcs)
                    if rc not in (None, 0, EXIT_CLEAN_PREEMPTION,
                                  EXIT_RESHARD_SLICE_LOSS)]
            flagged = any(rc == EXIT_RESHARD_SLICE_LOSS for rc in rcs)
            # exit-code contract: every failing worker exited
            # EXIT_CLEAN_PREEMPTION -> checkpointed before dying — relaunch
            # for free; exit 84 is the explicit reshard signal — a worker
            # VERIFIED the loss and saved an emergency universal checkpoint
            # first, so shrinking is safe and budget-free (hard crashes
            # alone stay plain failures: a worker bug must not silently
            # shrink the job); anything else burns restart budget
            preempted = all(rc == EXIT_CLEAN_PREEMPTION for rc in bad)
            reshard = not preempted and self.allow_reshard and flagged
            if reshard:
                reason = "reshard"
            elif preempted:
                reason = "preemption"
            else:
                reason = f"worker_exit_{bad[0]}"
            self.restart_reasons.append(reason)
            self.restart_counts[reason] += 1
            self._record_restart(reason, len(hosts))
            if preempted:
                self.preemptions += 1
                if self.preemptions > max(10, 3 * self.max_restarts):
                    logger.error("elastic agent: too many consecutive "
                                 "preemptions; giving up")
                    return 1
                logger.warning(
                    f"elastic agent: clean preemption (exit "
                    f"{EXIT_CLEAN_PREEMPTION}); relaunching without "
                    f"consuming restart budget "
                    f"({self.restarts}/{self.max_restarts} used)")
                time.sleep(self.backoff.delay(1))
                continue
            if reshard:
                self.reshards += 1
                if self.reshards > max(10, 3 * self.max_restarts):
                    logger.error("elastic agent: too many reshards; "
                                 "giving up")
                    return 1
                if hard:
                    self._excluded.extend(hard)
                    self._excluded_sig = tuple(self._host_pool())
                survivors = len(hosts) - len(hard)
                logger.warning(
                    f"elastic agent: reshardable slice loss (exit "
                    f"{EXIT_RESHARD_SLICE_LOSS}, {len(hard)} hosts lost); "
                    f"relaunching {survivors} survivors budget-free "
                    f"(reshard #{self.reshards}); universal checkpoint "
                    f"reshard-restores on the shrunken mesh")
                time.sleep(self.backoff.delay(min(self.reshards, 4)))
                continue
            self.restarts += 1
            if self.restarts > self.max_restarts:
                logger.error("elastic agent: restart budget exhausted")
                return 1
            delay = self.backoff.delay(self.restarts)
            logger.warning(
                f"elastic agent: worker failure ({reason}); re-reading "
                f"membership and relaunching "
                f"({self.restarts}/{self.max_restarts}) after {delay:.2f}s")
            time.sleep(delay)

    def _record_restart(self, reason, n_hosts):
        """Restart count + reason through telemetry (lazy import: the agent
        must stay usable on a host without jax — telemetry is stdlib-only
        but lives under the deepspeed_tpu namespace)."""
        try:
            from deepspeed_tpu import telemetry
            telemetry.record("Fault/worker", 1, kind="counter", reason=reason,
                             hosts=n_hosts, restarts=self.restarts,
                             preemptions=self.preemptions,
                             reshards=self.reshards)
            telemetry.count("elastic/restart", reason=reason)
            telemetry.record("elastic/world_size", n_hosts, kind="gauge",
                             event=reason)
        except Exception:
            pass


def main(args=None):
    """``ds_elastic``-style CLI (reference ``bin/ds_elastic``)."""
    import argparse
    import json

    parser = argparse.ArgumentParser(description="deepspeed_tpu elastic agent")
    parser.add_argument("--hostfile", required=True)
    parser.add_argument("--deepspeed_config", default=None)
    parser.add_argument("--master_addr", default="127.0.0.1")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--launcher", default="ssh", choices=["ssh", "local"])
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs="...")
    args = parser.parse_args(args)
    ds_config = {}
    if args.deepspeed_config:
        with open(args.deepspeed_config) as f:
            ds_config = json.load(f)
    agent = DSElasticAgent(args.user_script, args.user_args, ds_config,
                           hostfile=args.hostfile,
                           master_addr=args.master_addr,
                           master_port=args.master_port,
                           max_restarts=args.max_restarts,
                           launcher=args.launcher)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
