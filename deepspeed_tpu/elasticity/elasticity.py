"""Elastic training — batch-size/chip-count compatibility sets.

Reference ``elasticity/elasticity.py``: pre-computes the set of (total batch,
micro batch, accelerator count) combinations that keep the global batch fixed,
so training can resume at any permitted world size without changing
optimization dynamics (:83 v0.1, :126 v0.2 which adds model-parallel
awareness); ``compute_elastic_config`` (:233) resolves the final batch triple
for the current world size, and the engine enforces membership at init.

The chip-count analog of "GPUs" is TPU chips (``jax.device_count`` across
hosts); elastic re-launch itself is the scheduler's job (GKE/Borg preemption
+ ``jax.distributed`` re-init) — this module owns the batch math and
enforcement, and universal checkpoints (checkpoint/universal.py) own the
state resharding on resume.
"""

from functools import reduce

from deepspeed_tpu.utils.logging import logger


class ElasticityError(Exception):
    pass


def _candidate_batch_sizes(micro_batches, max_acceptable_batch_size):
    """All feasible total batch sizes: mbs * gas <= max (reference
    _get_candidate_batch_sizes)."""
    candidates = set()
    for mbs in micro_batches:
        gas = max_acceptable_batch_size // mbs
        if gas > 0:
            candidates.add(mbs * gas)
    return sorted(candidates)


def _compatible_gpus_for_batch(batch, micro_batches, min_gpus, max_gpus):
    """Accelerator counts that evenly consume ``batch`` with some micro batch
    (reference _get_compatible_gpus)."""
    valid = set()
    for mbs in micro_batches:
        if batch % mbs:
            continue
        total_micro = batch // mbs
        for g in range(min_gpus, min(max_gpus, total_micro) + 1):
            if total_micro % g == 0:
                valid.add(g)
    return sorted(valid)


def get_compatible_gpus(micro_batches, max_acceptable_batch_size,
                        min_gpus=1, max_gpus=10000, prefer_larger=True,
                        version=0.2, model_parallel_size=1):
    """Pick the total batch size maximizing chip-count coverage (reference
    v0.1 :83 / v0.2 :126; v0.2 scales counts by the model-parallel size).

    Returns (final_batch_size, valid_chip_counts)."""
    if version >= 0.2 and model_parallel_size > 1:
        # chips come in model-parallel groups; DP world = chips / mp
        min_gpus = max(1, min_gpus // model_parallel_size)
        max_gpus = max_gpus // model_parallel_size
    best = (0, 0, [])  # (coverage, batch, gpus)
    for batch in _candidate_batch_sizes(micro_batches, max_acceptable_batch_size):
        gpus = _compatible_gpus_for_batch(batch, micro_batches, min_gpus, max_gpus)
        if not gpus:
            continue
        coverage = len(gpus)
        key = (coverage, batch if prefer_larger else -batch)
        if key > (best[0], best[1] if prefer_larger else -best[1]):
            best = (coverage, batch, gpus)
    if not best[2]:
        raise ElasticityError(
            f"no compatible batch size for micro_batches={micro_batches}, "
            f"max={max_acceptable_batch_size}, gpus=[{min_gpus},{max_gpus}]")
    if version >= 0.2 and model_parallel_size > 1:
        return best[1], [g * model_parallel_size for g in best[2]]
    return best[1], best[2]


def elasticity_enabled(ds_config):
    ec = ds_config.get("elasticity", {}) if isinstance(ds_config, dict) \
        else getattr(ds_config, "elasticity_config", None)
    if isinstance(ec, dict):
        return bool(ec.get("enabled", False))
    return bool(ec and ec.enabled)


def compute_elastic_config(ds_config, target_deployment=None, world_size=0,
                           return_microbatch=False):
    """Resolve the elastic batch configuration (reference :233).

    Returns (final_batch_size, valid_gpus[, micro_batch]) — and when
    ``world_size`` > 0, validates membership and computes the micro batch
    that satisfies batch = mbs * gas * world_size."""
    ec = ds_config.get("elasticity", {}) if isinstance(ds_config, dict) else {}
    if not ec.get("enabled", False):
        raise ElasticityError("elasticity not enabled in config")
    micro_batches = ec.get("micro_batch_sizes", [2, 4, 6])
    final_batch, valid_gpus = get_compatible_gpus(
        micro_batches=micro_batches,
        max_acceptable_batch_size=ec.get("max_train_batch_size", 2000),
        min_gpus=ec.get("min_gpus", 1), max_gpus=ec.get("max_gpus", 10000),
        prefer_larger=ec.get("prefer_larger_batch", True),
        version=float(ec.get("version", 0.2)),
        model_parallel_size=int(ec.get("model_parallel_size", 1)))
    logger.info(f"elasticity: final_batch={final_batch} valid_chip_counts={valid_gpus}")
    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityError(
                f"world size {world_size} not in the elastic-compatible set "
                f"{valid_gpus} for batch {final_batch}")
        # largest micro batch that divides this world's per-chip share
        per_gpu = final_batch // world_size
        mbs = max((m for m in micro_batches if per_gpu % m == 0), default=None)
        if mbs is None:
            raise ElasticityError(
                f"no micro batch in {micro_batches} divides per-chip batch {per_gpu}")
        if return_microbatch:
            return final_batch, valid_gpus, mbs
        return final_batch, valid_gpus
    if return_microbatch:
        return final_batch, valid_gpus, None
    return final_batch, valid_gpus
