from deepspeed_tpu.elasticity.elasticity import (compute_elastic_config,
                                                 elasticity_enabled,
                                                 get_compatible_gpus)

__all__ = ["compute_elastic_config", "elasticity_enabled", "get_compatible_gpus"]
