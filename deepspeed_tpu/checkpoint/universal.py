"""Universal checkpointing — topology-independent fp32 fragments.

Reference ``checkpoint/ds_to_universal.py`` (extract ``extract_zero_shards``
:88, merge ``merge_tp_slices`` :171) + loader ``universal_checkpoint.py`` +
offline ``utils/zero_to_fp32.py``: ZeRO shards are merged into per-parameter
fp32 fragment files (fp32 weight, exp_avg, exp_avg_sq) keyed by parameter
name, loadable at ANY (TP, PP, DP) topology.

On TPU the engine state is a tree of GSPMD global arrays, so "merge shards"
is a device_get and "reshard at load" is a device_put under the new mesh —
the heavy lifting the reference does by file surgery falls out of the array
model. The universal format here is one npz of name-keyed fragments
(``<param>::fp32`` / ``::exp_avg`` / ``::exp_avg_sq``) + a JSON manifest
(step counters, LR scheduler state), produced from a live engine
(``save_universal_checkpoint``) or offline from a saved checkpoint directory
(``ds_to_universal``), and loaded into any engine whose parameter tree has
the same *names* — regardless of mesh shape, ZeRO stage, offload mode or
qwZ quantization.
"""

import json
import os
import shutil

import numpy as np

import jax

from deepspeed_tpu.utils.tensor_fragment import (moment_leaves, opt_param_paths,
                                                 param_paths_by_key)

UNIVERSAL_ARRAYS = "universal_fragments.npz"
UNIVERSAL_META = "universal_meta.json"
#: pointer file naming the newest durably-published universal tag — written
#: with the same tmp+fsync+rename dance as the engine's 'latest'
LATEST_UNIVERSAL = "latest_universal"


def _keyed(tree):
    return {jax.tree_util.keystr(p): l
            for p, l in jax.tree_util.tree_flatten_with_path(tree)[0]}


def _opt_step_count(opt_state):
    """The optax Adam step counter (max over ``count`` leaves; 0 if none)."""
    best = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(opt_state)[0]:
        if any(getattr(p, "name", None) == "count" for p in path):
            try:
                best = max(best, int(np.asarray(jax.device_get(leaf))))
            except (TypeError, ValueError):
                pass
    return best


def _streamed_slots(engine):
    """Map the ZeRO-Infinity param tier's (block, leaf) cells onto the
    model's CANONICAL tree paths, via a sentinel pass through
    ``streaming_merge``. Each full path maps to an ordered [(block_i,
    leaf_j), ...] list — length L for stacked-scan families (fragment
    carries the leading scan dim), length 1 for per-layer families like
    Mixtral (fragment is that layer's leaf). Universal fragments therefore
    use identical names whether the engine streams or not."""
    store = engine._param_store
    L = store.num_blocks
    sentinel = jax.tree_util.tree_unflatten(
        store._treedef,
        [np.arange(j * L, (j + 1) * L) for j in range(len(store._paths))])
    merged = engine.module.streaming_merge({}, sentinel)
    slots = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(merged)[0]:
        flat = np.asarray(leaf).reshape(-1)
        slots[jax.tree_util.keystr(path)] = [(int(v) % L, int(v) // L)
                                             for v in flat]
    return slots


def _topology_meta(topology):
    """The saving topology, recorded so a load at a different world can name
    the remap it performed (:func:`topology_remap`)."""
    return {
        "world_size": topology.world_size(),
        "axes": {a: topology.get_dim(a) for a in topology.axis_names},
        "zero_hierarchy": topology.zero_hierarchy,
    }


def save_universal_checkpoint(engine, out_dir, tag=None):
    """Write universal fragments from a live engine (the online equivalent of
    reference ``ds_to_universal.py`` main). ``tag`` becomes a subdirectory,
    mirroring ``save_checkpoint``'s dir/tag layout.

    Crash-consistent: fragments + meta are written into a ``.tmp.<pid>``
    sibling, fsynced, then atomically swapped into place (the checkpoint
    engine's publish dance, same ``ckpt.publish`` fault point) — a crash at
    ANY instant leaves either the previous complete tag or the new one,
    never a torn npz. With ``tag``, the :data:`LATEST_UNIVERSAL` pointer in
    the parent dir is updated (atomically) only AFTER the tag is durable,
    so the elastic reshard path always restores from a complete tag."""
    from deepspeed_tpu.runtime.checkpoint_engine.native_engine import (
        _publish_dir, atomic_write_text)
    root = out_dir
    if tag is not None:
        out_dir = os.path.join(out_dir, str(tag))
    parent = os.path.dirname(os.path.abspath(out_dir))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{os.path.abspath(out_dir)}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)  # stale crash leftovers
    os.makedirs(tmp)
    blobs = {}
    masters = engine.get_model_parameters(dtype=np.float32)  # gathers all tiers
    keyed = _keyed(masters)
    for k, v in keyed.items():
        blobs[f"{k}::fp32"] = np.asarray(v, dtype=np.float32)

    if engine._offload is not None:
        swap_states = (engine._offload.swapper.state_arrays()
                       if engine._offload.swapper is not None else None)
        for k in engine._offload.masters:
            shape = engine._offload.shapes[k]
            if swap_states is not None:
                m, v = swap_states[k]
            else:
                m, v = engine._offload.adam.state_for(
                    k, engine._offload.masters[k].size)
            blobs[f"{k}::exp_avg"] = np.asarray(m, np.float32).reshape(shape)
            blobs[f"{k}::exp_avg_sq"] = np.asarray(v, np.float32).reshape(shape)
    if engine._param_store is not None:
        # ZeRO-Infinity param tier: host moments re-keyed to canonical paths
        store = engine._param_store
        for fk, entries in _streamed_slots(engine).items():
            ms, vs = [], []
            for (i, j) in entries:
                m, v = store.get_moments(i, j)
                shape = tuple(store.block_shapes[j])
                ms.append(np.asarray(m, np.float32).reshape(shape))
                vs.append(np.asarray(v, np.float32).reshape(shape))
            blobs[f"{fk}::exp_avg"] = ms[0] if len(ms) == 1 else np.stack(ms)
            blobs[f"{fk}::exp_avg_sq"] = vs[0] if len(vs) == 1 else np.stack(vs)

    # device-resident moments (the whole tree, or the offload remainder)
    for fk, (_, leaf) in moment_leaves(engine.state.opt_state,
                                       opt_param_paths(engine)).items():
        blobs[fk] = np.asarray(jax.device_get(leaf), dtype=np.float32)

    meta = {
        "counters": {
            "global_steps": engine.global_steps,
            "global_samples": engine.global_samples,
            "micro_steps": engine.micro_steps,
        },
        "lr_scheduler": engine.lr_scheduler.state_dict(),
        "param_keys": sorted(keyed),
        # optax bias-correction step (distinct from global_steps when fp16
        # overflow skips occurred)
        "optimizer_step": _opt_step_count(engine.state.opt_state),
        "topology": _topology_meta(engine.topology),
        "format": "deepspeed_tpu_universal_v1",
    }
    try:
        for name, writer in ((UNIVERSAL_ARRAYS,
                              lambda f: np.savez(f, **blobs)),
                             (UNIVERSAL_META,
                              lambda f: f.write(json.dumps(meta)))):
            mode = "wb" if name.endswith(".npz") else "w"
            with open(os.path.join(tmp, name), mode) as f:
                writer(f)
                f.flush()
                os.fsync(f.fileno())
        _publish_dir(tmp, out_dir)  # trips the ckpt.publish fault point
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if tag is not None:
        atomic_write_text(os.path.join(root, LATEST_UNIVERSAL), str(tag))
    return out_dir


def latest_universal_tag(root):
    """The newest durably-published universal tag under ``root``, or None.
    Reads the :data:`LATEST_UNIVERSAL` pointer; falls back to scanning for
    complete tag dirs (both fragment files present — torn ``.tmp.`` dirs
    are never candidates) newest-mtime-first when the pointer is missing
    or stale."""
    ptr = os.path.join(root, LATEST_UNIVERSAL)
    if os.path.exists(ptr):
        with open(ptr) as f:
            tag = f.read().strip()
        if tag and os.path.exists(os.path.join(root, tag, UNIVERSAL_META)):
            return tag
    candidates = []
    if os.path.isdir(root):
        for name in os.listdir(root):
            d = os.path.join(root, name)
            if ".tmp." in name or ".old." in name or not os.path.isdir(d):
                continue
            if os.path.exists(os.path.join(d, UNIVERSAL_ARRAYS)) and \
                    os.path.exists(os.path.join(d, UNIVERSAL_META)):
                candidates.append((os.path.getmtime(d), name))
    return max(candidates)[1] if candidates else None


def read_universal_meta(universal_dir):
    with open(os.path.join(universal_dir, UNIVERSAL_META)) as f:
        return json.load(f)


def topology_remap(meta, topology):
    """Describe the topology remap a load of ``meta`` onto ``topology``
    performs (the elastic reshard path's accounting record): fragments are
    name-keyed and fp32, so the remap is exact — this computes the world /
    per-axis deltas, it does not transform data."""
    saved = meta.get("topology") or {}
    new_axes = {a: topology.get_dim(a) for a in topology.axis_names}
    old_axes = saved.get("axes", {})
    return {
        "from_world": saved.get("world_size"),
        "to_world": topology.world_size(),
        "resharded": bool(saved) and saved.get("world_size") !=
            topology.world_size(),
        "axis_deltas": {a: (old_axes.get(a), new_axes[a])
                        for a in new_axes
                        if old_axes.get(a) != new_axes[a]},
        "zero_hierarchy": (saved.get("zero_hierarchy"),
                           topology.zero_hierarchy),
    }


def ds_to_universal(ckpt_dir, out_dir, engine):
    """Offline conversion of an engine checkpoint directory (reference
    ``ds_to_universal.py``): load it into ``engine`` (any topology), then
    re-emit universal fragments."""
    engine.load_checkpoint(os.path.dirname(ckpt_dir), tag=os.path.basename(ckpt_dir))
    return save_universal_checkpoint(engine, out_dir)


def _set_all_masters(engine, new_by_key):
    """Replace every master value named in ``new_by_key`` in ONE pass over
    each tier (linear, unlike per-param safe_set); returns the count set."""
    import jax.numpy as jnp  # noqa: F401 (used in both branches)
    loaded = [0]

    def rep(path, leaf):
        k = jax.tree_util.keystr(path)
        if k in new_by_key:
            loaded[0] += 1
            val = np.asarray(new_by_key[k], dtype=np.float32)
            return jax.device_put(jnp.asarray(val, dtype=leaf.dtype),
                                  leaf.sharding) if hasattr(leaf, "sharding") \
                else val
        return leaf

    if engine._param_store is not None:
        store = engine._param_store
        for fk, entries in _streamed_slots(engine).items():
            if fk not in new_by_key:
                continue
            arr = np.asarray(new_by_key[fk], np.float32)
            for idx, (i, j) in enumerate(entries):
                store.set_master(i, j, arr[idx] if len(entries) > 1 else arr)
            loaded[0] += 1
        store._publish_from_masters()
        if engine.state.master is not None:
            engine.state = engine.state._replace(
                master=jax.tree_util.tree_map_with_path(rep, engine.state.master))
        else:
            engine.state = engine.state._replace(
                params=jax.tree_util.tree_map_with_path(rep, engine.state.params))
        return loaded[0]
    if engine._offload is not None:
        for k, buf in engine._offload.masters.items():
            if k in new_by_key:
                buf[:] = np.asarray(new_by_key[k], np.float32).reshape(-1)
                loaded[0] += 1
        # device remainder: the master dict's keys ARE the canonical names
        new_master = {}
        for k, leaf in engine.state.master.items():
            if k in new_by_key:
                loaded[0] += 1
                new_master[k] = jax.device_put(
                    jnp.asarray(np.asarray(new_by_key[k], np.float32),
                                dtype=leaf.dtype), leaf.sharding)
            else:
                new_master[k] = leaf
        engine.state = engine.state._replace(master=new_master)
    elif engine.state.master is not None:
        engine.state = engine.state._replace(
            master=jax.tree_util.tree_map_with_path(rep, engine.state.master))
    else:
        engine.state = engine.state._replace(
            params=jax.tree_util.tree_map_with_path(rep, engine.state.params))
    return loaded[0]


def load_universal_checkpoint(engine, universal_dir, load_optimizer_states=True):
    """Load universal fragments into ``engine`` at its CURRENT topology
    (reference ``universal_checkpoint.py:117`` load_hp_checkpoint_state):
    fragments are matched by parameter name; device_put under the engine's
    mesh reshards them."""
    data = np.load(os.path.join(universal_dir, UNIVERSAL_ARRAYS))
    with open(os.path.join(universal_dir, UNIVERSAL_META)) as f:
        meta = json.load(f)
    frags = {k: data[k] for k in data.files}

    weights = {k: frags[f"{k}::fp32"] for k in meta["param_keys"]
               if f"{k}::fp32" in frags}
    missing = [k for k in meta["param_keys"] if k not in weights]
    if missing:
        raise ValueError(f"universal checkpoint missing fp32 fragments for {missing}")
    loaded = _set_all_masters(engine, weights)
    if loaded != len(weights):
        raise ValueError(
            f"only {loaded}/{len(weights)} parameters matched this engine's tree — "
            f"model structure differs from the checkpoint")
    # refresh the working copy from the new masters (the engine normally does
    # this inside the apply-step)
    engine._refresh_working_from_master()

    # counters BEFORE moments: the host Adam's step count derives from them
    c = meta.get("counters", {})
    engine.global_steps = int(c.get("global_steps", 0))
    engine.global_samples = int(c.get("global_samples", 0))
    engine.micro_steps = int(c.get("micro_steps", 0))
    if load_optimizer_states:
        _load_moments(engine, frags)
        _restore_opt_step_count(engine,
                                int(meta.get("optimizer_step",
                                             engine.global_steps)))
    if "lr_scheduler" in meta:
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    return loaded


def _load_moments(engine, frags):
    import jax.numpy as jnp
    if engine._param_store is not None:
        store = engine._param_store
        for fk, entries in _streamed_slots(engine).items():
            if f"{fk}::exp_avg" not in frags or f"{fk}::exp_avg_sq" not in frags:
                continue
            m = np.asarray(frags[f"{fk}::exp_avg"], np.float32)
            v = np.asarray(frags[f"{fk}::exp_avg_sq"], np.float32)
            for idx, (i, j) in enumerate(entries):
                if len(entries) > 1:
                    store.set_moments(i, j, m[idx], v[idx])
                else:
                    store.set_moments(i, j, m, v)
    if engine._offload is not None:
        swap_updates = {}
        for k in engine._offload.masters:
            if f"{k}::exp_avg" not in frags or f"{k}::exp_avg_sq" not in frags:
                continue
            m = frags[f"{k}::exp_avg"].reshape(-1)
            v = frags[f"{k}::exp_avg_sq"].reshape(-1)
            if engine._offload.swapper is not None:
                swap_updates[k] = (m, v)  # NVMe owns the moments; keep DRAM clean
            else:
                engine._offload.adam.set_state(k, m, v)
        if swap_updates:
            engine._offload.swapper.load_state_arrays(swap_updates)
        # host adam.step_count is restored by _restore_opt_step_count

    # device-resident optax moments (covers both normal and offload-remainder)
    matches = moment_leaves(engine.state.opt_state, opt_param_paths(engine))
    by_path = {}
    for fk, (path, leaf) in matches.items():
        if fk in frags:
            by_path[path] = jax.device_put(
                jnp.asarray(frags[fk], leaf.dtype), leaf.sharding)

    def rep(path, leaf):
        return by_path.get(tuple(path), leaf)

    engine.state = engine.state._replace(
        opt_state=jax.tree_util.tree_map_with_path(rep, engine.state.opt_state))


def _restore_opt_step_count(engine, step):
    """Set every optax ``count`` leaf to the saved optimizer step so Adam
    bias correction resumes where it left off (the host tier's
    ``adam.step_count`` analog for device-resident state)."""
    import jax.numpy as jnp

    def rep(path, leaf):
        if any(getattr(p, "name", None) == "count" for p in path):
            return jax.device_put(jnp.asarray(step, leaf.dtype), leaf.sharding) \
                if hasattr(leaf, "sharding") else jnp.asarray(step, leaf.dtype)
        return leaf

    engine.state = engine.state._replace(
        opt_state=jax.tree_util.tree_map_with_path(rep, engine.state.opt_state))
    if engine._offload is not None:
        engine._offload.adam.step_count = step
    if engine._param_store is not None:
        engine._param_store.set_opt_step(step)


def get_fp32_state_dict_from_zero_checkpoint(universal_dir):
    """Offline fp32 weights extraction (reference ``utils/zero_to_fp32.py:604``)
    from a universal directory: returns {param_name: np.ndarray}."""
    data = np.load(os.path.join(universal_dir, UNIVERSAL_ARRAYS))
    return {k[:-len("::fp32")]: data[k] for k in data.files if k.endswith("::fp32")}
