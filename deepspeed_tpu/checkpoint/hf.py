"""HuggingFace checkpoint interop: safetensors/torch state dicts <-> flax trees.

Capability analog of the reference's HF loading stack
(``inference/v2/checkpoint/huggingface_engine.py``,
``module_inject/replace_module.py:182`` checkpoint injection): real pretrained
weights in, servable/trainable parameter trees out — plus the inverse export so
``save_16bit_model`` emits a checkpoint ``from_pretrained`` can read.

Supported families: llama (llama/llama2/mistral/qwen2 — qwen2 adds qkv bias),
gpt2, opt, mixtral. Conventions handled:

- torch ``nn.Linear`` stores ``[out, in]`` -> flax kernels are ``[in, out]``
  (transposed); GPT-2's Conv1D is already ``[in, out]``.
- HF llama-family rotary is half-split (``rotate_half``: pairs ``(j, j+d/2)``)
  while the TPU models use interleaved pairs ``(2j, 2j+1)`` (better for the
  VPU's even/odd lanes): q/k projection output columns are permuted so the
  models compute identical attention. The export applies the inverse.
- ``scan_layers`` models stack per-layer tensors along axis 0.
"""

import json
import os
import re

import numpy as np

from deepspeed_tpu.utils.logging import logger

LLAMA_FAMILY = ("llama", "mistral", "qwen2")
SUPPORTED = LLAMA_FAMILY + ("gpt2", "opt", "mixtral", "falcon", "phi", "bloom",
                            "gpt_neox", "gptj", "bert", "roberta",
                            "distilbert", "qwen", "internlm")


class UnsupportedModelError(ValueError):
    """Model family the converters don't cover — callers may fall back
    (e.g. ``save_16bit_model`` degrades to an npz dump on exactly this)."""


# ---------------------------------------------------------------------------
# state-dict IO
# ---------------------------------------------------------------------------

def load_state_dict(model_dir):
    """Read every ``*.safetensors`` (preferred) or ``pytorch_model*.bin`` in
    ``model_dir`` into one {name: np.ndarray} dict."""
    sd = {}
    st_files = sorted(f for f in os.listdir(model_dir) if f.endswith(".safetensors"))
    if st_files:
        for f in st_files:
            path = os.path.join(model_dir, f)
            try:
                from safetensors.numpy import load_file
                sd.update(load_file(path))
            except (TypeError, ValueError):
                # bf16 tensors aren't numpy-native; round-trip through torch
                from safetensors.torch import load_file as load_torch
                for k, v in load_torch(path).items():
                    sd[k] = v.float().numpy()
        return sd
    bin_files = sorted(f for f in os.listdir(model_dir)
                       if re.match(r"pytorch_model.*\.bin$", f))
    if not bin_files:
        raise FileNotFoundError(f"no safetensors/bin weights in {model_dir}")
    import torch
    for f in bin_files:
        for k, v in torch.load(os.path.join(model_dir, f), map_location="cpu",
                               weights_only=True).items():
            sd[k] = v.float().numpy() if v.dtype == torch.bfloat16 else v.numpy()
    return sd


def save_safetensors(state_dict, model_dir, filename="model.safetensors"):
    from safetensors.numpy import save_file
    os.makedirs(model_dir, exist_ok=True)
    save_file({k: np.ascontiguousarray(v) for k, v in state_dict.items()},
              os.path.join(model_dir, filename))
    return os.path.join(model_dir, filename)


def detect_model_type(model_dir):
    with open(os.path.join(model_dir, "config.json")) as f:
        return json.load(f)["model_type"]


# ---------------------------------------------------------------------------
# rotary convention permutation (half-split <-> interleaved)
# ---------------------------------------------------------------------------

def _rotary_perm(dh):
    """perm such that interleaved[..., p[i]] reads half-split[..., i]."""
    perm = np.empty(dh, dtype=np.int64)
    perm[0::2] = np.arange(dh // 2)
    perm[1::2] = np.arange(dh // 2) + dh // 2
    return perm


def _permute_qk_out(mat, n_heads, dh, inverse=False, rotary_dim=None):
    """Permute the per-head output dim (last axis) of a q/k projection
    (kernel [in, H*Dh] or bias [H*Dh]) between rotary conventions.
    ``rotary_dim`` < dh permutes only the rotated slice (phi partial rotary)."""
    rd = dh if rotary_dim is None else rotary_dim
    perm = np.concatenate([_rotary_perm(rd), np.arange(rd, dh)])
    if inverse:
        perm = np.argsort(perm)
    shaped = mat.reshape(mat.shape[:-1] + (n_heads, dh))
    return shaped[..., perm].reshape(mat.shape)


# ---------------------------------------------------------------------------
# llama family (llama / mistral / qwen2)
# ---------------------------------------------------------------------------

def _stack(layers):
    return np.stack(layers, axis=0)


def llama_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    """HF llama/mistral/qwen2 state dict -> our LlamaForCausalLM tree
    (models/llama.py)."""
    H, KV, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    L = cfg.num_hidden_layers

    def g(name):
        return sd[name].astype(dtype)

    def lin(name, heads=None):
        w = g(name).T  # [out,in] -> [in,out]
        if heads is not None:
            w = _permute_qk_out(w, heads, Dh)
        return w

    def bias(name, heads=None):
        key = name
        if key not in sd:
            return None
        b = g(key)
        if heads is not None:
            b = _permute_qk_out(b, heads, Dh)
        return b

    def layer(i):
        p = f"model.layers.{i}."
        attn = {"q_proj": {"kernel": lin(p + "self_attn.q_proj.weight", H)},
                "k_proj": {"kernel": lin(p + "self_attn.k_proj.weight", KV)},
                "v_proj": {"kernel": lin(p + "self_attn.v_proj.weight")},
                "o_proj": {"kernel": lin(p + "self_attn.o_proj.weight")}}
        for nm, heads in (("q_proj", H), ("k_proj", KV), ("v_proj", None),
                          ("o_proj", None)):   # o bias: InternLM family
            b = bias(p + f"self_attn.{nm}.bias", heads)
            if b is not None:
                attn[nm]["bias"] = b
        return {
            "input_layernorm": {"scale": g(p + "input_layernorm.weight")},
            "post_attention_layernorm": {"scale": g(p + "post_attention_layernorm.weight")},
            "self_attn": attn,
            "mlp": {"gate_proj": {"kernel": lin(p + "mlp.gate_proj.weight")},
                    "up_proj": {"kernel": lin(p + "mlp.up_proj.weight")},
                    "down_proj": {"kernel": lin(p + "mlp.down_proj.weight")}},
        }

    embed = g("model.embed_tokens.weight")
    lm_head = g("lm_head.weight") if "lm_head.weight" in sd else embed
    tree = {"embed_tokens": embed,
            "norm": {"scale": g("model.norm.weight")},
            "lm_head": lm_head}
    layers = [layer(i) for i in range(L)]
    if scan_layers:
        import jax
        tree["layers"] = {"block": jax.tree.map(lambda *xs: _stack(xs), *layers)}
    else:
        for i, l in enumerate(layers):
            tree[f"layers_{i}"] = l
    return tree


def llama_from_flax(params, cfg, dtype=np.float32):
    """Inverse of :func:`llama_to_flax` -> HF-named state dict."""
    import jax
    H, KV, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    L = cfg.num_hidden_layers
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)

    def layer_tree(i):
        if "layers" in params:
            return jax.tree.map(lambda x: x[i], params["layers"]["block"])
        return params[f"layers_{i}"]

    sd = {"model.embed_tokens.weight": params["embed_tokens"],
          "model.norm.weight": params["norm"]["scale"],
          "lm_head.weight": params["lm_head"]}
    for i in range(L):
        l = layer_tree(i)
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = l["input_layernorm"]["scale"]
        sd[p + "post_attention_layernorm.weight"] = l["post_attention_layernorm"]["scale"]
        at = l["self_attn"]
        sd[p + "self_attn.q_proj.weight"] = _permute_qk_out(
            at["q_proj"]["kernel"], H, Dh, inverse=True).T
        sd[p + "self_attn.k_proj.weight"] = _permute_qk_out(
            at["k_proj"]["kernel"], KV, Dh, inverse=True).T
        sd[p + "self_attn.v_proj.weight"] = at["v_proj"]["kernel"].T
        sd[p + "self_attn.o_proj.weight"] = at["o_proj"]["kernel"].T
        for nm, heads in (("q_proj", H), ("k_proj", KV), ("v_proj", None),
                          ("o_proj", None)):
            if "bias" in at[nm]:
                b = at[nm]["bias"]
                if heads is not None:
                    b = _permute_qk_out(b, heads, Dh, inverse=True)
                sd[p + f"self_attn.{nm}.bias"] = b
        sd[p + "mlp.gate_proj.weight"] = l["mlp"]["gate_proj"]["kernel"].T
        sd[p + "mlp.up_proj.weight"] = l["mlp"]["up_proj"]["kernel"].T
        sd[p + "mlp.down_proj.weight"] = l["mlp"]["down_proj"]["kernel"].T
    return sd


def llama_config_from_hf(hf_cfg, **overrides):
    """transformers LlamaConfig/MistralConfig/Qwen2Config -> our LlamaConfig."""
    from deepspeed_tpu.models.llama import LlamaConfig
    kw = dict(
        vocab_size=hf_cfg.vocab_size,
        hidden_size=hf_cfg.hidden_size,
        intermediate_size=hf_cfg.intermediate_size,
        num_hidden_layers=hf_cfg.num_hidden_layers,
        num_attention_heads=hf_cfg.num_attention_heads,
        num_key_value_heads=getattr(hf_cfg, "num_key_value_heads", None)
        or hf_cfg.num_attention_heads,
        max_position_embeddings=hf_cfg.max_position_embeddings,
        rms_norm_eps=hf_cfg.rms_norm_eps,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        head_dim=getattr(hf_cfg, "head_dim", None),
        attention_bias=bool(getattr(hf_cfg, "attention_bias", False)
                            or hf_cfg.model_type == "qwen2"),
        sliding_window=getattr(hf_cfg, "sliding_window", None)
        if getattr(hf_cfg, "use_sliding_window", True) else None,
    )
    kw.update(overrides)
    return LlamaConfig(**kw)


# ---------------------------------------------------------------------------
# qwen (v1) — the ORIGINAL Qwen architecture (QWenLMHeadModel, model_type
# "qwen", shipped via trust_remote_code). Llama-shaped with: fused biased
# c_attn (no GQA), unbiased c_proj attention output, and a swapped-gate MLP
# (intermediate = w1(x) * silu(w2(x)), i.e. gate_proj = w2, up_proj = w1,
# down_proj = c_proj, ff width = intermediate_size // 2). Reference policy:
# ``deepspeed/module_inject/containers/qwen.py`` (DS_QWenContainer).
# ---------------------------------------------------------------------------

def qwen_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    """Qwen-v1 HF state dict -> our LlamaForCausalLM tree."""
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    L = cfg.num_hidden_layers
    D = cfg.hidden_size

    def g(name):
        return sd[name].astype(dtype)

    def layer(i):
        p = f"transformer.h.{i}."
        # c_attn: [3D, D] rows = q|k|v; Qwen applies rotate_half like HF
        # llama, so the same qk permutation maps to our interleaved rotary
        w = g(p + "attn.c_attn.weight")
        b = g(p + "attn.c_attn.bias")
        qw, kw, vw = (w[j * D:(j + 1) * D].T for j in range(3))
        qb, kb, vb = (b[j * D:(j + 1) * D] for j in range(3))
        attn = {
            "q_proj": {"kernel": _permute_qk_out(qw, H, Dh),
                       "bias": _permute_qk_out(qb, H, Dh)},
            "k_proj": {"kernel": _permute_qk_out(kw, H, Dh),
                       "bias": _permute_qk_out(kb, H, Dh)},
            "v_proj": {"kernel": vw, "bias": vb},
            "o_proj": {"kernel": g(p + "attn.c_proj.weight").T},
        }
        return {
            "input_layernorm": {"scale": g(p + "ln_1.weight")},
            "post_attention_layernorm": {"scale": g(p + "ln_2.weight")},
            "self_attn": attn,
            "mlp": {"gate_proj": {"kernel": g(p + "mlp.w2.weight").T},
                    "up_proj": {"kernel": g(p + "mlp.w1.weight").T},
                    "down_proj": {"kernel": g(p + "mlp.c_proj.weight").T}},
        }

    tree = {"embed_tokens": g("transformer.wte.weight"),
            "norm": {"scale": g("transformer.ln_f.weight")},
            "lm_head": g("lm_head.weight")}
    layers = [layer(i) for i in range(L)]
    if scan_layers:
        import jax
        tree["layers"] = {"block": jax.tree.map(lambda *xs: _stack(xs), *layers)}
    else:
        for i, l in enumerate(layers):
            tree[f"layers_{i}"] = l
    return tree


def qwen_from_flax(params, cfg, dtype=np.float32):
    """Inverse of :func:`qwen_to_flax` -> Qwen-v1-named state dict."""
    import jax
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    L = cfg.num_hidden_layers
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)

    def layer_tree(i):
        if "layers" in params:
            return jax.tree.map(lambda x: x[i], params["layers"]["block"])
        return params[f"layers_{i}"]

    sd = {"transformer.wte.weight": params["embed_tokens"],
          "transformer.ln_f.weight": params["norm"]["scale"],
          "lm_head.weight": params["lm_head"]}
    for i in range(L):
        l = layer_tree(i)
        p = f"transformer.h.{i}."
        sd[p + "ln_1.weight"] = l["input_layernorm"]["scale"]
        sd[p + "ln_2.weight"] = l["post_attention_layernorm"]["scale"]
        at = l["self_attn"]
        qw = _permute_qk_out(at["q_proj"]["kernel"], H, Dh, inverse=True).T
        kw = _permute_qk_out(at["k_proj"]["kernel"], H, Dh, inverse=True).T
        vw = at["v_proj"]["kernel"].T
        sd[p + "attn.c_attn.weight"] = np.concatenate([qw, kw, vw], axis=0)
        qb = _permute_qk_out(at["q_proj"]["bias"], H, Dh, inverse=True)
        kb = _permute_qk_out(at["k_proj"]["bias"], H, Dh, inverse=True)
        sd[p + "attn.c_attn.bias"] = np.concatenate(
            [qb, kb, at["v_proj"]["bias"]], axis=0)
        sd[p + "attn.c_proj.weight"] = at["o_proj"]["kernel"].T
        sd[p + "mlp.w2.weight"] = l["mlp"]["gate_proj"]["kernel"].T
        sd[p + "mlp.w1.weight"] = l["mlp"]["up_proj"]["kernel"].T
        sd[p + "mlp.c_proj.weight"] = l["mlp"]["down_proj"]["kernel"].T
    return sd


def qwen_config_from_json(raw, **overrides):
    """Qwen-v1 config.json dict -> our LlamaConfig. NTK/log-n attention
    extrapolation (use_dynamic_ntk / use_logn_attn) is identity within the
    native seq_length window, which is what max_position_embeddings is set
    to; beyond-window extrapolation is not represented."""
    from deepspeed_tpu.models.llama import LlamaConfig
    if not raw.get("no_bias", True):
        raise UnsupportedModelError(
            "qwen with no_bias=false (biased c_proj/mlp) not represented")
    if raw.get("use_dynamic_ntk") or raw.get("use_logn_attn"):
        logger.warning(
            "qwen: use_dynamic_ntk/use_logn_attn are identity within the "
            "native seq_length window; beyond-window extrapolation is not "
            "represented (max_position_embeddings capped at seq_length)")
    kw = dict(
        vocab_size=raw["vocab_size"],
        hidden_size=raw["hidden_size"],
        intermediate_size=raw["intermediate_size"] // 2,
        num_hidden_layers=raw["num_hidden_layers"],
        num_attention_heads=raw["num_attention_heads"],
        num_key_value_heads=raw["num_attention_heads"],
        max_position_embeddings=raw.get("seq_length", 2048),
        rms_norm_eps=raw.get("layer_norm_epsilon", 1e-6),
        rope_theta=raw.get("rotary_emb_base", 10000.0),
        head_dim=raw.get("kv_channels", None),
        attention_bias=True,
    )
    kw.update(overrides)
    return LlamaConfig(**kw)


# ---------------------------------------------------------------------------
# gpt2
# ---------------------------------------------------------------------------

def gpt2_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    """HF GPT-2 (Conv1D: weights already [in, out]) -> models/gpt2.py tree."""
    L = cfg.n_layer

    def g(name):
        t = sd[name]
        return t.astype(dtype)

    def layer(i):
        p = f"h.{i}."
        return {
            "ln_1": {"scale": g(p + "ln_1.weight"), "bias": g(p + "ln_1.bias")},
            "ln_2": {"scale": g(p + "ln_2.weight"), "bias": g(p + "ln_2.bias")},
            "attn": {"c_attn": {"kernel": g(p + "attn.c_attn.weight"),
                                "bias": g(p + "attn.c_attn.bias")},
                     "c_proj": {"kernel": g(p + "attn.c_proj.weight"),
                                "bias": g(p + "attn.c_proj.bias")}},
            "mlp": {"c_fc": {"kernel": g(p + "mlp.c_fc.weight"),
                             "bias": g(p + "mlp.c_fc.bias")},
                    "c_proj": {"kernel": g(p + "mlp.c_proj.weight"),
                               "bias": g(p + "mlp.c_proj.bias")}},
        }

    sd = {k.removeprefix("transformer."): v for k, v in sd.items()}
    tree = {"wte": g("wte.weight"), "wpe": g("wpe.weight"),
            "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")}}
    layers = [layer(i) for i in range(L)]
    if scan_layers:
        import jax
        tree["h"] = {"block": jax.tree.map(lambda *xs: _stack(xs), *layers)}
    else:
        for i, l in enumerate(layers):
            tree[f"h_{i}"] = l
    return tree


def gpt2_from_flax(params, cfg, dtype=np.float32):
    import jax
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)
    L = cfg.n_layer
    sd = {"wte.weight": params["wte"], "wpe.weight": params["wpe"],
          "ln_f.weight": params["ln_f"]["scale"],
          "ln_f.bias": params["ln_f"]["bias"]}
    for i in range(L):
        l = (jax.tree.map(lambda x: x[i], params["h"]["block"])
             if "h" in params else params[f"h_{i}"])
        p = f"h.{i}."
        sd[p + "ln_1.weight"] = l["ln_1"]["scale"]
        sd[p + "ln_1.bias"] = l["ln_1"]["bias"]
        sd[p + "ln_2.weight"] = l["ln_2"]["scale"]
        sd[p + "ln_2.bias"] = l["ln_2"]["bias"]
        for blk, names in (("attn", ("c_attn", "c_proj")),
                           ("mlp", ("c_fc", "c_proj"))):
            for nm in names:
                sd[p + f"{blk}.{nm}.weight"] = l[blk][nm]["kernel"]
                sd[p + f"{blk}.{nm}.bias"] = l[blk][nm]["bias"]
    return sd


# ---------------------------------------------------------------------------
# opt
# ---------------------------------------------------------------------------

def opt_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    L = cfg.num_hidden_layers
    sd = {k.removeprefix("model."): v for k, v in sd.items()}

    def g(name):
        return sd[name].astype(dtype)

    def lin(p, nm):
        return {"kernel": g(p + nm + ".weight").T, "bias": g(p + nm + ".bias")}

    def ln(name):
        return {"scale": g(name + ".weight"), "bias": g(name + ".bias")}

    def layer(i):
        p = f"decoder.layers.{i}."
        return {
            "self_attn": {nm: lin(p + "self_attn.", nm)
                          for nm in ("q_proj", "k_proj", "v_proj", "out_proj")},
            "self_attn_layer_norm": ln(p + "self_attn_layer_norm"),
            "final_layer_norm": ln(p + "final_layer_norm"),
            "fc1": lin(p, "fc1"),
            "fc2": lin(p, "fc2"),
        }

    tree = {"embed_tokens": g("decoder.embed_tokens.weight"),
            "embed_positions": g("decoder.embed_positions.weight"),
            "final_layer_norm": ln("decoder.final_layer_norm")}
    layers = [layer(i) for i in range(L)]
    if scan_layers:
        import jax
        tree["layers"] = {"block": jax.tree.map(lambda *xs: _stack(xs), *layers)}
    else:
        for i, l in enumerate(layers):
            tree[f"layers_{i}"] = l
    return tree


def opt_from_flax(params, cfg, dtype=np.float32):
    import jax
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)
    L = cfg.num_hidden_layers
    sd = {"model.decoder.embed_tokens.weight": params["embed_tokens"],
          "model.decoder.embed_positions.weight": params["embed_positions"],
          "model.decoder.final_layer_norm.weight": params["final_layer_norm"]["scale"],
          "model.decoder.final_layer_norm.bias": params["final_layer_norm"]["bias"],
          "lm_head.weight": params["embed_tokens"]}
    for i in range(L):
        l = (jax.tree.map(lambda x: x[i], params["layers"]["block"])
             if "layers" in params else params[f"layers_{i}"])
        p = f"model.decoder.layers.{i}."
        for nm in ("q_proj", "k_proj", "v_proj", "out_proj"):
            sd[p + f"self_attn.{nm}.weight"] = l["self_attn"][nm]["kernel"].T
            sd[p + f"self_attn.{nm}.bias"] = l["self_attn"][nm]["bias"]
        for nm in ("fc1", "fc2"):
            sd[p + f"{nm}.weight"] = l[nm]["kernel"].T
            sd[p + f"{nm}.bias"] = l[nm]["bias"]
        for nm in ("self_attn_layer_norm", "final_layer_norm"):
            sd[p + f"{nm}.weight"] = l[nm]["scale"]
            sd[p + f"{nm}.bias"] = l[nm]["bias"]
    return sd


# ---------------------------------------------------------------------------
# mixtral
# ---------------------------------------------------------------------------

def mixtral_to_flax(sd, cfg, dtype=np.float32):
    """HF Mixtral -> models/mixtral.py tree (experts stacked [E, in, out])."""
    H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
    Dh = cfg.hidden_size // H
    L, E = cfg.num_hidden_layers, cfg.num_local_experts

    def g(name):
        return sd[name].astype(dtype)

    def lin(name, heads=None):
        w = g(name).T
        if heads is not None:
            w = _permute_qk_out(w, heads, Dh)
        return w

    tree = {"embed_tokens": g("model.embed_tokens.weight"),
            "norm": {"scale": g("model.norm.weight")},
            "lm_head": g("lm_head.weight") if "lm_head.weight" in sd
            else g("model.embed_tokens.weight")}
    for i in range(L):
        p = f"model.layers.{i}."
        experts = {w: _stack([g(p + f"block_sparse_moe.experts.{e}.{w}.weight").T
                              for e in range(E)]) for w in ("w1", "w2", "w3")}
        tree[f"layers_{i}"] = {
            "input_layernorm": {"scale": g(p + "input_layernorm.weight")},
            "post_attention_layernorm": {"scale": g(p + "post_attention_layernorm.weight")},
            "self_attn": {"q_proj": {"kernel": lin(p + "self_attn.q_proj.weight", H)},
                          "k_proj": {"kernel": lin(p + "self_attn.k_proj.weight", KV)},
                          "v_proj": {"kernel": lin(p + "self_attn.v_proj.weight")},
                          "o_proj": {"kernel": lin(p + "self_attn.o_proj.weight")}},
            "block_sparse_moe": {
                "gate": {"wg": lin(p + "block_sparse_moe.gate.weight")},
                "experts": {"MixtralExpertMLP_0": {
                    w: {"kernel": experts[w]} for w in ("w1", "w2", "w3")}}},
        }
    return tree


def mixtral_from_flax(params, cfg, dtype=np.float32):
    import jax
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)
    H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
    Dh = cfg.hidden_size // H
    L, E = cfg.num_hidden_layers, cfg.num_local_experts
    sd = {"model.embed_tokens.weight": params["embed_tokens"],
          "model.norm.weight": params["norm"]["scale"],
          "lm_head.weight": params["lm_head"]}
    for i in range(L):
        l = params[f"layers_{i}"]
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = l["input_layernorm"]["scale"]
        sd[p + "post_attention_layernorm.weight"] = l["post_attention_layernorm"]["scale"]
        at = l["self_attn"]
        sd[p + "self_attn.q_proj.weight"] = _permute_qk_out(
            at["q_proj"]["kernel"], H, Dh, inverse=True).T
        sd[p + "self_attn.k_proj.weight"] = _permute_qk_out(
            at["k_proj"]["kernel"], KV, Dh, inverse=True).T
        sd[p + "self_attn.v_proj.weight"] = at["v_proj"]["kernel"].T
        sd[p + "self_attn.o_proj.weight"] = at["o_proj"]["kernel"].T
        sd[p + "block_sparse_moe.gate.weight"] = l["block_sparse_moe"]["gate"]["wg"].T
        ex = l["block_sparse_moe"]["experts"]["MixtralExpertMLP_0"]
        for w in ("w1", "w2", "w3"):
            for e in range(E):
                sd[p + f"block_sparse_moe.experts.{e}.{w}.weight"] = ex[w]["kernel"][e].T
    return sd


# ---------------------------------------------------------------------------
# falcon / phi (parallel-residual families, models/parallel_block.py)
# ---------------------------------------------------------------------------

def _falcon_split_qkv(fused, H, KV, Dh, interleaved):
    """Fused QKV wire layout -> (q, k, v) on the OUTPUT axis (last).

    multi_query=True stores contiguous blocks [H q | KV k | KV v];
    multi_query=False stores per-head interleaved [H, (q,k,v), Dh]."""
    if not interleaved:
        return (fused[..., : H * Dh],
                fused[..., H * Dh: (H + KV) * Dh],
                fused[..., (H + KV) * Dh:])
    shaped = fused.reshape(fused.shape[:-1] + (H, 3, Dh))
    q, k, v = (shaped[..., j, :].reshape(fused.shape[:-1] + (H * Dh,))
               for j in range(3))
    return q, k, v


def _fuse_qkv_interleaved(q, k, v, H, Dh):
    """Inverse of ``_falcon_split_qkv(..., interleaved=True)``: our q|k|v
    concat (last axis) -> per-head [H, 3, Dh] wire layout. Works for kernels
    ([in, H*Dh] each) and biases ([H*Dh] each)."""
    shaped = [a.reshape(a.shape[:-1] + (H, Dh)) for a in (q, k, v)]
    return np.stack(shaped, axis=-2).reshape(q.shape[:-1] + (3 * H * Dh,))


def falcon_to_flax(sd, cfg, dtype=np.float32):
    """HF Falcon (7b lineage: parallel_attn, rotary) -> tree. Handles both
    multi_query (block QKV) and per-head-interleaved layouts, with or
    without linear biases."""
    H, KV, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    interleaved = KV == H  # multi_query=False stores per-head interleaved
    sd = {k.removeprefix("transformer."): v for k, v in sd.items()}

    def g(name):
        return sd[name].astype(dtype)

    def ln(p):
        return {"scale": g(p + ".weight"), "bias": g(p + ".bias")}

    def lin(p, transform=None):
        out = {"kernel": g(p + ".weight").T}
        if p + ".bias" in sd:
            out["bias"] = g(p + ".bias")
        if transform:
            out = {k: transform(v) for k, v in out.items()}
        return out

    def qkv_transform(w):
        # w: [..., (H+2KV)*Dh] wire layout -> our [q|k|v] block layout with
        # the rotary columns permuted to the interleaved convention
        q, k, v = _falcon_split_qkv(w, H, KV, Dh, interleaved)
        q = _permute_qk_out(q, H, Dh)
        k = _permute_qk_out(k, KV, Dh)
        return np.concatenate([q, k, v], axis=-1)

    embed = g("word_embeddings.weight")
    tree = {"embed_tokens": embed,
            "final_layernorm": ln("ln_f")}
    if not cfg.tie_lm_head:
        tree["lm_head"] = sd["lm_head.weight"].astype(dtype) \
            if "lm_head.weight" in sd else embed
    for i in range(cfg.num_hidden_layers):
        p = f"h.{i}."
        tree[f"layers_{i}"] = {
            "input_layernorm": ln(p + "input_layernorm"),
            "query_key_value": lin(p + "self_attention.query_key_value",
                                   transform=qkv_transform),
            "dense": lin(p + "self_attention.dense"),
            "fc1": lin(p + "mlp.dense_h_to_4h"),
            "fc2": lin(p + "mlp.dense_4h_to_h"),
        }
    return tree


def phi_to_flax(sd, cfg, dtype=np.float32):
    """HF Phi (phi-1.5/phi-2) -> tree (partial rotary, biases everywhere)."""
    H, KV, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    rd = cfg.rotary_dim

    def g(name):
        return sd[name].astype(dtype)

    def lin(p, heads=None):
        out = {"kernel": g(p + ".weight").T}
        if p + ".bias" in sd:
            out["bias"] = g(p + ".bias")
        if heads is not None:
            out = {k: _permute_qk_out(v, heads, Dh, rotary_dim=rd)
                   for k, v in out.items()}
        return out

    def ln(p):
        return {"scale": g(p + ".weight"), "bias": g(p + ".bias")}

    tree = {"embed_tokens": g("model.embed_tokens.weight"),
            "final_layernorm": ln("model.final_layernorm"),
            "lm_head": g("lm_head.weight")}
    if "lm_head.bias" in sd:
        tree["lm_head_bias"] = g("lm_head.bias")
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        tree[f"layers_{i}"] = {
            "input_layernorm": ln(p + "input_layernorm"),
            "q_proj": lin(p + "self_attn.q_proj", heads=H),
            "k_proj": lin(p + "self_attn.k_proj", heads=KV),
            "v_proj": lin(p + "self_attn.v_proj"),
            "dense": lin(p + "self_attn.dense"),
            "fc1": lin(p + "mlp.fc1"),
            "fc2": lin(p + "mlp.fc2"),
        }
    return tree


def gptneox_to_flax(sd, cfg, dtype=np.float32):
    """HF GPT-NeoX -> parallel-block tree (dual LN, fused interleaved QKV,
    partial rotary permuted to our interleaved convention)."""
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    rd = cfg.rotary_dim
    sd = {k.removeprefix("gpt_neox."): v for k, v in sd.items()}

    def g(name):
        return sd[name].astype(dtype)

    def ln(p):
        return {"scale": g(p + ".weight"), "bias": g(p + ".bias")}

    def lin(p, transform=None):
        out = {"kernel": g(p + ".weight").T, "bias": g(p + ".bias")}
        if transform:
            out = {k: transform(v) for k, v in out.items()}
        return out

    def qkv_transform(w):
        q, k, v = _falcon_split_qkv(w, H, H, Dh, interleaved=True)
        q = _permute_qk_out(q, H, Dh, rotary_dim=rd)
        k = _permute_qk_out(k, H, Dh, rotary_dim=rd)
        return np.concatenate([q, k, v], axis=-1)

    tree = {"embed_tokens": g("embed_in.weight"),
            "final_layernorm": ln("final_layer_norm")}
    if not cfg.tie_lm_head:
        # tied checkpoints drop embed_out from safetensors entirely
        tree["lm_head"] = (sd["embed_out.weight"].astype(dtype)
                           if "embed_out.weight" in sd
                           else tree["embed_tokens"])
    for i in range(cfg.num_hidden_layers):
        p = f"layers.{i}."
        tree[f"layers_{i}"] = {
            "input_layernorm": ln(p + "input_layernorm"),
            "post_attention_layernorm": ln(p + "post_attention_layernorm"),
            "query_key_value": lin(p + "attention.query_key_value",
                                   transform=qkv_transform),
            "dense": lin(p + "attention.dense"),
            "fc1": lin(p + "mlp.dense_h_to_4h"),
            "fc2": lin(p + "mlp.dense_4h_to_h"),
        }
    return tree


def gptj_to_flax(sd, cfg, dtype=np.float32):
    """HF GPT-J -> parallel-block tree. GPT-J's interleaved partial rotary is
    OUR native convention — no q/k permutation."""
    sd = {k.removeprefix("transformer."): v for k, v in sd.items()}

    def g(name):
        return sd[name].astype(dtype)

    def lin(p):
        out = {"kernel": g(p + ".weight").T}
        if p + ".bias" in sd:
            out["bias"] = g(p + ".bias")
        return out

    def ln(p):
        return {"scale": g(p + ".weight"), "bias": g(p + ".bias")}

    tree = {"embed_tokens": g("wte.weight"),
            "final_layernorm": ln("ln_f"),
            "lm_head": g("lm_head.weight")}
    if "lm_head.bias" in sd:
        tree["lm_head_bias"] = g("lm_head.bias")
    for i in range(cfg.num_hidden_layers):
        p = f"h.{i}."
        tree[f"layers_{i}"] = {
            "input_layernorm": ln(p + "ln_1"),
            "q_proj": lin(p + "attn.q_proj"),
            "k_proj": lin(p + "attn.k_proj"),
            "v_proj": lin(p + "attn.v_proj"),
            "dense": lin(p + "attn.out_proj"),
            "fc1": lin(p + "mlp.fc_in"),
            "fc2": lin(p + "mlp.fc_out"),
        }
    return tree


def _parallel_block_family(cfg):
    """Which HF family a ParallelBlockConfig describes — derivable from the
    architectural flags (used by export: the config carries no family tag)."""
    if cfg.dual_layernorm:
        return "gpt_neox"
    if cfg.fused_qkv:
        return "falcon"
    if not cfg._bias("qkv_bias") and cfg._bias("mlp_bias"):
        return "gptj"
    return "phi"


def parallel_block_from_flax(params, cfg, dtype=np.float32):
    """Inverse converters for the parallel-residual families
    (falcon/phi/gpt_neox/gptj). Returns (state_dict, hf_config_dict)."""
    import jax
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)
    H, KV, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
    fam = _parallel_block_family(cfg)
    rd = cfg.rotary_dim

    def unperm(mat, heads, rdim):
        return _permute_qk_out(mat, heads, Dh, inverse=True, rotary_dim=rdim)

    def fuse_interleaved(q, k, v):
        return _fuse_qkv_interleaved(q, k, v, H, Dh)

    sd = {}

    def put_lin(name, leaf, transpose=True):
        sd[name + ".weight"] = leaf["kernel"].T if transpose else leaf["kernel"]
        if "bias" in leaf:
            sd[name + ".bias"] = leaf["bias"]

    for i in range(cfg.num_hidden_layers):
        l = params[f"layers_{i}"]
        if fam == "gpt_neox":
            p = f"gpt_neox.layers.{i}."
            for ours, theirs in (("input_layernorm", "input_layernorm"),
                                 ("post_attention_layernorm",
                                  "post_attention_layernorm")):
                sd[p + theirs + ".weight"] = l[ours]["scale"]
                sd[p + theirs + ".bias"] = l[ours]["bias"]
            qkv = l["query_key_value"]
            q, k, v = np.split(qkv["kernel"], [H * Dh, 2 * H * Dh], axis=-1)
            qb, kb, vb = np.split(qkv["bias"], [H * Dh, 2 * H * Dh], axis=-1)
            w = fuse_interleaved(unperm(q, H, rd), unperm(k, H, rd), v)
            b = fuse_interleaved(unperm(qb, H, rd), unperm(kb, H, rd), vb)
            sd[p + "attention.query_key_value.weight"] = w.T
            sd[p + "attention.query_key_value.bias"] = b
            put_lin(p + "attention.dense", l["dense"])
            put_lin(p + "mlp.dense_h_to_4h", l["fc1"])
            put_lin(p + "mlp.dense_4h_to_h", l["fc2"])
        elif fam == "falcon":
            p = f"transformer.h.{i}."
            sd[p + "input_layernorm.weight"] = l["input_layernorm"]["scale"]
            sd[p + "input_layernorm.bias"] = l["input_layernorm"]["bias"]
            qkv = l["query_key_value"]

            def falcon_wire(a):
                # mirror the loader: multi_query (KV==1) is block concat,
                # KV==H is per-head interleaved (transformers' _split_heads)
                q, k, v = np.split(a, [H * Dh, (H + KV) * Dh], axis=-1)
                q, k = unperm(q, H, rd), unperm(k, KV, rd)
                if KV == H:
                    return _fuse_qkv_interleaved(q, k, v, H, Dh)
                return np.concatenate([q, k, v], axis=-1)

            sd[p + "self_attention.query_key_value.weight"] = \
                falcon_wire(qkv["kernel"]).T
            if "bias" in qkv:
                sd[p + "self_attention.query_key_value.bias"] = \
                    falcon_wire(qkv["bias"])
            put_lin(p + "self_attention.dense", l["dense"])
            put_lin(p + "mlp.dense_h_to_4h", l["fc1"])
            put_lin(p + "mlp.dense_4h_to_h", l["fc2"])
        elif fam == "gptj":
            p = f"transformer.h.{i}."
            sd[p + "ln_1.weight"] = l["input_layernorm"]["scale"]
            sd[p + "ln_1.bias"] = l["input_layernorm"]["bias"]
            for ours, theirs in (("q_proj", "attn.q_proj"),
                                 ("k_proj", "attn.k_proj"),
                                 ("v_proj", "attn.v_proj"),
                                 ("dense", "attn.out_proj"),
                                 ("fc1", "mlp.fc_in"), ("fc2", "mlp.fc_out")):
                put_lin(p + theirs, l[ours])     # native rotary: no unperm
        else:  # phi
            p = f"model.layers.{i}."
            sd[p + "input_layernorm.weight"] = l["input_layernorm"]["scale"]
            sd[p + "input_layernorm.bias"] = l["input_layernorm"]["bias"]
            for ours, theirs, heads in (("q_proj", "self_attn.q_proj", H),
                                        ("k_proj", "self_attn.k_proj", KV),
                                        ("v_proj", "self_attn.v_proj", None),
                                        ("dense", "self_attn.dense", None),
                                        ("fc1", "mlp.fc1", None),
                                        ("fc2", "mlp.fc2", None)):
                leaf = dict(l[ours])
                if heads is not None:
                    leaf = {k2: unperm(v2, heads, rd)
                            for k2, v2 in leaf.items()}
                put_lin(p + theirs, leaf)

    embed = params["embed_tokens"]
    head = embed if cfg.tie_lm_head else params["lm_head"]
    fl = params["final_layernorm"]
    if fam == "gpt_neox":
        sd["gpt_neox.embed_in.weight"] = embed
        sd["gpt_neox.final_layer_norm.weight"] = fl["scale"]
        sd["gpt_neox.final_layer_norm.bias"] = fl["bias"]
        sd["embed_out.weight"] = head
        hf = {"model_type": "gpt_neox", "architectures": ["GPTNeoXForCausalLM"],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "intermediate_size": cfg.intermediate_size,
              "num_hidden_layers": cfg.num_hidden_layers,
              "num_attention_heads": cfg.num_attention_heads,
              "max_position_embeddings": cfg.max_position_embeddings,
              "layer_norm_eps": cfg.layer_norm_eps,
              "rotary_pct": cfg.rotary_pct,
              "rotary_emb_base": cfg.rope_theta,
              "use_parallel_residual": True,
              "hidden_act": "gelu" if cfg.gelu_exact else "gelu_new",
              "tie_word_embeddings": False}
    elif fam == "falcon":
        sd["transformer.word_embeddings.weight"] = embed
        sd["transformer.ln_f.weight"] = fl["scale"]
        sd["transformer.ln_f.bias"] = fl["bias"]
        if not cfg.tie_lm_head:
            sd["lm_head.weight"] = head
        hf = {"model_type": "falcon", "architectures": ["FalconForCausalLM"],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "ffn_hidden_size": cfg.intermediate_size,
              "num_hidden_layers": cfg.num_hidden_layers,
              "num_attention_heads": cfg.num_attention_heads,
              "num_kv_heads": cfg.num_key_value_heads,
              "multi_query": cfg.num_key_value_heads == 1,
              "parallel_attn": True, "bias": cfg.use_bias, "alibi": False,
              "new_decoder_architecture": False,
              "rope_theta": cfg.rope_theta,
              "layer_norm_epsilon": cfg.layer_norm_eps,
              "max_position_embeddings": cfg.max_position_embeddings,
              "tie_word_embeddings": bool(cfg.tie_lm_head)}
    elif fam == "gptj":
        sd["transformer.wte.weight"] = embed
        sd["transformer.ln_f.weight"] = fl["scale"]
        sd["transformer.ln_f.bias"] = fl["bias"]
        sd["lm_head.weight"] = head
        if "lm_head_bias" in params:
            sd["lm_head.bias"] = params["lm_head_bias"]
        hf = {"model_type": "gptj", "architectures": ["GPTJForCausalLM"],
              "vocab_size": cfg.vocab_size, "n_embd": cfg.hidden_size,
              "n_inner": cfg.intermediate_size,
              "n_layer": cfg.num_hidden_layers, "n_head": cfg.num_attention_heads,
              "n_positions": cfg.max_position_embeddings,
              "rotary_dim": cfg.rotary_dim,
              "layer_norm_epsilon": cfg.layer_norm_eps,
              "activation_function": "gelu_new",
              "tie_word_embeddings": False}
    else:  # phi
        sd["model.embed_tokens.weight"] = embed
        sd["model.final_layernorm.weight"] = fl["scale"]
        sd["model.final_layernorm.bias"] = fl["bias"]
        sd["lm_head.weight"] = head
        if "lm_head_bias" in params:
            sd["lm_head.bias"] = params["lm_head_bias"]
        hf = {"model_type": "phi", "architectures": ["PhiForCausalLM"],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "intermediate_size": cfg.intermediate_size,
              "num_hidden_layers": cfg.num_hidden_layers,
              "num_attention_heads": cfg.num_attention_heads,
              "num_key_value_heads": cfg.num_key_value_heads,
              "max_position_embeddings": cfg.max_position_embeddings,
              "layer_norm_eps": cfg.layer_norm_eps,
              "rope_theta": cfg.rope_theta,
              "partial_rotary_factor": cfg.rotary_pct,
              "hidden_act": "gelu" if cfg.gelu_exact else "gelu_new",
              "tie_word_embeddings": False}
    return sd, hf


# ---------------------------------------------------------------------------
# bloom
# ---------------------------------------------------------------------------

def bloom_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    """HF BLOOM -> models/bloom.py tree. The fused QKV is stored per-head
    interleaved ([H, 3, Dh] on the out axis); converted to our q|k|v concat."""
    H, Dh = cfg.num_attention_heads, cfg.head_dim
    L = cfg.num_hidden_layers
    sd = {k.removeprefix("transformer."): v for k, v in sd.items()}

    def g(name):
        return sd[name].astype(dtype)

    def qkv(p):
        w = g(p + "query_key_value.weight").T           # [D, 3D] interleaved
        b = g(p + "query_key_value.bias")               # [3D]
        qw, kw, vw = _falcon_split_qkv(w, H, H, Dh, interleaved=True)
        qb, kb, vb = _falcon_split_qkv(b, H, H, Dh, interleaved=True)
        return {"kernel": np.concatenate([qw, kw, vw], axis=-1),
                "bias": np.concatenate([qb, kb, vb], axis=-1)}

    def lin(name):
        return {"kernel": g(name + ".weight").T, "bias": g(name + ".bias")}

    def ln(name):
        return {"scale": g(name + ".weight"), "bias": g(name + ".bias")}

    def layer(i):
        p = f"h.{i}."
        return {
            "input_layernorm": ln(p + "input_layernorm"),
            "post_attention_layernorm": ln(p + "post_attention_layernorm"),
            "query_key_value": qkv(p + "self_attention."),
            "dense": lin(p + "self_attention.dense"),
            "dense_h_to_4h": lin(p + "mlp.dense_h_to_4h"),
            "dense_4h_to_h": lin(p + "mlp.dense_4h_to_h"),
        }

    tree = {"word_embeddings": g("word_embeddings.weight"),
            "word_embeddings_layernorm": ln("word_embeddings_layernorm"),
            "ln_f": ln("ln_f")}
    layers = [layer(i) for i in range(L)]
    if scan_layers:
        import jax
        tree["h"] = {"block": jax.tree.map(lambda *xs: _stack(xs), *layers)}
    else:
        for i, l in enumerate(layers):
            tree[f"h_{i}"] = l
    return tree


def bloom_from_flax(params, cfg, dtype=np.float32):
    import jax
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)
    H, Dh = cfg.num_attention_heads, cfg.head_dim

    def interleave_qkv(kernel, bias):
        """our q|k|v concat (out axis) -> HF per-head [H, 3, Dh] layout."""
        def to_hf(a):
            q, k, v = np.split(a, 3, axis=-1)
            return _fuse_qkv_interleaved(q, k, v, H, Dh)
        return to_hf(kernel), to_hf(bias)

    sd = {"word_embeddings.weight": params["word_embeddings"],
          "word_embeddings_layernorm.weight":
              params["word_embeddings_layernorm"]["scale"],
          "word_embeddings_layernorm.bias":
              params["word_embeddings_layernorm"]["bias"],
          "ln_f.weight": params["ln_f"]["scale"],
          "ln_f.bias": params["ln_f"]["bias"]}
    for i in range(cfg.num_hidden_layers):
        l = (jax.tree.map(lambda x: x[i], params["h"]["block"])
             if "h" in params else params[f"h_{i}"])
        p = f"h.{i}."
        for lname in ("input_layernorm", "post_attention_layernorm"):
            sd[p + lname + ".weight"] = l[lname]["scale"]
            sd[p + lname + ".bias"] = l[lname]["bias"]
        kw, kb = interleave_qkv(l["query_key_value"]["kernel"],
                                l["query_key_value"]["bias"])
        sd[p + "self_attention.query_key_value.weight"] = kw.T
        sd[p + "self_attention.query_key_value.bias"] = kb
        sd[p + "self_attention.dense.weight"] = l["dense"]["kernel"].T
        sd[p + "self_attention.dense.bias"] = l["dense"]["bias"]
        sd[p + "mlp.dense_h_to_4h.weight"] = l["dense_h_to_4h"]["kernel"].T
        sd[p + "mlp.dense_h_to_4h.bias"] = l["dense_h_to_4h"]["bias"]
        sd[p + "mlp.dense_4h_to_h.weight"] = l["dense_4h_to_h"]["kernel"].T
        sd[p + "mlp.dense_4h_to_h.bias"] = l["dense_4h_to_h"]["bias"]
    sd = {"transformer." + k: v for k, v in sd.items()}
    sd["lm_head.weight"] = params["word_embeddings"]  # tied
    return sd


# ---------------------------------------------------------------------------
# top-level API
# ---------------------------------------------------------------------------

def bert_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    """HF ``BertForMaskedLM`` state dict -> models/bert.py tree. torch Linear
    weights are [out, in] and transpose to flax [in, out]; the decoder stays
    tied to the word embeddings (cls.predictions.decoder.weight is the same
    tensor in HF, so only the bias is read)."""
    L = cfg.num_hidden_layers

    def g(name):
        return sd[name].astype(dtype)

    def lin(name):
        return {"kernel": g(name + ".weight").T, "bias": g(name + ".bias")}

    def ln(name):
        return {"scale": g(name + ".weight"), "bias": g(name + ".bias")}

    def layer(i):
        p = f"bert.encoder.layer.{i}."
        return {
            "query": lin(p + "attention.self.query"),
            "key": lin(p + "attention.self.key"),
            "value": lin(p + "attention.self.value"),
            "attn_out": lin(p + "attention.output.dense"),
            "attn_ln": ln(p + "attention.output.LayerNorm"),
            "intermediate": lin(p + "intermediate.dense"),
            "output": lin(p + "output.dense"),
            "out_ln": ln(p + "output.LayerNorm"),
        }

    bert = {
        "word_embeddings": g("bert.embeddings.word_embeddings.weight"),
        "position_embeddings": g("bert.embeddings.position_embeddings.weight"),
        "embeddings_ln": ln("bert.embeddings.LayerNorm"),
    }
    if cfg.type_vocab_size:
        bert["token_type_embeddings"] = g(
            "bert.embeddings.token_type_embeddings.weight")
    layers = [layer(i) for i in range(L)]
    if scan_layers:
        import jax
        bert["layers"] = {"block": jax.tree.map(lambda *xs: _stack(xs), *layers)}
    else:
        for i, l in enumerate(layers):
            bert[f"layers_{i}"] = l
    bias_key = "cls.predictions.bias" if "cls.predictions.bias" in sd \
        else "cls.predictions.decoder.bias"
    return {
        "bert": bert,
        "transform": lin("cls.predictions.transform.dense"),
        "transform_ln": ln("cls.predictions.transform.LayerNorm"),
        "decoder_bias": g(bias_key),
    }


def bert_from_flax(params, cfg, dtype=np.float32):
    """models/bert.py tree -> HF ``BertForMaskedLM`` state dict (decoder tied:
    cls.predictions.decoder.weight is emitted as the embedding matrix)."""
    import jax
    params = jax.tree.map(lambda x: np.asarray(x, dtype=dtype), params)
    bert = params["bert"]
    L = cfg.num_hidden_layers
    sd = {
        "bert.embeddings.word_embeddings.weight": bert["word_embeddings"],
        "bert.embeddings.position_embeddings.weight": bert["position_embeddings"],
        "bert.embeddings.token_type_embeddings.weight": bert["token_type_embeddings"],
        "bert.embeddings.LayerNorm.weight": bert["embeddings_ln"]["scale"],
        "bert.embeddings.LayerNorm.bias": bert["embeddings_ln"]["bias"],
        "cls.predictions.transform.dense.weight": params["transform"]["kernel"].T,
        "cls.predictions.transform.dense.bias": params["transform"]["bias"],
        "cls.predictions.transform.LayerNorm.weight": params["transform_ln"]["scale"],
        "cls.predictions.transform.LayerNorm.bias": params["transform_ln"]["bias"],
        "cls.predictions.bias": params["decoder_bias"],
        "cls.predictions.decoder.weight": bert["word_embeddings"],
        "cls.predictions.decoder.bias": params["decoder_bias"],
    }
    hf_of = {"query": "attention.self.query", "key": "attention.self.key",
             "value": "attention.self.value", "attn_out": "attention.output.dense",
             "intermediate": "intermediate.dense", "output": "output.dense"}
    ln_of = {"attn_ln": "attention.output.LayerNorm", "out_ln": "output.LayerNorm"}
    for i in range(L):
        l = (jax.tree.map(lambda x: x[i], bert["layers"]["block"])
             if "layers" in bert else bert[f"layers_{i}"])
        p = f"bert.encoder.layer.{i}."
        for ours, theirs in hf_of.items():
            sd[p + theirs + ".weight"] = l[ours]["kernel"].T
            sd[p + theirs + ".bias"] = l[ours]["bias"]
        for ours, theirs in ln_of.items():
            sd[p + theirs + ".weight"] = l[ours]["scale"]
            sd[p + theirs + ".bias"] = l[ours]["bias"]
    return sd


def roberta_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    """HF ``RobertaForMaskedLM`` -> models/bert.py tree (same architecture:
    renamed modules, lm_head instead of cls.predictions, position offset 2).
    reference encoder coverage: ``module_inject/replace_policy.py`` lists
    bert/roberta in one policy family."""
    renamed = {}
    for k, v in sd.items():
        k2 = k.replace("roberta.", "bert.")
        k2 = k2.replace("lm_head.dense.", "cls.predictions.transform.dense.")
        k2 = k2.replace("lm_head.layer_norm.",
                        "cls.predictions.transform.LayerNorm.")
        k2 = k2.replace("lm_head.decoder.", "cls.predictions.decoder.")
        if k2 == "lm_head.bias":
            k2 = "cls.predictions.bias"
        renamed[k2] = v
    return bert_to_flax(renamed, cfg, scan_layers=scan_layers, dtype=dtype)


def distilbert_to_flax(sd, cfg, scan_layers=True, dtype=np.float32):
    """HF ``DistilBertForMaskedLM`` -> models/bert.py tree (BERT without
    token types; q_lin/k_lin/v_lin/out_lin + ffn naming; vocab_* MLM head).
    reference ``module_inject/containers/distil_bert.py`` coverage."""
    renamed = {}
    layer_map = {
        "attention.q_lin.": "attention.self.query.",
        "attention.k_lin.": "attention.self.key.",
        "attention.v_lin.": "attention.self.value.",
        "attention.out_lin.": "attention.output.dense.",
        "sa_layer_norm.": "attention.output.LayerNorm.",
        "ffn.lin1.": "intermediate.dense.",
        "ffn.lin2.": "output.dense.",
        "output_layer_norm.": "output.LayerNorm.",
    }
    for k, v in sd.items():
        k2 = k.replace("distilbert.transformer.layer.", "bert.encoder.layer.")
        k2 = k2.replace("distilbert.embeddings.", "bert.embeddings.")
        for old, new in layer_map.items():
            k2 = k2.replace(old, new)
        k2 = k2.replace("vocab_transform.", "cls.predictions.transform.dense.")
        k2 = k2.replace("vocab_layer_norm.",
                        "cls.predictions.transform.LayerNorm.")
        if k2 == "vocab_projector.bias":
            k2 = "cls.predictions.bias"
        k2 = k2.replace("vocab_projector.", "cls.predictions.decoder.")
        renamed[k2] = v
    return bert_to_flax(renamed, cfg, scan_layers=scan_layers, dtype=dtype)


def bert_config_from_hf(hf_cfg, **overrides):
    from deepspeed_tpu.models.bert import BertConfig
    kw = dict(vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
              num_hidden_layers=hf_cfg.num_hidden_layers,
              num_attention_heads=hf_cfg.num_attention_heads,
              intermediate_size=hf_cfg.intermediate_size,
              max_position_embeddings=hf_cfg.max_position_embeddings,
              type_vocab_size=hf_cfg.type_vocab_size,
              layer_norm_eps=hf_cfg.layer_norm_eps)
    kw.update(overrides)
    return BertConfig(**kw)


def load_pretrained(model_dir, dtype=np.float32, scan_layers=True):
    """Load an HF checkpoint directory -> (model, flax params).

    The model family is detected from ``config.json``; returns one of the
    in-tree flax models configured to match, with weights converted."""
    # remote-code families (no transformers config class registered): read
    # config.json directly — AutoConfig would demand trust_remote_code
    raw_mt = detect_model_type(model_dir)
    if raw_mt in ("qwen", "internlm"):
        from deepspeed_tpu.models.llama import LlamaForCausalLM
        with open(os.path.join(model_dir, "config.json")) as f:
            raw = json.load(f)
        sd = load_state_dict(model_dir)
        if raw_mt == "qwen":
            cfg = qwen_config_from_json(raw, scan_layers=scan_layers)
            return (LlamaForCausalLM(cfg),
                    qwen_to_flax(sd, cfg, scan_layers=scan_layers, dtype=dtype))
        # internlm (v1): llama naming with bias=True on q/k/v/o (reference
        # container: deepspeed/module_inject/containers/internlm.py)
        bias = bool(raw.get("bias", True))
        from deepspeed_tpu.models.llama import LlamaConfig
        cfg = LlamaConfig(
            vocab_size=raw["vocab_size"], hidden_size=raw["hidden_size"],
            intermediate_size=raw["intermediate_size"],
            num_hidden_layers=raw["num_hidden_layers"],
            num_attention_heads=raw["num_attention_heads"],
            num_key_value_heads=raw.get("num_key_value_heads",
                                        raw["num_attention_heads"]),
            max_position_embeddings=raw.get("max_position_embeddings", 2048),
            rms_norm_eps=raw.get("rms_norm_eps", 1e-6),
            rope_theta=raw.get("rope_theta", 10000.0),
            head_dim=raw.get("head_dim", None),  # export_pretrained writes
            # this for nonstandard head dims; reload must honor it
            attention_bias=bias, attention_out_bias=bias,
            scan_layers=scan_layers)
        return (LlamaForCausalLM(cfg),
                llama_to_flax(sd, cfg, scan_layers=scan_layers, dtype=dtype))
    import transformers
    hf_cfg = transformers.AutoConfig.from_pretrained(model_dir)
    sd = load_state_dict(model_dir)
    mt = hf_cfg.model_type
    if mt in LLAMA_FAMILY:
        from deepspeed_tpu.models.llama import LlamaForCausalLM
        cfg = llama_config_from_hf(hf_cfg, scan_layers=scan_layers)
        return (LlamaForCausalLM(cfg),
                llama_to_flax(sd, cfg, scan_layers=scan_layers, dtype=dtype))
    if mt == "gpt2":
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        cfg = GPT2Config(vocab_size=hf_cfg.vocab_size, n_positions=hf_cfg.n_positions,
                         n_embd=hf_cfg.n_embd, n_layer=hf_cfg.n_layer,
                         n_head=hf_cfg.n_head,
                         layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
                         scan_layers=scan_layers)
        return GPT2LMHeadModel(cfg), gpt2_to_flax(sd, cfg, scan_layers=scan_layers,
                                                  dtype=dtype)
    if mt == "bert":
        from deepspeed_tpu.models.bert import BertForMaskedLM
        act = getattr(hf_cfg, "hidden_act", "gelu")
        if act != "gelu":
            raise UnsupportedModelError(
                f"BERT hidden_act={act!r} not supported — models/bert.py "
                "hardcodes exact gelu (the bert-base/large lineage)")
        pet = getattr(hf_cfg, "position_embedding_type", "absolute")
        if pet != "absolute":
            raise UnsupportedModelError(
                f"BERT position_embedding_type={pet!r} not supported — only "
                "learned absolute positions are represented")
        if not getattr(hf_cfg, "tie_word_embeddings", True):
            raise UnsupportedModelError(
                "BERT tie_word_embeddings=False not supported — the MLM "
                "decoder is tied to the word embeddings")
        if getattr(hf_cfg, "is_decoder", False):
            raise UnsupportedModelError(
                "is_decoder=True (BertLMHeadModel causal lineage) not "
                "supported — models/bert.py is a bidirectional encoder")
        cfg = bert_config_from_hf(hf_cfg, scan_layers=scan_layers)
        return (BertForMaskedLM(cfg),
                bert_to_flax(sd, cfg, scan_layers=scan_layers, dtype=dtype))
    if mt == "roberta":
        from deepspeed_tpu.models.bert import BertForMaskedLM
        act = getattr(hf_cfg, "hidden_act", "gelu")
        if act != "gelu":
            raise UnsupportedModelError(f"RoBERTa hidden_act={act!r} "
                                        "not supported (exact gelu only)")
        if getattr(hf_cfg, "position_embedding_type", "absolute") != "absolute":
            raise UnsupportedModelError(
                "RoBERTa relative position embeddings not supported")
        if not getattr(hf_cfg, "tie_word_embeddings", True):
            raise UnsupportedModelError(
                "RoBERTa tie_word_embeddings=False not supported — the MLM "
                "decoder is tied to the word embeddings")
        if getattr(hf_cfg, "is_decoder", False):
            raise UnsupportedModelError(
                "is_decoder=True causal RoBERTa not supported")
        offset = (getattr(hf_cfg, "pad_token_id", 1) or 1) + 1
        cfg = bert_config_from_hf(
            hf_cfg, scan_layers=scan_layers, position_offset=offset,
            # HF stores max_position_embeddings INCLUDING the offset rows
            max_position_embeddings=hf_cfg.max_position_embeddings - offset)
        return (BertForMaskedLM(cfg),
                roberta_to_flax(sd, cfg, scan_layers=scan_layers, dtype=dtype))
    if mt == "distilbert":
        from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
        act = getattr(hf_cfg, "activation", "gelu")
        if act != "gelu":
            raise UnsupportedModelError(f"DistilBERT activation={act!r} "
                                        "not supported (exact gelu only)")
        if not getattr(hf_cfg, "tie_word_embeddings", True):
            raise UnsupportedModelError(
                "DistilBERT tie_word_embeddings=False not supported")
        if getattr(hf_cfg, "sinusoidal_pos_embds", False):
            raise UnsupportedModelError(
                "DistilBERT sinusoidal_pos_embds not supported (learned "
                "positions only)")
        cfg = BertConfig(vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.dim,
                         num_hidden_layers=hf_cfg.n_layers,
                         num_attention_heads=hf_cfg.n_heads,
                         intermediate_size=hf_cfg.hidden_dim,
                         max_position_embeddings=hf_cfg.max_position_embeddings,
                         type_vocab_size=0, layer_norm_eps=1e-12,
                         scan_layers=scan_layers)
        return (BertForMaskedLM(cfg),
                distilbert_to_flax(sd, cfg, scan_layers=scan_layers,
                                   dtype=dtype))
    if mt == "opt":
        from deepspeed_tpu.models.opt import OPTConfig, OPTForCausalLM
        if not getattr(hf_cfg, "do_layer_norm_before", True):
            raise UnsupportedModelError(
                "OPT do_layer_norm_before=False (opt-350m post-LN lineage) "
                "not supported — the pre-LN model cannot represent it")
        if getattr(hf_cfg, "word_embed_proj_dim",
                   hf_cfg.hidden_size) != hf_cfg.hidden_size:
            raise UnsupportedModelError(
                "OPT word_embed_proj_dim != hidden_size (project_in/out "
                "lineage, e.g. opt-350m) not supported")
        cfg = OPTConfig(vocab_size=hf_cfg.vocab_size,
                        hidden_size=hf_cfg.hidden_size,
                        ffn_dim=hf_cfg.ffn_dim,
                        num_hidden_layers=hf_cfg.num_hidden_layers,
                        num_attention_heads=hf_cfg.num_attention_heads,
                        max_position_embeddings=hf_cfg.max_position_embeddings,
                        scan_layers=scan_layers)
        return OPTForCausalLM(cfg), opt_to_flax(sd, cfg, scan_layers=scan_layers,
                                                dtype=dtype)
    if mt == "mixtral":
        from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
        cfg = MixtralConfig(vocab_size=hf_cfg.vocab_size,
                            hidden_size=hf_cfg.hidden_size,
                            intermediate_size=hf_cfg.intermediate_size,
                            num_hidden_layers=hf_cfg.num_hidden_layers,
                            num_attention_heads=hf_cfg.num_attention_heads,
                            num_key_value_heads=hf_cfg.num_key_value_heads,
                            num_local_experts=hf_cfg.num_local_experts,
                            num_experts_per_tok=hf_cfg.num_experts_per_tok,
                            max_position_embeddings=hf_cfg.max_position_embeddings,
                            rms_norm_eps=hf_cfg.rms_norm_eps,
                            rope_theta=getattr(hf_cfg, "rope_theta", 1e6))
        return MixtralForCausalLM(cfg), mixtral_to_flax(sd, cfg, dtype=dtype)
    if mt == "falcon":
        from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                         ParallelBlockForCausalLM)
        if getattr(hf_cfg, "new_decoder_architecture", False):
            raise UnsupportedModelError(
                "falcon new_decoder_architecture (40b/180b grouped-qkv layout) "
                "not supported yet; 7b-lineage (multi_query) is")
        if getattr(hf_cfg, "alibi", False):
            raise UnsupportedModelError("falcon alibi variant not supported")
        if not getattr(hf_cfg, "parallel_attn", True):
            raise UnsupportedModelError(
                "falcon parallel_attn=False (sequential-residual falcon-rw "
                "lineage) not supported — the parallel-block model cannot "
                "represent it")
        kv = 1 if getattr(hf_cfg, "multi_query", True) else hf_cfg.num_attention_heads
        cfg = ParallelBlockConfig(
            vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
            intermediate_size=getattr(hf_cfg, "ffn_hidden_size",
                                      4 * hf_cfg.hidden_size),
            num_hidden_layers=hf_cfg.num_hidden_layers,
            num_attention_heads=hf_cfg.num_attention_heads,
            num_key_value_heads=kv,
            max_position_embeddings=getattr(hf_cfg, "max_position_embeddings", 2048),
            layer_norm_eps=hf_cfg.layer_norm_epsilon,
            rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
            use_bias=bool(getattr(hf_cfg, "bias", False)),
            fused_qkv=True,
            tie_lm_head=bool(getattr(hf_cfg, "tie_word_embeddings", False)))
        return (ParallelBlockForCausalLM(cfg),
                falcon_to_flax(sd, cfg, dtype=dtype))
    if mt == "phi":
        from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                         ParallelBlockForCausalLM)
        kv = getattr(hf_cfg, "num_key_value_heads", None) or hf_cfg.num_attention_heads
        cfg = ParallelBlockConfig(
            vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
            intermediate_size=hf_cfg.intermediate_size,
            num_hidden_layers=hf_cfg.num_hidden_layers,
            num_attention_heads=hf_cfg.num_attention_heads,
            num_key_value_heads=kv,
            max_position_embeddings=hf_cfg.max_position_embeddings,
            layer_norm_eps=hf_cfg.layer_norm_eps,
            rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
            rotary_pct=getattr(hf_cfg, "partial_rotary_factor", 1.0),
            use_bias=True, fused_qkv=False,
            # phi hidden_act is gelu_new (tanh); exact only if configured so
            gelu_exact=getattr(hf_cfg, "hidden_act", "gelu_new")
            not in ("gelu_new", "gelu_pytorch_tanh"),
            lm_head_bias="lm_head.bias" in sd)
        return ParallelBlockForCausalLM(cfg), phi_to_flax(sd, cfg, dtype=dtype)
    if mt == "bloom":
        from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
        if getattr(hf_cfg, "apply_residual_connection_post_layernorm", False):
            raise UnsupportedModelError(
                "bloom apply_residual_connection_post_layernorm=True not "
                "supported — the pre-LN-residual model cannot represent it")
        cfg = BloomConfig(vocab_size=hf_cfg.vocab_size,
                          hidden_size=hf_cfg.hidden_size,
                          num_hidden_layers=hf_cfg.n_layer,
                          num_attention_heads=hf_cfg.n_head,
                          layer_norm_epsilon=hf_cfg.layer_norm_epsilon,
                          scan_layers=scan_layers)
        return BloomForCausalLM(cfg), bloom_to_flax(sd, cfg,
                                                    scan_layers=scan_layers,
                                                    dtype=dtype)
    if mt == "gpt_neox":
        from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                         ParallelBlockForCausalLM)
        if not getattr(hf_cfg, "use_parallel_residual", True):
            raise UnsupportedModelError(
                "gpt_neox use_parallel_residual=False (pythia-70m-v0 lineage) "
                "not supported — the parallel-block model cannot represent it")
        cfg = ParallelBlockConfig(
            vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
            intermediate_size=hf_cfg.intermediate_size,
            num_hidden_layers=hf_cfg.num_hidden_layers,
            num_attention_heads=hf_cfg.num_attention_heads,
            num_key_value_heads=hf_cfg.num_attention_heads,
            max_position_embeddings=hf_cfg.max_position_embeddings,
            layer_norm_eps=hf_cfg.layer_norm_eps,
            rope_theta=getattr(hf_cfg, "rotary_emb_base", 10000.0),
            rotary_pct=getattr(hf_cfg, "rotary_pct", 0.25),
            use_bias=True, fused_qkv=True, dual_layernorm=True,
            gelu_exact=getattr(hf_cfg, "hidden_act", "gelu") == "gelu",
            tie_lm_head=bool(getattr(hf_cfg, "tie_word_embeddings", False)))
        return (ParallelBlockForCausalLM(cfg),
                gptneox_to_flax(sd, cfg, dtype=dtype))
    if mt == "gptj":
        from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                         ParallelBlockForCausalLM)
        cfg = ParallelBlockConfig(
            vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.n_embd,
            intermediate_size=getattr(hf_cfg, "n_inner", None) or
            4 * hf_cfg.n_embd,
            num_hidden_layers=hf_cfg.n_layer,
            num_attention_heads=hf_cfg.n_head,
            num_key_value_heads=hf_cfg.n_head,
            max_position_embeddings=hf_cfg.n_positions,
            layer_norm_eps=hf_cfg.layer_norm_epsilon,
            rotary_pct=hf_cfg.rotary_dim / (hf_cfg.n_embd // hf_cfg.n_head),
            use_bias=True, qkv_bias=False, dense_bias=False,
            fused_qkv=False, gelu_exact=False,
            lm_head_bias="lm_head.bias" in sd)
        return ParallelBlockForCausalLM(cfg), gptj_to_flax(sd, cfg, dtype=dtype)
    raise UnsupportedModelError(
        f"unsupported model_type {mt!r}; supported: {SUPPORTED}")


def export_pretrained(params, cfg, save_dir, dtype=np.float32):
    """Inverse of :func:`load_pretrained`: write ``model.safetensors`` +
    ``config.json`` that ``transformers.from_pretrained`` can load."""
    from deepspeed_tpu.models.llama import LlamaConfig

    name = type(cfg).__name__
    if isinstance(cfg, LlamaConfig):
        sd = llama_from_flax(params, cfg, dtype=dtype)
        # pick the faithful HF family: sliding_window => mistral (global
        # attention would silently diverge past the window), qkv-bias => qwen2
        if cfg.sliding_window:
            mt, arch = "mistral", "MistralForCausalLM"
        elif cfg.attention_out_bias:
            # q/k/v/o all biased => InternLM lineage (remote-code family)
            mt, arch = "internlm", "InternLMForCausalLM"
        elif cfg.attention_bias:
            mt, arch = "qwen2", "Qwen2ForCausalLM"
        else:
            mt, arch = "llama", "LlamaForCausalLM"
        hf = {"model_type": mt, "architectures": [arch],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "intermediate_size": cfg.intermediate_size,
              "num_hidden_layers": cfg.num_hidden_layers,
              "num_attention_heads": cfg.num_attention_heads,
              "num_key_value_heads": cfg.num_key_value_heads,
              "max_position_embeddings": cfg.max_position_embeddings,
              "rms_norm_eps": cfg.rms_norm_eps, "rope_theta": cfg.rope_theta,
              "tie_word_embeddings": False,
              "torch_dtype": {np.dtype(np.float16): "float16",
                              np.dtype(np.float32): "float32"}.get(
                                  np.dtype(dtype), "bfloat16")}
        if cfg.sliding_window:
            hf["sliding_window"] = int(cfg.sliding_window)
        if mt == "internlm":
            hf["bias"] = True
        elif mt != "qwen2":
            hf["attention_bias"] = cfg.attention_bias
        if cfg.head_dim != cfg.hidden_size // cfg.num_attention_heads:
            hf["head_dim"] = int(cfg.head_dim)
    elif name == "GPT2Config":
        sd = gpt2_from_flax(params, cfg, dtype=dtype)
        hf = {"model_type": "gpt2", "architectures": ["GPT2LMHeadModel"],
              "vocab_size": cfg.vocab_size, "n_positions": cfg.n_positions,
              "n_embd": cfg.n_embd, "n_layer": cfg.n_layer, "n_head": cfg.n_head,
              "layer_norm_epsilon": cfg.layer_norm_epsilon}
    elif name == "BertConfig":
        if cfg.position_offset or not cfg.type_vocab_size:
            raise UnsupportedModelError(
                "HF export is implemented for the plain BERT naming only; "
                "RoBERTa/DistilBERT-loaded trees (position_offset or no "
                "token types) are load-only")
        sd = bert_from_flax(params, cfg, dtype=dtype)
        hf = {"model_type": "bert", "architectures": ["BertForMaskedLM"],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "num_hidden_layers": cfg.num_hidden_layers,
              "num_attention_heads": cfg.num_attention_heads,
              "intermediate_size": cfg.intermediate_size,
              "max_position_embeddings": cfg.max_position_embeddings,
              "type_vocab_size": cfg.type_vocab_size,
              "layer_norm_eps": cfg.layer_norm_eps,
              "hidden_act": "gelu", "position_embedding_type": "absolute"}
    elif name == "OPTConfig":
        sd = opt_from_flax(params, cfg, dtype=dtype)
        hf = {"model_type": "opt", "architectures": ["OPTForCausalLM"],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "ffn_dim": cfg.ffn_dim, "num_hidden_layers": cfg.num_hidden_layers,
              "num_attention_heads": cfg.num_attention_heads,
              "max_position_embeddings": cfg.max_position_embeddings,
              "do_layer_norm_before": True, "word_embed_proj_dim": cfg.hidden_size}
    elif name == "MixtralConfig":
        sd = mixtral_from_flax(params, cfg, dtype=dtype)
        hf = {"model_type": "mixtral", "architectures": ["MixtralForCausalLM"],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "intermediate_size": cfg.intermediate_size,
              "num_hidden_layers": cfg.num_hidden_layers,
              "num_attention_heads": cfg.num_attention_heads,
              "num_key_value_heads": cfg.num_key_value_heads,
              "num_local_experts": cfg.num_local_experts,
              "num_experts_per_tok": cfg.num_experts_per_tok,
              "max_position_embeddings": cfg.max_position_embeddings,
              "tie_word_embeddings": False}
    elif name == "BloomConfig":
        sd = bloom_from_flax(params, cfg, dtype=dtype)
        hf = {"model_type": "bloom", "architectures": ["BloomForCausalLM"],
              "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
              "n_layer": cfg.num_hidden_layers,
              "n_head": cfg.num_attention_heads,
              "layer_norm_epsilon": cfg.layer_norm_epsilon,
              "tie_word_embeddings": True}
    elif name == "ParallelBlockConfig":
        sd, hf = parallel_block_from_flax(params, cfg, dtype=dtype)
    else:
        raise UnsupportedModelError(f"unsupported model config {name}")

    os.makedirs(save_dir, exist_ok=True)
    path = save_safetensors(sd, save_dir)
    with open(os.path.join(save_dir, "config.json"), "w") as f:
        json.dump(hf, f, indent=2)
    logger.info(f"exported HF checkpoint to {save_dir} "
                f"({sum(v.size for v in sd.values())/1e6:.1f}M params)")
    return path
