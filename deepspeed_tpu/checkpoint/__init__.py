"""Universal checkpointing (reference ``deepspeed/checkpoint/``)."""

from deepspeed_tpu.checkpoint.universal import (
    ds_to_universal, get_fp32_state_dict_from_zero_checkpoint,
    load_universal_checkpoint, save_universal_checkpoint)
from deepspeed_tpu.checkpoint.ds_interop import (
    DeepSpeedCheckpoint, ds_checkpoint_to_universal,
    get_fp32_state_dict_from_ds_checkpoint, load_deepspeed_checkpoint,
    read_deepspeed_checkpoint)

__all__ = ["ds_to_universal", "get_fp32_state_dict_from_zero_checkpoint",
           "load_universal_checkpoint", "save_universal_checkpoint",
           "ds_checkpoint_to_universal",
           "get_fp32_state_dict_from_ds_checkpoint",
           "load_deepspeed_checkpoint", "read_deepspeed_checkpoint",
           "DeepSpeedCheckpoint"]
