"""Universal checkpointing (reference ``deepspeed/checkpoint/``)."""

from deepspeed_tpu.checkpoint.universal import (
    ds_to_universal, get_fp32_state_dict_from_zero_checkpoint,
    latest_universal_tag, load_universal_checkpoint, read_universal_meta,
    save_universal_checkpoint, topology_remap)
from deepspeed_tpu.checkpoint.ds_interop import (
    DeepSpeedCheckpoint, ds_checkpoint_to_universal,
    get_fp32_state_dict_from_ds_checkpoint, load_deepspeed_checkpoint,
    read_deepspeed_checkpoint)

__all__ = ["ds_to_universal", "get_fp32_state_dict_from_zero_checkpoint",
           "latest_universal_tag", "load_universal_checkpoint",
           "read_universal_meta", "save_universal_checkpoint",
           "topology_remap",
           "ds_checkpoint_to_universal",
           "get_fp32_state_dict_from_ds_checkpoint",
           "load_deepspeed_checkpoint", "read_deepspeed_checkpoint",
           "DeepSpeedCheckpoint"]
