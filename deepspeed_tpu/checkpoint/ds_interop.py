"""Reference-format DeepSpeed checkpoint ingestion.

Reads the reference's eager on-disk checkpoint layout (written by
``deepspeed/runtime/engine.py save_checkpoint``; consumed by
``deepspeed/utils/zero_to_fp32.py`` and ``checkpoint/ds_to_universal.py:88,171``):

    <dir>/latest                                   — text file naming the tag
    <dir>/<tag>/mp_rank_00_model_states.pt          — module state + param_shapes
    <dir>/<tag>/zero_pp_rank_{dp}_mp_rank_{tp:02d}_optim_states.pt
        (also with bf16_/fp16_ prefixes)            — per-rank flat fp32
        partitions + base optimizer state

so an existing DeepSpeed training run can migrate its *optimizer state* (not
just HF-exported weights) onto this framework: the ZeRO shards are merged back
into full fp32 tensors per parameter and re-emitted in the universal fragment
format (``checkpoint/universal.py``), which loads at any mesh topology.

Reconstruction rules (capability match of ``zero_to_fp32.py``):
  stage 1/2 — each param group's fp32 master is a flat vector partitioned
    contiguously across the DP ranks (2*world-aligned padding at the tail);
    merging is rank-order concat, then per-parameter slicing in the
    ``param_shapes`` group order. Adam moments partition identically.
  stage 3  — every parameter is individually padded to a multiple of the
    world size and round-robin sliced: rank r holds elements
    [r*ceil(n/w), (r+1)*ceil(n/w)) of each param's flat buffer; per-rank flat
    groups concatenate those slices in param order.

Only torch (CPU) is needed to deserialize the .pt files; everything else is
numpy. torch is imported lazily so the module stays importable without it.
"""

import dataclasses
import glob
import math
import os
import re
from typing import Any, Callable, Dict, List, Optional

import numpy as np

LATEST_FILE = "latest"
MODEL_FILE_GLOB = "*mp_rank_*_model_states.pt"
OPTIM_FILE_GLOB = "*_optim_states.pt"

# keys of the reference's saved dicts (checkpoint/constants.py)
_OPT_SD = "optimizer_state_dict"
_SINGLE_PARTITION = "single_partition_of_fp32_groups"   # stage 1/2
_FLAT_GROUPS = "fp32_flat_groups"                        # stage 3
_BASE_OPT = "base_optimizer_state"
_ZERO_STAGE = "zero_stage"
_PARTITION_COUNT = "partition_count"
_PARAM_SHAPES = "param_shapes"
_MODULE = "module"
_BUFFER_NAMES = "buffer_names"
_SHARED_PARAMS = "shared_params"


@dataclasses.dataclass
class DsCheckpoint:
    """A parsed reference checkpoint: full (merged) fp32 tensors by name."""
    zero_stage: int
    world_size: int
    tag: str
    fp32: Dict[str, np.ndarray]
    exp_avg: Dict[str, np.ndarray]
    exp_avg_sq: Dict[str, np.ndarray]
    buffers: Dict[str, np.ndarray]
    step: int
    shared_params: List[Any]


def resolve_tag(ckpt_dir: str, tag: Optional[str] = None) -> str:
    """Tag from the ``latest`` file (reference load_checkpoint default)."""
    if tag is not None:
        return tag
    latest = os.path.join(ckpt_dir, LATEST_FILE)
    if not os.path.isfile(latest):
        raise FileNotFoundError(
            f"no tag given and no '{LATEST_FILE}' file in {ckpt_dir}")
    with open(latest) as f:
        return f.read().strip()


def _natural(path):
    return [int(t) if t.isdigit() else t
            for t in re.split(r"(\d+)", os.path.basename(path))]


def _load_pt(path):
    import torch
    return torch.load(path, map_location="cpu", weights_only=False)


def _to_np(t):
    import torch
    if isinstance(t, torch.Tensor):
        return t.detach().to(torch.float32).cpu().numpy()
    return t


def read_deepspeed_checkpoint(ckpt_dir: str, tag: Optional[str] = None
                              ) -> DsCheckpoint:
    """Parse and merge a reference checkpoint directory into full fp32
    tensors (weights + Adam moments) keyed by the module parameter names."""
    tag = resolve_tag(ckpt_dir, tag)
    d = os.path.join(ckpt_dir, tag)
    model_files = sorted(glob.glob(os.path.join(d, MODEL_FILE_GLOB)),
                         key=_natural)
    optim_files = sorted(glob.glob(os.path.join(d, OPTIM_FILE_GLOB)),
                         key=_natural)
    if not model_files:
        raise FileNotFoundError(f"no *_model_states.pt under {d}")
    if not optim_files:
        raise FileNotFoundError(f"no *_optim_states.pt under {d}")

    mstate = _load_pt(model_files[0])
    param_shapes = mstate[_PARAM_SHAPES]
    if isinstance(param_shapes, dict):  # some versions save a single dict
        param_shapes = [param_shapes]
    buffer_names = set(mstate.get(_BUFFER_NAMES, []) or [])
    buffers = {k: _to_np(v) for k, v in mstate.get(_MODULE, {}).items()
               if k in buffer_names}
    shared = list(mstate.get(_SHARED_PARAMS, []) or [])

    opt_sds = [_load_pt(f)[_OPT_SD] for f in optim_files]
    zero_stage = int(opt_sds[0].get(_ZERO_STAGE, 1))
    world = opt_sds[0].get(_PARTITION_COUNT, len(opt_sds))
    if isinstance(world, (list, tuple)):
        world = max(int(w) for w in world)
    world = int(world)
    if len(opt_sds) != world:
        raise ValueError(f"expected {world} optim shard files, found "
                         f"{len(opt_sds)} under {d}")

    def flat_per_rank(key_fn):
        """[rank][group] -> flat np vector (stage3: groups pre-concatenated)."""
        out = []
        for sd in opt_sds:
            groups = key_fn(sd)
            if zero_stage == 3:
                groups = [np.concatenate([_to_np(g).reshape(-1)
                                          for g in groups])]
            out.append([_to_np(g).reshape(-1) for g in groups])
        return out

    if zero_stage <= 2:
        fp32_parts = flat_per_rank(lambda sd: sd[_SINGLE_PARTITION])
    else:
        fp32_parts = flat_per_rank(lambda sd: sd[_FLAT_GROUPS])

    base = opt_sds[0].get(_BASE_OPT, {}) or {}
    state_groups = base.get("state", {})
    step = 0
    for g in (state_groups.values() if isinstance(state_groups, dict)
              else state_groups):
        s = g.get("step", 0)
        try:
            step = max(step, int(_to_np(s)))
        except (TypeError, ValueError):
            pass

    def moment_parts(moment_key):
        ok = all(_BASE_OPT in sd and sd[_BASE_OPT].get("state")
                 for sd in opt_sds)
        if not ok:
            return None
        try:
            return flat_per_rank(lambda sd: [
                sd[_BASE_OPT]["state"][g][moment_key]
                for g in sorted(sd[_BASE_OPT]["state"])])
        except KeyError:
            return None

    m_parts = moment_parts("exp_avg")
    v_parts = moment_parts("exp_avg_sq")

    if zero_stage <= 2:
        merge = _merge_stage2
    else:
        merge = _merge_stage3
    fp32 = merge(fp32_parts, param_shapes, world)
    exp_avg = merge(m_parts, param_shapes, world) if m_parts else {}
    exp_avg_sq = merge(v_parts, param_shapes, world) if v_parts else {}

    return DsCheckpoint(zero_stage=zero_stage, world_size=world, tag=tag,
                        fp32=fp32, exp_avg=exp_avg, exp_avg_sq=exp_avg_sq,
                        buffers=buffers, step=step, shared_params=shared)


def _shape_numel(shape):
    return int(np.prod([int(s) for s in tuple(shape)])) if len(tuple(shape)) \
        else 1


def _merge_stage2(parts, param_shapes, world):
    """Concat each group's rank partitions; slice params in group order.
    The tail may carry up to 2*world alignment padding (reference zero2
    NCCL alignment) — tolerated, never consumed."""
    out = {}
    n_groups = len(parts[0])
    for g in range(n_groups):
        merged = np.concatenate([parts[r][g] for r in range(world)])
        offset = 0
        shapes = param_shapes[g] if g < len(param_shapes) else {}
        for name, shape in shapes.items():
            n = _shape_numel(shape)
            if offset + n > merged.size:
                raise ValueError(
                    f"group {g} exhausted at '{name}': need {n} elements at "
                    f"offset {offset}, have {merged.size}")
            out[name] = merged[offset:offset + n].reshape(tuple(shape))
            offset += n
        align = 2 * world
        if math.ceil(offset / align) * align < merged.size and shapes:
            raise ValueError(
                f"group {g}: {merged.size - offset} leftover elements exceed "
                f"the 2*world alignment padding — shapes do not match shards")
    return out


def _merge_stage3(parts, param_shapes, world):
    """Zip per-param slices: rank r holds [r*ceil(n/w), (r+1)*ceil(n/w)) of
    each (padded) param, concatenated in param order."""
    shapes = {}
    for group in param_shapes:
        shapes.update(group)
    out = {}
    offsets = [0] * world
    for name, shape in shapes.items():
        n = _shape_numel(shape)
        per = math.ceil(n / world)
        frags = []
        for r in range(world):
            frag = parts[r][0][offsets[r]:offsets[r] + per]
            if frag.size < per:
                raise ValueError(
                    f"rank {r} flat group exhausted at '{name}'")
            frags.append(frag)
            offsets[r] += per
        out[name] = np.concatenate(frags)[:n].reshape(tuple(shape))
    return out


def _default_name_map(name: str) -> str:
    """torch dotted name -> jax keystr: 'layers.0.kernel' ->
    "['layers']['0']['kernel']". No layout changes (transposition/fusion is
    model-specific — see checkpoint/hf.py for the HF weight conventions)."""
    return "".join(f"['{p}']" for p in name.split("."))


def ds_checkpoint_to_universal(ckpt_dir: str, out_dir: str,
                               tag: Optional[str] = None,
                               name_map: Optional[Callable[[str], str]] = None
                               ) -> str:
    """Convert a reference checkpoint directory into this framework's
    universal fragment format (offline; no engine or devices needed) — the
    cross-framework analog of reference ``ds_to_universal.py`` main."""
    return universal_from_parsed(read_deepspeed_checkpoint(ckpt_dir, tag),
                                 out_dir, name_map=name_map)


def universal_from_parsed(ck: DsCheckpoint, out_dir: str,
                          name_map: Optional[Callable[[str], str]] = None
                          ) -> str:
    """Write-out half of the conversion, reusing an already-parsed
    checkpoint (no second disk parse/merge)."""
    import json
    from deepspeed_tpu.checkpoint.universal import (UNIVERSAL_ARRAYS,
                                                    UNIVERSAL_META)
    nm = name_map or _default_name_map
    blobs, keys = {}, []
    for name, arr in ck.fp32.items():
        k = nm(name)
        keys.append(k)
        blobs[f"{k}::fp32"] = np.asarray(arr, np.float32)
        if name in ck.exp_avg:
            blobs[f"{k}::exp_avg"] = np.asarray(ck.exp_avg[name], np.float32)
        if name in ck.exp_avg_sq:
            blobs[f"{k}::exp_avg_sq"] = np.asarray(ck.exp_avg_sq[name],
                                                   np.float32)
    os.makedirs(out_dir, exist_ok=True)
    np.savez(os.path.join(out_dir, UNIVERSAL_ARRAYS), **blobs)
    meta = {
        "counters": {"global_steps": ck.step, "global_samples": 0,
                     "micro_steps": ck.step},
        "param_keys": sorted(keys),
        "optimizer_step": ck.step,
        "format": "deepspeed_tpu_universal_v1",
        "source": {"layout": "deepspeed_reference", "tag": ck.tag,
                   "zero_stage": ck.zero_stage,
                   "world_size": ck.world_size},
    }
    with open(os.path.join(out_dir, UNIVERSAL_META), "w") as f:
        json.dump(meta, f)
    return out_dir


def load_deepspeed_checkpoint(engine, ckpt_dir: str, tag: Optional[str] = None,
                              name_map: Optional[Callable[[str], str]] = None,
                              load_optimizer_states: bool = True) -> int:
    """Load a reference-format checkpoint directly into a live engine at its
    current topology (convert-in-memory + universal load)."""
    import tempfile
    from deepspeed_tpu.checkpoint.universal import load_universal_checkpoint
    with tempfile.TemporaryDirectory() as tmp:
        ds_checkpoint_to_universal(ckpt_dir, tmp, tag=tag, name_map=name_map)
        return load_universal_checkpoint(
            engine, tmp, load_optimizer_states=load_optimizer_states)


def consolidate_fp32(ck: DsCheckpoint) -> Dict[str, np.ndarray]:
    """Full fp32 state dict from an already-parsed checkpoint: buffers +
    merged weights, shared parameters recovered by aliasing."""
    out = dict(ck.buffers)
    out.update(ck.fp32)
    for pair in ck.shared_params:
        if len(pair) == 2 and pair[1] in out:
            out[pair[0]] = out[pair[1]]
    return out


def get_fp32_state_dict_from_ds_checkpoint(ckpt_dir: str,
                                           tag: Optional[str] = None
                                           ) -> Dict[str, np.ndarray]:
    """zero_to_fp32-style consolidation of reference shards: full fp32
    weights by module parameter name (reference ``utils/zero_to_fp32.py:604``
    ``get_fp32_state_dict_from_zero_checkpoint``)."""
    return consolidate_fp32(read_deepspeed_checkpoint(ckpt_dir, tag))


class DeepSpeedCheckpoint:
    """Inspection wrapper over a parsed reference checkpoint (the TPU-native
    subset of reference ``checkpoint/deepspeed_checkpoint.py:33`` — iteration,
    degrees, merged states; the Megatron layer_*-file 3D maps don't apply to
    the mesh-sharded runtime, conversion goes through
    :func:`ds_checkpoint_to_universal` instead of file surgery)."""

    def __init__(self, ckpt_dir, tag=None):
        self.dir = ckpt_dir
        self._ck = read_deepspeed_checkpoint(ckpt_dir, tag)
        self.tag = self._ck.tag

    @property
    def zero_stage(self):
        return self._ck.zero_stage

    @property
    def dp_degree(self):
        return self._ck.world_size

    def get_iteration(self):
        return self._ck.step

    def parameter_names(self):
        return sorted(self._ck.fp32)

    def get_fp32_state_dict(self):
        """Merged full-precision weights (zero_to_fp32 semantics)."""
        return consolidate_fp32(self._ck)

    def get_optimizer_state(self, name):
        """{exp_avg, exp_avg_sq} for one parameter (merged across shards)."""
        out = {}
        if name in self._ck.exp_avg:
            out["exp_avg"] = self._ck.exp_avg[name]
        if name in self._ck.exp_avg_sq:
            out["exp_avg_sq"] = self._ck.exp_avg_sq[name]
        return out

    def to_universal(self, out_dir, name_map=None):
        return universal_from_parsed(self._ck, out_dir, name_map=name_map)
