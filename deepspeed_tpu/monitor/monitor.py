"""Monitoring fan-out (mirrors reference ``deepspeed/monitor/monitor.py:13,29``).

``MonitorMaster`` fans events out to TensorBoard / W&B / CSV writers; engine
writes (name, value, global_sample) event tuples, same schema as the reference
(``engine.py:2273``).
"""

import csv
import os

from deepspeed_tpu.utils.logging import logger


class Monitor:

    def __init__(self, config):
        self.config = config
        self.enabled = getattr(config, "enabled", False)

    def write_events(self, event_list):
        raise NotImplementedError


class CsvMonitor(Monitor):
    """reference ``monitor/csv_monitor.py``: one csv per event name."""

    def __init__(self, config):
        super().__init__(config)
        self.output_path = config.output_path or "csv_monitor_output"
        self.job_name = config.job_name
        self._files = {}

    def _path(self, name):
        d = os.path.join(self.output_path, self.job_name)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name.replace("/", "_") + ".csv")

    def write_events(self, event_list):
        for name, value, step in event_list:
            p = self._path(name)
            new = not os.path.exists(p)
            with open(p, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class TensorBoardMonitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        self.writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
                path = os.path.join(config.output_path or "tensorboard_output", config.job_name)
                self.writer = SummaryWriter(log_dir=path)
            except Exception as e:
                logger.warning(f"tensorboard unavailable ({e}); disabling TB monitor")
                self.enabled = False

    def write_events(self, event_list):
        if self.writer is None:
            return
        for name, value, step in event_list:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, config):
        super().__init__(config)
        self.run = None
        if self.enabled:
            try:
                import wandb
                self.run = wandb.init(project=config.project, group=config.group)
            except Exception as e:
                logger.warning(f"wandb unavailable ({e}); disabling wandb monitor")
                self.enabled = False

    def write_events(self, event_list):
        if self.run is None:
            return
        import wandb
        for name, value, step in event_list:
            wandb.log({name: value}, step=step)


class MonitorMaster(Monitor):
    """reference ``monitor/monitor.py:29``."""

    def __init__(self, ds_config):
        self.writers = []
        if ds_config.monitor_config_tb.enabled:
            self.writers.append(TensorBoardMonitor(ds_config.monitor_config_tb))
        if ds_config.monitor_config_csv.enabled:
            self.writers.append(CsvMonitor(ds_config.monitor_config_csv))
        if ds_config.monitor_config_wandb.enabled:
            self.writers.append(WandbMonitor(ds_config.monitor_config_wandb))
        self.enabled = any(w.enabled for w in self.writers)

    def write_events(self, event_list):
        for w in self.writers:
            if w.enabled:
                w.write_events(event_list)
