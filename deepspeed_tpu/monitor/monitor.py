"""Monitoring fan-out (mirrors reference ``deepspeed/monitor/monitor.py:13,29``).

``MonitorMaster`` fans events out to TensorBoard / W&B / CSV writers; engine
writes (name, value, global_sample) event tuples, same schema as the reference
(``engine.py:2273``).
"""

import csv
import os

from deepspeed_tpu.utils.logging import logger


class Monitor:

    def __init__(self, config):
        self.config = config
        self.enabled = getattr(config, "enabled", False)

    def write_events(self, event_list):
        raise NotImplementedError


class CsvMonitor(Monitor):
    """reference ``monitor/csv_monitor.py``: one csv per event name."""

    def __init__(self, config):
        super().__init__(config)
        self.output_path = config.output_path or "csv_monitor_output"
        self.job_name = config.job_name
        self._files = {}

    def _path(self, name):
        d = os.path.join(self.output_path, self.job_name)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, name.replace("/", "_") + ".csv")

    def write_events(self, event_list):
        for name, value, step in event_list:
            p = self._path(name)
            new = not os.path.exists(p)
            with open(p, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, value])


class TensorBoardMonitor(Monitor):
    """Optional-dependency writer: torch (for SummaryWriter) may be absent on
    a TPU host. A missing or broken import disables the writer cleanly at
    construction — enabling TB in the config without torch installed must
    degrade to a one-line warning, never an ImportError mid-training."""

    def __init__(self, config):
        super().__init__(config)
        self.writer = None
        if self.enabled:
            try:
                from torch.utils.tensorboard import SummaryWriter
            except Exception as e:  # ImportError or a broken torch install
                logger.warning(f"tensorboard unavailable ({e}); disabling TB monitor")
                self.enabled = False
                return
            try:
                path = os.path.join(config.output_path or "tensorboard_output", config.job_name)
                self.writer = SummaryWriter(log_dir=path)
            except Exception as e:
                logger.warning(f"tensorboard writer failed ({e}); disabling TB monitor")
                self.enabled = False

    def write_events(self, event_list):
        if self.writer is None:
            return
        for name, value, step in event_list:
            self.writer.add_scalar(name, value, step)
        self.writer.flush()


class WandbMonitor(Monitor):
    """Optional-dependency writer (same guard contract as TB): keeps the
    imported module handle so ``write_events`` never re-imports."""

    def __init__(self, config):
        super().__init__(config)
        self.run = None
        self._wandb = None
        if self.enabled:
            try:
                import wandb
            except Exception as e:
                logger.warning(f"wandb unavailable ({e}); disabling wandb monitor")
                self.enabled = False
                return
            try:
                self.run = wandb.init(project=config.project, group=config.group)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb init failed ({e}); disabling wandb monitor")
                self.enabled = False

    def write_events(self, event_list):
        if self.run is None or self._wandb is None:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class MonitorMaster(Monitor):
    """reference ``monitor/monitor.py:29`` — the fan-out hub. The engine's
    ``write_events`` lands here and is forwarded to every enabled backend;
    one backend failing (full disk, dead wandb session) disables that backend
    with a warning instead of killing the training loop."""

    def __init__(self, ds_config):
        self.writers = []
        if ds_config.monitor_config_tb.enabled:
            self.writers.append(TensorBoardMonitor(ds_config.monitor_config_tb))
        if ds_config.monitor_config_csv.enabled:
            self.writers.append(CsvMonitor(ds_config.monitor_config_csv))
        if ds_config.monitor_config_wandb.enabled:
            self.writers.append(WandbMonitor(ds_config.monitor_config_wandb))
        self.enabled = any(w.enabled for w in self.writers)

    def write_events(self, event_list):
        for w in self.writers:
            if not w.enabled:
                continue
            try:
                w.write_events(event_list)
            except Exception as e:
                logger.warning(f"{type(w).__name__}.write_events failed ({e}); "
                               f"disabling this backend")
                w.enabled = False
        self.enabled = any(w.enabled for w in self.writers)
