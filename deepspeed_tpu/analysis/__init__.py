"""Static analysis for the TPU stack — graftlint.

Layer A (``astlint``) is stdlib-only and safe to load standalone (the
``kernel_table``/``perf_gate`` pattern); Layer B (``jaxpr_checks``)
requires jax and runs in the ``lint`` pytest lane. Import submodules
directly — this package ``__init__`` must stay import-light so the
tier-1 CPU lane can reach Layer A without pulling in jax.
"""

__all__ = ["astlint", "jaxpr_checks"]
