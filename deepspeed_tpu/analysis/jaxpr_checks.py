"""graftlint Layer B — jaxpr-level checks for traced programs.

Layer A (``astlint``) sees the source; this module sees what jax actually
traced. The gap matters: an fp32 upcast hides inside a ``jnp.mean``, a
collective's axis binding depends on which shard_map wrapped the call, and
the overlap planner's claimed collective inventory is only honest if the
scheduled program traces the same ops the plan priced. These checks walk a
``ClosedJaxpr`` (recursing through pjit/shard_map/scan/cond sub-jaxprs) for:

* **JX001 upcast**: ``convert_element_type`` to float32 from bf16 in a
  bf16 program, excluding jnp's intentional accumulation upcasts (a
  convert consumed *only* by reduce primitives is how ``bf16.sum()``
  is supposed to look) and tiny scalars below ``min_elems``.
* **JX002 unbound collective**: a collective primitive whose axis names
  are not bound by any enclosing shard_map — it would fail at lowering
  on real meshes, or silently run on an implicit axis.
* **JX003 callback**: ``pure_callback``/``io_callback``/``debug_callback``
  inside a hot program — each one is a host round-trip per step.
* **plan drift** (``check_plan_drift``): the overlap plan's comm_ops
  inventory vs what the scheduled program actually traces, compared by
  the same prefetch/bucket/tail classes ``overlap_schedule._op_class``
  uses.

jax is REQUIRED here — this file runs in the ``lint`` pytest lane
(``pytest -m lint``), never in the tier-1 stdlib dry-run path. Callers
trace with ``jax.make_jaxpr`` (no compile, no execution), so the checks
are cheap enough for CI.
"""

import numpy as np

import deepspeed_tpu.utils.jax_compat  # noqa: F401 (installs jax.shard_map shim)
import jax

try:  # jax >= 0.4.30 moved the public IR types
    from jax.extend.core import ClosedJaxpr, Jaxpr  # type: ignore
except ImportError:  # pragma: no cover - older jax
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore

__all__ = [
    "iter_eqns", "check_upcasts", "check_collectives", "check_callbacks",
    "check_program", "check_moe_wire", "check_verify_prefill_parity",
    "collective_inventory", "check_plan_drift", "trace_jaxpr",
]

#: collective primitives and how they map onto the overlap plan's op names
_COLLECTIVE_PRIMS = {
    "all_gather": "all_gather",
    "psum": "all_reduce",
    "all_reduce": "all_reduce",
    "reduce_scatter": "reduce_scatter",
    "psum_scatter": "reduce_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
}
#: reduce-style consumers that legitimize an accumulation upcast
_REDUCE_PRIMS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "argmax", "argmin", "reduce_precision", "cumsum",
    "cumlogsumexp", "cummax", "cummin", "cumprod",
}
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}

#: plan-op -> schedule class, mirroring ``overlap_schedule._op_class`` —
#: kept in sync by test_jaxpr_checks (drift here would silently un-gate)
_PREFETCH_OPS = ("all_gather", "gather")
_BUCKET_OPS = ("reduce_scatter", "psum_scatter", "all_to_all", "exchange")
_MOE_DISPATCH_OPS = ("a2a_dispatch",)
_MOE_COMBINE_OPS = ("a2a_combine",)


def op_class(op):
    """prefetch | bucket | tail | moe_dispatch | moe_combine — the overlap
    schedule's cost classes."""
    name = str(op).lower()
    # moe classes first: "a2a_*" must not fall through to the generic
    # "all_to_all"/"exchange" bucket class
    if any(k in name for k in _MOE_DISPATCH_OPS):
        return "moe_dispatch"
    if any(k in name for k in _MOE_COMBINE_OPS):
        return "moe_combine"
    if any(k in name for k in _PREFETCH_OPS):
        return "prefetch"
    if any(k in name for k in _BUCKET_OPS):
        return "bucket"
    return "tail"


def trace_jaxpr(fn, *args, **kwargs):
    """``jax.make_jaxpr`` without executing or compiling ``fn``."""
    return jax.make_jaxpr(fn)(*args, **kwargs)


def _as_jaxpr(j):
    return j.jaxpr if isinstance(j, ClosedJaxpr) else j


def _sub_jaxprs(params):
    for v in params.values():
        if isinstance(v, (Jaxpr, ClosedJaxpr)):
            yield v
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, (Jaxpr, ClosedJaxpr)):
                    yield x


def _shard_map_axes(eqn):
    """Axis names a shard_map eqn binds for its body (manual axes only)."""
    mesh = eqn.params.get("mesh")
    names = set(getattr(mesh, "axis_names", ()) or ())
    auto = eqn.params.get("auto") or frozenset()
    return frozenset(n for n in names if n not in auto)


def iter_eqns(jaxpr, bound_axes=frozenset(), path=()):
    """Yield ``(eqn, bound_axes, path)`` over every equation, recursing
    into sub-jaxprs. ``bound_axes`` accumulates axis names bound by
    enclosing shard_map eqns; ``path`` is the tuple of enclosing primitive
    names (outermost first) for finding messages."""
    for eqn in _as_jaxpr(jaxpr).eqns:
        prim = eqn.primitive.name
        yield eqn, bound_axes, path
        inner_axes = bound_axes
        if prim == "shard_map":
            inner_axes = bound_axes | _shard_map_axes(eqn)
        for sub in _sub_jaxprs(eqn.params):
            for item in iter_eqns(sub, inner_axes, path + (prim,)):
                yield item


def _axis_names(eqn):
    """Axis names a collective eqn operates over, across jax's spellings."""
    raw = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(raw, (str, int)):
        raw = (raw,)
    return tuple(raw)


def _eqn_loc(eqn, path):
    where = " > ".join(path) if path else "top level"
    return f"{eqn.primitive.name} at {where}"


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def check_upcasts(closed, min_elems=4096):
    """JX001: bf16 -> f32 ``convert_element_type`` whose result feeds
    non-reduce math. A convert consumed ONLY by reduce primitives is jnp's
    intentional accumulation upcast (``bf16.sum()`` must accumulate in f32
    or lose mantissa); anything else re-widens activations/grads the
    program claimed were bf16 — 2x the HBM traffic the cost model priced.
    Scalars/small tensors under ``min_elems`` are noise, not bandwidth."""
    findings = []
    for eqn, _axes, path in iter_eqns(closed):
        if eqn.primitive.name != "convert_element_type":
            continue
        new = np.dtype(eqn.params.get("new_dtype"))
        src_aval = eqn.invars[0].aval
        src = np.dtype(src_aval.dtype)
        if not (src == np.dtype("bfloat16") and new == np.dtype("float32")):
            continue
        if int(np.prod(src_aval.shape or (1,))) < min_elems:
            continue
        out = eqn.outvars[0]
        # scan the eqn's own jaxpr level for consumers of the converted var
        consumers = []
        owner = closed
        for e2, _a, p2 in iter_eqns(closed):
            if p2 == path and any(v is out for v in e2.invars):
                consumers.append(e2.primitive.name)
        del owner
        if consumers and all(c in _REDUCE_PRIMS for c in consumers):
            continue  # accumulation upcast — the one we want
        findings.append({
            "check": "JX001", "severity": "error",
            "eqn": _eqn_loc(eqn, path),
            "message": (f"bf16->f32 upcast of shape {tuple(src_aval.shape)} "
                        f"feeds {sorted(set(consumers)) or ['program output']}"
                        f" — non-accumulation f32 math in a bf16 program"),
        })
    return findings


def check_collectives(closed, extra_bound=()):
    """JX002: collectives whose axis names no enclosing shard_map binds.
    ``extra_bound`` names axes the caller knows are bound outside the
    traced fragment (e.g. tracing a shard_map BODY directly)."""
    findings = []
    extra = frozenset(extra_bound)
    for eqn, bound, path in iter_eqns(closed):
        prim = eqn.primitive.name
        if prim not in _COLLECTIVE_PRIMS:
            continue
        missing = [a for a in _axis_names(eqn)
                   if a not in bound and a not in extra]
        if missing:
            findings.append({
                "check": "JX002", "severity": "error",
                "eqn": _eqn_loc(eqn, path),
                "message": (f"collective {prim} over axis {missing} with no "
                            f"enclosing shard_map binding it — lowering on "
                            f"a real mesh will fail or pick an implicit "
                            f"axis"),
            })
    return findings


def check_callbacks(closed, allow=()):
    """JX003: host callbacks traced into the program. Each one is a
    device->host->device round trip per execution — on the micro-step or
    decode step that is a synchronous stall the overlap schedule cannot
    hide. ``allow`` lists callback target names (``str(callback)``
    substrings) that are accepted (e.g. an intentional debug lane)."""
    findings = []
    for eqn, _axes, path in iter_eqns(closed):
        prim = eqn.primitive.name
        if prim not in _CALLBACK_PRIMS:
            continue
        target = str(eqn.params.get("callback", ""))
        if any(a and a in target for a in allow):
            continue
        findings.append({
            "check": "JX003", "severity": "error",
            "eqn": _eqn_loc(eqn, path),
            "message": (f"{prim} traced into the program ({target[:80]}) — "
                        f"a host round-trip every step; hoist it out of the "
                        f"hot path or move it to telemetry"),
        })
    return findings


def check_moe_wire(closed, wire_bits, inter_axis=None):
    """JX004: the MoE expert all-to-all's traced wire precision vs what the
    layer was CONFIGURED to send. With ``a2a_wire_bits`` set, the dispatch
    and combine payloads must cross the wire as byte-wide integers (the
    block-quantized q tensor); an fp32 payload means the quantization was
    configured but never reached the collective — 4x the DCN bytes the
    perf gate priced.

    Two findings: (a) ``wire_bits`` set but NO byte-wide all_to_all traced
    anywhere; (b) ``inter_axis`` given and the float elements crossing it
    outnumber the byte-wide elements (scales are a ~1/group_size sliver —
    float payload dominating means the data leg itself is fp)."""
    if not wire_bits:
        return []
    int_elems = 0
    inter_float_elems = 0
    inter_int_elems = 0
    for eqn, _axes, path in iter_eqns(closed):
        if eqn.primitive.name != "all_to_all":
            continue
        aval = eqn.invars[0].aval
        n = int(np.prod(aval.shape or (1,)))
        byte_wide = (np.dtype(aval.dtype).kind in "iu"
                     and np.dtype(aval.dtype).itemsize == 1)
        if byte_wide:
            int_elems += n
        if inter_axis is not None and inter_axis in _axis_names(eqn):
            if byte_wide:
                inter_int_elems += n
            elif np.dtype(aval.dtype).kind == "f":
                inter_float_elems += n
    findings = []
    if int_elems == 0:
        findings.append({
            "check": "JX004", "severity": "error",
            "eqn": "all_to_all (program-wide)",
            "message": (f"a2a_wire_bits={wire_bits} configured but no "
                        f"byte-wide all_to_all traced — the quantized wire "
                        f"never materialized; every leg is full precision"),
        })
    elif inter_axis is not None and inter_float_elems > max(inter_int_elems,
                                                            1):
        findings.append({
            "check": "JX004", "severity": "error",
            "eqn": f"all_to_all over {inter_axis!r}",
            "message": (f"float elements over {inter_axis!r} "
                        f"({inter_float_elems}) exceed the byte-wide payload "
                        f"({inter_int_elems}) — the fp data leg rides the "
                        f"axis int{wire_bits} was configured for"),
        })
    return findings


def check_program(closed, dtype="bfloat16", min_elems=4096,
                  extra_bound=(), allow_callbacks=()):
    """All three eqn checks over one program. ``dtype`` gates JX001 —
    upcast findings only make sense for bf16 programs."""
    findings = []
    if np.dtype(dtype) == np.dtype("bfloat16"):
        findings += check_upcasts(closed, min_elems=min_elems)
    findings += check_collectives(closed, extra_bound=extra_bound)
    findings += check_callbacks(closed, allow=allow_callbacks)
    return findings


def _scan_signatures(closed):
    """(printed body jaxpr, location) of every ``scan`` eqn in trace order."""
    sigs = []
    for eqn, _axes, path in iter_eqns(closed):
        if eqn.primitive.name == "scan":
            sigs.append((str(eqn.params.get("jaxpr", "")), _eqn_loc(eqn, path)))
    return sigs


def check_verify_prefill_parity(prefill_closed, verify_closed):
    """JX005: the speculative verify forward must lower through the SAME
    layer ``scan`` as plain ragged prefill. The draft-then-verify design
    only holds its bit-exactness oracle (and its cost model) if the verify
    chunk rides the ragged prefill kernels — a forked trunk or a
    dense-decode fallback would silently re-trace a different layer program
    whose logits can drift from the plain decode stream. Both programs
    close over the shared ``_ragged_trunk``, so their layer scans must
    print identically; any divergence is a fork.

    Pass the two ``jax.make_jaxpr`` traces (plain ``ragged_forward`` and
    ``ragged_forward_verify``) over the same pool/table shapes."""
    findings = []
    pre = _scan_signatures(prefill_closed)
    ver = _scan_signatures(verify_closed)
    if not pre:
        findings.append({
            "check": "JX005", "severity": "error",
            "eqn": "scan (prefill program)",
            "message": "plain prefill traced no layer scan — cannot "
                       "establish the kernel the verify forward must share",
        })
    if not ver:
        findings.append({
            "check": "JX005", "severity": "error",
            "eqn": "scan (verify program)",
            "message": "verify forward traced no layer scan — the draft "
                       "chunk is not running the scanned ragged prefill "
                       "kernels at all",
        })
    if findings:
        return findings
    if [s for s, _ in pre] != [s for s, _ in ver]:
        where = next((loc for (sp, _), (sv, loc) in zip(pre, ver)
                      if sp != sv), ver[0][1])
        findings.append({
            "check": "JX005", "severity": "error",
            "eqn": where,
            "message": (f"verify forward's layer scan diverges from plain "
                        f"prefill ({len(pre)} vs {len(ver)} scans) — the "
                        f"verify chunk is not lowering through the shared "
                        f"ragged prefill kernel (trunk fork or dense-decode "
                        f"fallback); bit-exact accept/reject is void"),
        })
    return findings


# ---------------------------------------------------------------------------
# overlap-plan drift
# ---------------------------------------------------------------------------

def collective_inventory(closed):
    """Traced collectives, counted by plan-op name and schedule class::

        {"ops": {"all_gather": 8, "reduce_scatter": 4},
         "classes": {"prefetch": 8, "bucket": 4}}
    """
    ops, classes = {}, {}
    for eqn, _axes, _path in iter_eqns(closed):
        name = _COLLECTIVE_PRIMS.get(eqn.primitive.name)
        if name is None:
            continue
        ops[name] = ops.get(name, 0) + 1
        c = op_class(name)
        classes[c] = classes.get(c, 0) + 1
    return {"ops": dict(sorted(ops.items())),
            "classes": dict(sorted(classes.items()))}


def merge_inventories(*invs):
    """Union several programs' inventories (the scheduled step is split
    across micro_step and apply_step — the plan prices the whole round)."""
    out = {"ops": {}, "classes": {}}
    for inv in invs:
        for k in ("ops", "classes"):
            for name, n in inv.get(k, {}).items():
                out[k][name] = out[k].get(name, 0) + n
    out["ops"] = dict(sorted(out["ops"].items()))
    out["classes"] = dict(sorted(out["classes"].items()))
    return out


def check_plan_drift(plan, inventory):
    """Does the overlap plan's priced collective inventory match what the
    scheduled program actually traces? Compared by schedule class
    (prefetch/bucket/tail), because that is the granularity the planner
    prices and the exposure model hides. ``plan`` is an
    ``OverlapPlan.to_dict()`` (or the ``comm_ops`` list itself);
    ``inventory`` comes from :func:`collective_inventory` /
    :func:`merge_inventories`.

    Returns ``{"ok", "planned_classes", "traced_classes",
    "missing_in_trace", "missing_in_plan"}`` — a class the plan prices
    that never traces means the plan claims overlap for comm that does
    not exist; a traced class the plan omits means unpriced comm the
    exposure model never saw."""
    comm_ops = plan.get("comm_ops", plan) if isinstance(plan, dict) else plan
    planned = {}
    for op in comm_ops:
        name = op["op"] if isinstance(op, dict) else str(op)
        c = op_class(name)
        planned[c] = planned.get(c, 0) + int(
            op.get("count", 1) if isinstance(op, dict) else 1)
    traced = dict(inventory.get("classes", {}))
    missing_in_trace = sorted(c for c in planned if c not in traced)
    missing_in_plan = sorted(c for c in traced if c not in planned)
    return {
        "ok": not missing_in_trace and not missing_in_plan,
        "planned_classes": dict(sorted(planned.items())),
        "traced_classes": dict(sorted(traced.items())),
        "missing_in_trace": missing_in_trace,
        "missing_in_plan": missing_in_plan,
    }
