"""graftlint Layer A — AST rule engine for TPU-stack trace hazards.

Every perf win this repo ships (host-sync-free stepping, overlap scheduling,
fleet handoff) is an *invariant about program structure* — no blocking
device->host transfer on the hot path, no retrace per step, no wall-clock
read inside traced code — and runtime guards only cover the handful of paths
a test happens to drive. This module checks the invariants on the whole tree
statically, the DeepCompile thesis (PAPERS.md) applied pre-silicon: the
distributed-training graph's defects are cheapest to catch before a chip
ever sees the program.

STDLIB-ONLY at module scope (the ``kernel_table``/``overlap`` pattern):
``scripts/graftlint.py`` and ``scripts/perf_gate.py --dry-run`` load this
file standalone via importlib so the tier-1 CPU lane lints the tree without
importing the package or jax. Layer B (jaxpr checks, jax required) lives in
``analysis/jaxpr_checks.py``.

Rule inventory (docs/ANALYSIS.md has the full table):

======  ========  =====================================================
id      severity  hazard
======  ========  =====================================================
GL000   error     malformed ``# graftlint:`` pragma (unknown rule / no
                  reason) — a pragma that cannot suppress must not look
                  like it does
GL001   error     ``.item()`` — blocking device->host transfer
GL002   error     ``float()/int()/bool()`` over a jax expression —
                  implicit blocking transfer (or a tracer error)
GL003   error     ``jax.device_get`` outside the accounted
                  ``_host_fetch`` path
GL004   warn      ``np.asarray(...)`` — host materialization; device
                  values silently sync, host values are fine but every
                  new site deserves a look
GL101   error     ``jax.jit``/``pjit`` called inside a loop body —
                  fresh callable per iteration, retrace every time
GL102   warn      step-shaped jit (``*step``/``update``) without
                  ``donate_argnums`` — params+opt state double-buffer
                  in HBM
GL103   error     ``time.time()/perf_counter()`` in a function
                  reachable from traced code — traces as a constant
                  (or breaks the trace)
GL104   warn      ``jax.jit`` on a lambda / locally-defined function —
                  the jit cache keys on callable identity; a fresh
                  callable per call recompiles every call (factories
                  that cache the result pragma this)
GL105   info      module defines an injectable clock alias
                  (``_now = time.*``) but still reads ``time.*``
                  directly elsewhere — pin-ability regression
GL201   info      write to a ``global`` outside any ``with *lock*:``
                  block — thread-shared module state raced
======  ========  =====================================================

Suppression: ``# graftlint: allow[GL003] reason text`` on the finding's
line, or on the ``def`` line of the enclosing function to allow the whole
function. The reason is mandatory — a bare allow is itself a GL000 finding
and suppresses nothing. ``.item()``/``device_get``/``asarray`` inside a
function named ``_host_fetch``/``host_fetch`` are exempt by construction:
that IS the accounted path the rules funnel everything toward.

The baseline ratchet (``check_baseline``) freezes today's per-rule,
per-file counts (``onchip_results/lint_baseline.json``); counts may only
go down. New findings anywhere — a new ``.item()`` in a guarded path, a
jit in a loop — fail the gate (exit 3 via the CLI) before any test runs.
"""

import ast
import json
import os
import re

__all__ = [
    "RULES", "lint_source", "lint_file", "lint_paths", "summarize",
    "make_baseline", "load_baseline", "check_baseline", "format_finding",
]

#: rule id -> (severity, one-line summary). Severity is advisory metadata —
#: the ratchet treats every rule the same (counts may only go down).
RULES = {
    "GL000": ("error", "malformed graftlint pragma"),
    "GL001": ("error", ".item() blocks on a device->host transfer"),
    "GL002": ("error", "float/int/bool() over a jax expression syncs (or "
                       "raises on a tracer)"),
    "GL003": ("error", "jax.device_get outside the accounted _host_fetch "
                       "path"),
    "GL004": ("warn", "np.asarray materializes on host (device values "
                      "silently sync)"),
    "GL101": ("error", "jit built inside a loop body retraces every "
                       "iteration"),
    "GL102": ("warn", "step-shaped jit without donate_argnums "
                      "double-buffers params in HBM"),
    "GL103": ("error", "wall-clock read reachable from traced code traces "
                       "as a constant"),
    "GL104": ("warn", "jit on a fresh lambda/local def recompiles per "
                      "call unless the callee is cached"),
    "GL105": ("info", "raw time.* call bypasses the module's injectable "
                      "clock alias"),
    "GL201": ("info", "global write outside a lock block races "
                      "thread-shared module state"),
}

#: canonical callables whose call forces a host sync
_DEVICE_GET = {"jax.device_get"}
_ASARRAY = {"numpy.asarray", "numpy.array", "jax.device_get"}
#: canonical jit entry points (GL101/GL102/GL104)
_JIT_FNS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
#: callables that trace their function argument (GL103 roots)
_TRACING_FNS = _JIT_FNS | {
    "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.checkpoint", "jax.remat", "jax.grad", "jax.value_and_grad",
    "jax.vmap", "jax.pmap", "jax.lax.scan", "jax.lax.while_loop",
    "jax.lax.cond", "jax.lax.fori_loop", "jax.make_jaxpr", "jax.eval_shape",
}
#: wall-clock reads that become trace-time constants (GL103/GL105)
_CLOCK_FNS = {"time.time", "time.perf_counter", "time.monotonic",
              "time.time_ns", "time.perf_counter_ns", "time.monotonic_ns"}
#: functions whose body IS the accounted host fetch — GL001/GL003/GL004
#: are definitionally exempt inside them
_ACCOUNTED_FNS = {"_host_fetch", "host_fetch"}
#: function-name shapes that hold a full TrainState/params tree — missing
#: donation doubles the resident bytes (GL102)
_STEP_NAME = re.compile(r"(^|_)(micro_step|train_step|apply_step|step|"
                        r"update)(_fn)?$")

_PRAGMA = re.compile(r"#\s*graftlint:\s*allow\[([A-Za-z0-9_,\s]+)\]\s*(.*)$")
#: a comment that starts like a pragma but fails to parse — the tight
#: "#<ws>graftlint:<ws>allow" prefix keeps prose/regex mentions out
_PRAGMA_ATTEMPT = re.compile(r"#\s*graftlint:\s*allow")
_SKIP_DIRS = {"__pycache__", ".git", "build", "node_modules", ".venv"}


def _finding(rule, path, node, message):
    sev, _ = RULES[rule]
    return {"rule": rule, "severity": sev, "path": path,
            "line": getattr(node, "lineno", 0),
            "col": getattr(node, "col_offset", 0), "message": message}


def format_finding(f):
    return (f"{f['path']}:{f['line']}:{f['col'] + 1}: {f['rule']} "
            f"[{f['severity']}] {f['message']}")


# ---------------------------------------------------------------------------
# pragma parsing
# ---------------------------------------------------------------------------

def _parse_pragmas(src, path):
    """``{lineno: set(rule_ids)}`` for well-formed pragmas, plus GL000
    findings for malformed ones (unknown rule id or missing reason)."""
    allows, findings = {}, []
    for lineno, line in enumerate(src.splitlines(), start=1):
        m = _PRAGMA.search(line)
        if m is None:
            if _PRAGMA_ATTEMPT.search(line):
                node = ast.Constant(None)
                node.lineno, node.col_offset = lineno, 0
                findings.append(_finding(
                    "GL000", path, node,
                    "unparseable graftlint pragma (expected "
                    "'graftlint: allow[RULE] reason' in a comment)"))
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2).strip()
        node = ast.Constant(None)
        node.lineno, node.col_offset = lineno, 0
        bad = sorted(r for r in rules if r not in RULES)
        if bad:
            findings.append(_finding(
                "GL000", path, node,
                f"pragma names unknown rule(s) {', '.join(bad)}"))
            rules -= set(bad)
        if not reason:
            findings.append(_finding(
                "GL000", path, node,
                "pragma has no reason — 'allow[RULE] why it is safe' is "
                "required; a bare allow suppresses nothing"))
            continue  # an unjustified pragma must not suppress
        if rules:
            allows[lineno] = allows.get(lineno, set()) | rules
    return allows, findings


# ---------------------------------------------------------------------------
# name resolution (import-alias aware)
# ---------------------------------------------------------------------------

class _Aliases:
    """Maps local names to canonical dotted paths through import aliases:
    ``import numpy as np`` -> np = numpy; ``from jax import device_get`` ->
    device_get = jax.device_get. ``jax.numpy`` folds onto ``numpy``-style
    roots only where rules care (asarray)."""

    def __init__(self):
        self.map = {}

    def add_import(self, node):
        for a in node.names:
            local = a.asname or a.name.split(".")[0]
            self.map[local] = a.name if a.asname else a.name.split(".")[0]

    def add_import_from(self, node):
        if node.level or not node.module:
            return  # relative imports never alias jax/numpy/time
        for a in node.names:
            if a.name == "*":
                continue
            self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node):
        """Canonical dotted name for a Name/Attribute chain, or None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.map.get(node.id, node.id)
        parts.append(root)
        name = ".".join(reversed(parts))
        # fold jax.numpy onto numpy for the asarray-style rules
        if name.startswith("jax.numpy."):
            name = "jnp." + name[len("jax.numpy."):]
        return name


def _contains_jax_expr(node, aliases):
    """True when the expression subtree references jnp./jax. values — the
    float()/int()/bool() wrapper then forces a transfer (GL002)."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Attribute, ast.Name)):
            name = aliases.resolve(sub)
            if name and (name.startswith("jnp.") or name.startswith("jax.")
                         or name == "jnp" or name == "jax"):
                return True
    return False


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class _FunctionInfo:
    """Per-function facts for the module-local reachability pass (GL103)."""

    __slots__ = ("node", "name", "traced_root", "calls", "clock_calls")

    def __init__(self, node):
        self.node = node
        self.name = node.name
        self.traced_root = False   # jit-decorated / passed to a tracer
        self.calls = set()         # simple callee names within the module
        self.clock_calls = []      # (node, canonical clock name)


class _Linter(ast.NodeVisitor):

    def __init__(self, path, src, select=None):
        self.path = path
        self.select = select
        self.aliases = _Aliases()
        self.findings = []
        self.allow_lines, pragma_findings = _parse_pragmas(src, path)
        self._pragma_findings = pragma_findings
        self.func_stack = []       # enclosing FunctionDef nodes
        self.loop_depth = 0        # For/While nesting inside current func
        self.lock_depth = 0        # with-<lock>: nesting
        self.global_names = set()  # names declared global in current func
        self.functions = {}        # name -> _FunctionInfo (last def wins)
        self._fn_info = []         # stack parallel to func_stack
        self.clock_aliases = []    # (alias_name, assign_node) at module scope

    # -- emission -----------------------------------------------------------
    def emit(self, rule, node, message):
        if self.select is not None and rule not in self.select:
            return
        lines = {getattr(node, "lineno", 0)}
        for fn in self.func_stack:  # def-line pragma covers the function
            lines.add(fn.lineno)
        for ln in lines:
            if rule in self.allow_lines.get(ln, ()):
                return
        self.findings.append(_finding(rule, self.path, node, message))

    def _in_accounted_fn(self):
        return any(fn.name in _ACCOUNTED_FNS for fn in self.func_stack)

    # -- imports ------------------------------------------------------------
    def visit_Import(self, node):
        self.aliases.add_import(node)

    def visit_ImportFrom(self, node):
        self.aliases.add_import_from(node)

    # -- module-scope clock aliases (GL105) ---------------------------------
    def visit_Assign(self, node):
        if not self.func_stack:
            val = self.aliases.resolve(node.value) \
                if isinstance(node.value, (ast.Attribute, ast.Name)) else None
            if val in _CLOCK_FNS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.clock_aliases.append((tgt.id, node))
        self._check_global_write(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_global_write(node, [node.target])
        self.generic_visit(node)

    def _check_global_write(self, node, targets):
        if not self.func_stack or self.lock_depth > 0:
            return
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id in self.global_names:
                self.emit("GL201", node,
                          f"write to module global '{tgt.id}' outside a "
                          f"lock block — concurrent steppers race it")

    def visit_Global(self, node):
        self.global_names.update(node.names)

    # -- scopes -------------------------------------------------------------
    def visit_With(self, node):
        lockish = any(
            "lock" in (self.aliases.resolve(item.context_expr.func
                       if isinstance(item.context_expr, ast.Call)
                       else item.context_expr) or
                       ast.dump(item.context_expr)).lower()
            for item in node.items)
        self.lock_depth += 1 if lockish else 0
        self.generic_visit(node)
        self.lock_depth -= 1 if lockish else 0

    def _visit_function(self, node):
        info = _FunctionInfo(node)
        self.functions[node.name] = info
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = self.aliases.resolve(target)
            if name in _TRACING_FNS:
                info.traced_root = True
            if isinstance(dec, ast.Call):
                # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
                if name and name.rsplit(".", 1)[-1] == "partial" and \
                        dec.args and \
                        self.aliases.resolve(dec.args[0]) in _TRACING_FNS:
                    info.traced_root = True
                    self._check_donate(dec, node.name, node)
            if name in _JIT_FNS:
                self._check_donate(dec if isinstance(dec, ast.Call) else None,
                                   node.name, node)
        saved_globals = self.global_names
        saved_loops = self.loop_depth
        self.global_names = set(saved_globals)
        self.loop_depth = 0
        self.func_stack.append(node)
        self._fn_info.append(info)
        self.generic_visit(node)
        self._fn_info.pop()
        self.func_stack.pop()
        self.loop_depth = saved_loops
        self.global_names = saved_globals

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_For(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_While = visit_For

    def _check_donate(self, call, fn_name, report_node):
        """GL102: a step-shaped jit target must donate its state arg."""
        if not _STEP_NAME.search(fn_name or ""):
            return
        if (fn_name or "").startswith("eval"):
            return  # eval steps read state; donating it would be the bug
        kws = {k.arg for k in call.keywords} if call is not None else set()
        if not kws & {"donate_argnums", "donate_argnames"}:
            self.emit("GL102", report_node,
                      f"jit of step-shaped '{fn_name}' without "
                      f"donate_argnums — the old state stays resident and "
                      f"params double-buffer in HBM")

    # -- calls: the bulk of the rules ---------------------------------------
    def visit_Call(self, node):
        name = self.aliases.resolve(node.func)

        # GL001 — .item()
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
                and not node.args and not node.keywords \
                and not self._in_accounted_fn():
            self.emit("GL001", node,
                      ".item() blocks the host on a device->host transfer; "
                      "keep the value device-resident or route it through "
                      "the engine's accounted _host_fetch")

        if name is not None:
            # GL003 — device_get outside the accounted path
            if name in _DEVICE_GET and not self._in_accounted_fn():
                self.emit("GL003", node,
                          "jax.device_get outside _host_fetch — the fetch "
                          "is unaccounted, host_sync_count cannot audit it")
            # GL004 — np.asarray host materialization
            elif name in _ASARRAY and not self._in_accounted_fn():
                self.emit("GL004", node,
                          f"{name}() materializes on host; a device-array "
                          f"argument silently syncs the dispatch queue")
            # GL002 — float/int/bool over a jax expression
            elif name in ("float", "int", "bool") and len(node.args) == 1 \
                    and not self._in_accounted_fn() \
                    and _contains_jax_expr(node.args[0], self.aliases):
                self.emit("GL002", node,
                          f"{name}() over a jax expression forces a "
                          f"blocking transfer (and raises under trace)")
            # clock reads: record for the GL103 reachability pass; GL105
            # fires immediately when the module has an injectable alias
            elif name in _CLOCK_FNS:
                if self._fn_info:
                    self._fn_info[-1].clock_calls.append((node, name))
                if self.clock_aliases:
                    alias = self.clock_aliases[0][0]
                    self.emit("GL105", node,
                              f"raw {name}() bypasses this module's "
                              f"injectable clock alias '{alias}' — tests "
                              f"can no longer pin time")
            # GL101 / GL104 / GL102 — jit call forms
            elif name in _JIT_FNS:
                if self.loop_depth > 0:
                    self.emit("GL101", node,
                              "jit called inside a loop body builds a "
                              "fresh callable every iteration — the "
                              "compile cache never hits; hoist it out")
                if node.args:
                    target = node.args[0]
                    tname = target.id if isinstance(target, ast.Name) else None
                    if isinstance(target, ast.Lambda) or (
                            self.func_stack and tname in self.functions and
                            self._is_local_def(tname)):
                        self.emit("GL104", node,
                                  "jit over a fresh lambda/local def keys "
                                  "the compile cache on a new callable "
                                  "identity — cache the jitted result or "
                                  "hoist the callee to module scope")
                    if tname is not None:
                        self._check_donate(node, tname, node)
                    info = self.functions.get(tname)
                    if info is not None:
                        info.traced_root = True
            # any tracer taking a function argument marks GL103 roots
            elif name in _TRACING_FNS:
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in self.functions:
                        self.functions[arg.id].traced_root = True

        # record intra-module simple-name calls for the reachability pass
        if self._fn_info and isinstance(node.func, ast.Name):
            self._fn_info[-1].calls.add(node.func.id)

        self.generic_visit(node)

    def _is_local_def(self, name):
        """Is ``name`` a function defined inside the CURRENT function body
        (as opposed to module scope)? Local defs are fresh objects per call
        of the enclosing function."""
        info = self.functions.get(name)
        if info is None:
            return False
        encl = self.func_stack[-1]
        return any(child is info.node for child in ast.walk(encl)) and \
            info.node is not encl

    # -- finale -------------------------------------------------------------
    def finish(self):
        # GL103: propagate traced-root reachability over the module-local
        # simple-name call graph, then flag clock reads inside the closure
        reachable = {n for n, i in self.functions.items() if i.traced_root}
        changed = True
        while changed:
            changed = False
            for n, info in self.functions.items():
                if n in reachable:
                    for callee in info.calls:
                        if callee in self.functions and callee not in reachable:
                            reachable.add(callee)
                            changed = True
        for n in reachable:
            for node, cname in self.functions[n].clock_calls:
                self.emit("GL103", node,
                          f"{cname}() inside '{n}', which is reachable "
                          f"from traced code — under jit it traces as a "
                          f"compile-time constant; time outside the trace "
                          f"or use io_callback")
        # pragma findings honor line-level GL000 suppression of themselves
        for f in self._pragma_findings:
            if "GL000" not in self.allow_lines.get(f["line"], ()):
                if self.select is None or "GL000" in self.select:
                    self.findings.append(f)
        self.findings.sort(key=lambda f: (f["line"], f["col"], f["rule"]))
        return self.findings


def lint_source(src, path="<string>", select=None):
    """Lint one source string. Returns a list of finding dicts. A syntax
    error is reported as a GL000-style error finding rather than raised —
    the tree gate must not crash on one bad file."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        node = ast.Constant(None)
        node.lineno, node.col_offset = e.lineno or 0, (e.offset or 1) - 1
        f = _finding("GL000", path, node, f"unparseable source: {e.msg}")
        return [f]
    linter = _Linter(path, src, select=set(select) if select else None)
    linter.visit(tree)
    return linter.finish()


def lint_file(path, select=None, relative_to=None):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    rel = os.path.relpath(path, relative_to).replace(os.sep, "/") \
        if relative_to else path
    return lint_source(src, path=rel, select=select)


def lint_paths(paths, select=None, relative_to=None):
    """Lint files and directory trees (``*.py``, skipping ``__pycache__``
    and friends). Findings carry ``relative_to``-relative paths so the
    baseline is stable across checkouts."""
    findings = []
    for p in paths:
        if os.path.isfile(p):
            findings.extend(lint_file(p, select=select,
                                      relative_to=relative_to))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    findings.extend(lint_file(
                        os.path.join(dirpath, fn), select=select,
                        relative_to=relative_to))
    return findings


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------

def summarize(findings):
    """Per-rule totals and per-rule-per-file counts — the ratchet unit."""
    rules = {}
    for f in findings:
        r = rules.setdefault(f["rule"], {"count": 0, "files": {}})
        r["count"] += 1
        r["files"][f["path"]] = r["files"].get(f["path"], 0) + 1
    for r in rules.values():
        r["files"] = dict(sorted(r["files"].items()))
    return {"total": len(findings), "rules": dict(sorted(rules.items()))}


def make_baseline(findings, root="deepspeed_tpu"):
    return {"version": 1, "tool": "graftlint", "root": root,
            "regenerate": "python scripts/graftlint.py --write-baseline",
            **summarize(findings)}


def load_baseline(path):
    """Returns (baseline_dict, error_string). A missing/unreadable file or
    a wrong-shape doc is a hard error (exit 2): the gate must never pass
    because its own baseline rotted."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"cannot read lint baseline {path}: {e}"
    if not isinstance(doc, dict) or doc.get("tool") != "graftlint" \
            or not isinstance(doc.get("rules"), dict):
        return None, (f"malformed lint baseline {path}: expected a "
                      f"graftlint doc with a 'rules' map")
    for rid, entry in doc["rules"].items():
        if rid not in RULES:
            return None, f"baseline names unknown rule {rid}"
        if not isinstance(entry, dict) \
                or not isinstance(entry.get("count"), int) \
                or not isinstance(entry.get("files"), dict):
            return None, f"baseline rule {rid} entry malformed: {entry!r}"
    return doc, None


def check_baseline(findings, baseline):
    """The ratchet: per-rule totals AND per-rule-per-file counts may only
    go down. Returns a report dict::

        {"ok": bool,
         "regressions": ["GL001: deepspeed_tpu/x.py has 2 findings, "
                         "baseline allows 1", ...],
         "improvements": ["GL004: 120 -> 118 (baseline can tighten)", ...],
         "counts": {rule: current_count}}

    A finding in a file the baseline has never seen is a regression; a
    count below baseline is reported so the baseline can be regenerated
    tighter (it never auto-tightens — that would hide a flapping rule).
    """
    current = summarize(findings)
    base_rules = baseline.get("rules", {})
    regressions, improvements = [], []
    for rid in sorted(set(current["rules"]) | set(base_rules)):
        entry = current["rules"].get(rid, {"count": 0, "files": {}})
        base = base_rules.get(rid, {"count": 0, "files": {}})
        if entry["count"] > base["count"]:
            regressions.append(
                f"{rid}: {entry['count']} findings, baseline allows "
                f"{base['count']} ({RULES[rid][1]})")
        elif entry["count"] < base["count"]:
            improvements.append(
                f"{rid}: {base['count']} -> {entry['count']} (baseline can "
                f"tighten)")
        base_files = base.get("files", {})
        for path, n in sorted(entry["files"].items()):
            allowed = base_files.get(path, 0)
            if n > allowed:
                regressions.append(
                    f"{rid}: {path} has {n} finding(s), baseline allows "
                    f"{allowed}")
    return {"ok": not regressions, "regressions": regressions,
            "improvements": improvements,
            "counts": {rid: e["count"]
                       for rid, e in sorted(current["rules"].items())}}
