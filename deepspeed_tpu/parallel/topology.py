"""Device-mesh topology.

Mirrors the reference's ``ProcessTopology`` / ``PipeModelDataParallelTopology``
(``runtime/pipe/topology.py:12,244``) but TPU-native: instead of building
torch.distributed process groups per axis, we build ONE ``jax.sharding.Mesh``
whose named axes carry every parallelism form, and XLA's GSPMD partitioner
inserts collectives along those axes.

Canonical axis order (outermost → innermost):

    ("pp", "dpr", "dp", "ep", "sp", "tp")

- ``pp``  pipeline stages — outermost so stages map to DCN/slice boundaries
- ``dpr`` ZeRO replica groups — the hierarchical split of the data-parallel
  world used by MiCS (``runtime/zero/mics.py``) and ZeRO++ hpZ
  (``zero_hpz_partition_size``): state is sharded *within* a ``dp`` group and
  replicated *across* ``dpr`` groups. Size 1 unless hierarchy is requested.
  On a TPU pod this maps shard groups to ICI-connected slices and replica
  groups to DCN — exactly the node-local/cross-node split the reference
  builds with nested process groups.
- ``dp``  data parallel shard axis (ZeRO shard axis together with ep+sp)
- ``ep``  expert parallel — carved out of the data-parallel world, exactly as the
  reference forms expert groups inside DP (``utils/groups.py:114,254``)
- ``sp``  Ulysses sequence parallel (``deepspeed/sequence/layer.py``)
- ``tp``  tensor parallel — innermost so its collectives ride the fastest ICI links

Data-like axes: the global batch is sharded over ``(dpr, dp, ep)`` and the
sequence over ``sp``; gradients of shared (non-expert) parameters must
therefore be reduced over all of ``(dpr, dp, ep, sp)`` — those are also the
ZeRO partition axes (modulo the MiCS/hpZ carve-outs below).
"""

import numpy as np

AXIS_ORDER = ("pp", "dpr", "dp", "ep", "sp", "tp")


class MeshTopology:

    def __init__(self, pp=1, dp=-1, ep=1, sp=1, tp=1, devices=None,
                 zero_shard_size=None, zero_hierarchy=None):
        """``zero_shard_size`` splits the data-parallel world hierarchically:
        ``dp`` becomes the shard group (that size) and ``dpr`` the replica
        groups across it. ``zero_hierarchy`` records why: "mics"
        (``mics_shard_size``: ALL ZeRO state confined to the shard group) or
        "hpz" (``zero_hpz_partition_size``: only the stage-3 working params —
        the reference's secondary tensor — use the smaller group)."""
        import jax
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        fixed = pp * ep * sp * tp
        if dp == -1:
            assert n % fixed == 0, (
                f"device count {n} not divisible by pp*ep*sp*tp={fixed}")
            dp = n // fixed
        assert pp * dp * ep * sp * tp == n, (
            f"mesh {pp}x{dp}x{ep}x{sp}x{tp} != device count {n}")
        dpr = 1
        if zero_shard_size and zero_shard_size > 0:
            assert zero_shard_size <= dp, (
                f"zero shard size {zero_shard_size} exceeds the data-parallel "
                f"world {dp} (reference mics_shard_size/zero_hpz_partition_size "
                f"must divide the DP world)")
            assert dp % zero_shard_size == 0, (
                f"dp={dp} not divisible by zero shard size {zero_shard_size}")
            assert zero_hierarchy in ("mics", "hpz"), \
                "zero_shard_size requires zero_hierarchy of 'mics' or 'hpz'"
            dpr = dp // zero_shard_size
            dp = zero_shard_size
        self.zero_hierarchy = zero_hierarchy if dpr > 1 else None
        self.pp_size, self.dp_size, self.ep_size, self.sp_size, self.tp_size = pp, dp, ep, sp, tp
        self.dpr_size = dpr
        self._sizes = dict(pp=pp, dpr=dpr, dp=dp, ep=ep, sp=sp, tp=tp)
        dev_array = np.asarray(devices).reshape(pp, dpr, dp, ep, sp, tp)
        self.mesh = jax.sharding.Mesh(dev_array, AXIS_ORDER)

    @property
    def axis_names(self):
        return AXIS_ORDER

    def get_dim(self, axis):
        return self._sizes[axis]

    @property
    def zero_axes(self):
        """Axes over which ZeRO partitions master/optimizer state and grads;
        the reference's DP world (``groups._get_data_parallel_group``) is the
        product of these. MiCS confines ALL state to the shard group ("dp"),
        replicating across "dpr" — XLA then emits reduce-scatter inside the
        group plus a cross-group all-reduce, the MiCS hierarchical comm
        pattern (``runtime/zero/mics.py``)."""
        if self.zero_hierarchy == "mics":
            return ("dp", "ep", "sp")
        return ("dpr", "dp", "ep", "sp")

    @property
    def param_zero_axes(self):
        """Axes for the stage-3 *working* (bf16) parameter shards. Under hpZ
        these are the reference's secondary partitions
        (``zero_hpz_partition_size``): sharded only within the ICI-local
        group so backward all-gathers never cross DCN."""
        if self.zero_hierarchy in ("hpz", "mics"):
            return ("dp", "ep", "sp")
        return self.zero_axes

    @property
    def data_parallel_size(self):
        return self.dpr_size * self.dp_size * self.ep_size * self.sp_size

    @property
    def batch_spec(self):
        """PartitionSpec for a [batch, seq, ...] input."""
        from jax.sharding import PartitionSpec as P
        return P(("dpr", "dp", "ep"), "sp")

    def batch_sharding(self):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, self.batch_spec)

    def stacked_batch_sharding(self):
        """Sharding for a [gas, batch, seq, ...] micro-batch stack (the fused
        whole-window step): window axis replicated, batch over the data axes."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(None, *self.batch_spec))

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def sharding(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(*spec))

    # --- coordinate math, mirroring ProcessTopology (topology.py:12) ---
    def world_size(self):
        return int(np.prod([self._sizes[a] for a in AXIS_ORDER]))

    def get_rank(self, **coords):
        """Flat rank from axis coordinates (reference ``ProcessTopology.get_rank``)."""
        full = [coords.get(a, 0) for a in AXIS_ORDER]
        dims = [self._sizes[a] for a in AXIS_ORDER]
        rank = 0
        for c, d in zip(full, dims):
            rank = rank * d + c
        return rank

    def get_coord(self, rank):
        dims = [self._sizes[a] for a in AXIS_ORDER]
        coords = {}
        for a, d in zip(reversed(AXIS_ORDER), reversed(dims)):
            coords[a] = rank % d
            rank //= d
        return {a: coords[a] for a in AXIS_ORDER}

    def __repr__(self):
        shown = [a for a in AXIS_ORDER if a != "dpr" or self.dpr_size > 1]
        return ("MeshTopology(" +
                ", ".join(f"{a}={self._sizes[a]}" for a in shown) + ")")


# ---------------------------------------------------------------------------
# Active kernel mesh — the topology half of the Pallas SPMD dispatch layer.
#
# GSPMD cannot auto-partition Mosaic (Pallas TPU) kernels: compiling a traced
# kernel under a >1-device sharding fails with "Mosaic kernels cannot be
# automatically partitioned. Please wrap the call in a shard_map." The op
# layer (``ops/registry.py:sharded_kernel_call``) therefore wraps each kernel
# invocation in a ``shard_map`` over the *active* mesh. This registry answers
# two questions for it:
#
#   1. which mesh is active?  — an explicit ``use_kernel_mesh(mesh)`` context
#      wins; otherwise the globally installed ``groups`` topology (engines
#      install it at construction) is used.
#   2. which mesh axes play which kernel role?  — "data" axes shard the
#      batch/token dimension (the reference's DP/expert/replica worlds);
#      the "head" axis shards attention heads / output features (TP).
#
# Axes already bound as *manual* in an enclosing shard_map (e.g. the engine's
# qgZ step or an explicit Ulysses shard_map) are excluded: the kernel is
# already running per-shard along them, and nesting a second shard_map over
# the same names is invalid.
# ---------------------------------------------------------------------------

import contextlib

# mesh axis names recognized per kernel role. "data"/"batch"/"model" cover
# ad-hoc meshes built by scripts and tests; the canonical names are AXIS_ORDER.
DATA_AXIS_NAMES = ("dpr", "dp", "ep", "data", "batch")
HEAD_AXIS_NAMES = ("tp", "model")

_KERNEL_MESH_STACK = []


@contextlib.contextmanager
def use_kernel_mesh(mesh):
    """Make ``mesh`` the active kernel-dispatch mesh within the context.

    Pass a ``jax.sharding.Mesh`` (or a ``MeshTopology``, whose ``.mesh`` is
    taken) to route Pallas kernels through ``shard_map`` over it; pass
    ``None`` to explicitly disable kernel sharding (e.g. the single-device
    parity arm of an A/B test) even when a global topology is installed.
    """
    if isinstance(mesh, MeshTopology):
        mesh = mesh.mesh
    _KERNEL_MESH_STACK.append(mesh)
    try:
        yield mesh
    finally:
        _KERNEL_MESH_STACK.pop()


def active_kernel_mesh():
    """The mesh Pallas kernels should shard over, or None.

    Resolution order: innermost ``use_kernel_mesh`` context (a ``None`` entry
    disables dispatch), else the global ``groups`` topology's mesh if one has
    been initialized (without building one as a side effect).
    """
    if _KERNEL_MESH_STACK:
        return _KERNEL_MESH_STACK[-1]
    from deepspeed_tpu.parallel import groups
    topo = getattr(groups, "_TOPOLOGY", None)
    return topo.mesh if topo is not None else None


def _manual_axis_names(mesh):
    """Mesh axes already mapped by an enclosing shard_map at trace time."""
    try:
        from jax._src import core as _jcore
        env = _jcore.get_axis_env()
        return {a for a in mesh.axis_names if env.axis_exists(a)}
    except Exception:
        return set()


def kernel_partition_axes(mesh):
    """Map ``mesh``'s axes onto kernel roles.

    Returns ``{"data": tuple_of_axes, "head": axis_or_None}`` — only axes of
    size > 1 that are not already manual in an enclosing shard_map. ``data``
    may name several mesh axes (sharded jointly, like ``batch_spec``);
    ``head`` is at most one.
    """
    manual = _manual_axis_names(mesh)
    shape = dict(mesh.shape)
    data = tuple(a for a in DATA_AXIS_NAMES
                 if shape.get(a, 1) > 1 and a not in manual)
    head = next((a for a in HEAD_AXIS_NAMES
                 if shape.get(a, 1) > 1 and a not in manual), None)
    return {"data": data, "head": head}


def build_topology(config=None, devices=None):
    """Build a MeshTopology from a DeepSpeedConfig-like object (or defaults)."""
    pp = ep = sp = tp = 1
    zero_shard_size = zero_hierarchy = None
    if config is not None:
        pp = getattr(config, "pipeline_stages", 1) or 1
        ep = getattr(config, "expert_parallel_size", 1) or 1
        sp = getattr(config, "sequence_parallel_size", 1) or 1
        tp = getattr(config, "tensor_parallel_size", 1) or 1
        zc = getattr(config, "zero_config", None)
        if zc is not None:
            if getattr(zc, "mics_shard_size", -1) and zc.mics_shard_size > 0:
                zero_shard_size, zero_hierarchy = zc.mics_shard_size, "mics"
            elif getattr(zc, "zero_hpz_partition_size", 1) and \
                    zc.zero_hpz_partition_size > 1:
                zero_shard_size, zero_hierarchy = zc.zero_hpz_partition_size, "hpz"
    return MeshTopology(pp=pp, dp=-1, ep=ep, sp=sp, tp=tp, devices=devices,
                        zero_shard_size=zero_shard_size,
                        zero_hierarchy=zero_hierarchy)
