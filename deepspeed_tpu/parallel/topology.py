"""Device-mesh topology.

Mirrors the reference's ``ProcessTopology`` / ``PipeModelDataParallelTopology``
(``runtime/pipe/topology.py:12,244``) but TPU-native: instead of building
torch.distributed process groups per axis, we build ONE ``jax.sharding.Mesh``
whose named axes carry every parallelism form, and XLA's GSPMD partitioner
inserts collectives along those axes.

Canonical axis order (outermost → innermost):

    ("pp", "dp", "ep", "sp", "tp")

- ``pp``  pipeline stages — outermost so stages map to DCN/slice boundaries
- ``dp``  pure data parallel (ZeRO shard axis together with ep+sp)
- ``ep``  expert parallel — carved out of the data-parallel world, exactly as the
  reference forms expert groups inside DP (``utils/groups.py:114,254``)
- ``sp``  Ulysses sequence parallel (``deepspeed/sequence/layer.py``)
- ``tp``  tensor parallel — innermost so its collectives ride the fastest ICI links

Data-like axes: the global batch is sharded over ``(dp, ep)`` and the sequence
over ``sp``; gradients of shared (non-expert) parameters must therefore be
reduced over all of ``(dp, ep, sp)`` — those are also the ZeRO partition axes.
"""

import numpy as np

AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")


class MeshTopology:

    def __init__(self, pp=1, dp=-1, ep=1, sp=1, tp=1, devices=None):
        import jax
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        fixed = pp * ep * sp * tp
        if dp == -1:
            assert n % fixed == 0, (
                f"device count {n} not divisible by pp*ep*sp*tp={fixed}")
            dp = n // fixed
        assert pp * dp * ep * sp * tp == n, (
            f"mesh {pp}x{dp}x{ep}x{sp}x{tp} != device count {n}")
        self.pp_size, self.dp_size, self.ep_size, self.sp_size, self.tp_size = pp, dp, ep, sp, tp
        self._sizes = dict(pp=pp, dp=dp, ep=ep, sp=sp, tp=tp)
        dev_array = np.asarray(devices).reshape(pp, dp, ep, sp, tp)
        self.mesh = jax.sharding.Mesh(dev_array, AXIS_ORDER)

    @property
    def axis_names(self):
        return AXIS_ORDER

    def get_dim(self, axis):
        return self._sizes[axis]

    @property
    def zero_axes(self):
        """Axes over which ZeRO partitions params/grads/optimizer state; the
        reference's DP world (``groups._get_data_parallel_group``) is the
        product of these."""
        return ("dp", "ep", "sp")

    @property
    def data_parallel_size(self):
        return self.dp_size * self.ep_size * self.sp_size

    @property
    def batch_spec(self):
        """PartitionSpec for a [batch, seq, ...] input."""
        from jax.sharding import PartitionSpec as P
        return P(("dp", "ep"), "sp")

    def batch_sharding(self):
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh, self.batch_spec)

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def sharding(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(*spec))

    # --- coordinate math, mirroring ProcessTopology (topology.py:12) ---
    def world_size(self):
        return int(np.prod([self._sizes[a] for a in AXIS_ORDER]))

    def get_rank(self, **coords):
        """Flat rank from axis coordinates (reference ``ProcessTopology.get_rank``)."""
        full = [coords.get(a, 0) for a in AXIS_ORDER]
        dims = [self._sizes[a] for a in AXIS_ORDER]
        rank = 0
        for c, d in zip(full, dims):
            rank = rank * d + c
        return rank

    def get_coord(self, rank):
        dims = [self._sizes[a] for a in AXIS_ORDER]
        coords = {}
        for a, d in zip(reversed(AXIS_ORDER), reversed(dims)):
            coords[a] = rank % d
            rank //= d
        return {a: coords[a] for a in AXIS_ORDER}

    def __repr__(self):
        return ("MeshTopology(" +
                ", ".join(f"{a}={self._sizes[a]}" for a in AXIS_ORDER) + ")")


def build_topology(config=None, devices=None):
    """Build a MeshTopology from a DeepSpeedConfig-like object (or defaults)."""
    pp = ep = sp = tp = 1
    if config is not None:
        pp = getattr(config, "pipeline_stages", 1) or 1
        ep = getattr(config, "expert_parallel_size", 1) or 1
        sp = getattr(config, "sequence_parallel_size", 1) or 1
        tp = getattr(config, "tensor_parallel_size", 1) or 1
    return MeshTopology(pp=pp, dp=-1, ep=ep, sp=sp, tp=tp, devices=devices)
