"""Global topology registry — the analog of ``deepspeed/utils/groups.py``.

The reference materializes torch process groups per parallelism axis
(``groups.initialize(ep_size, mpu)``, ``utils/groups.py:52``; getters at
:397-487). On TPU a "group" is a named mesh axis; this module keeps the
process-wide ``MeshTopology`` and exposes the same getter surface.
"""

from deepspeed_tpu.parallel.topology import MeshTopology, build_topology

_TOPOLOGY = None


def initialize(ep_size=1, mesh_topology=None, config=None, devices=None):
    """Install the global topology (reference ``utils/groups.py:52`` initialize)."""
    global _TOPOLOGY
    if mesh_topology is not None:
        _TOPOLOGY = mesh_topology
    else:
        _TOPOLOGY = build_topology(config=config, devices=devices)
        if ep_size > 1 and _TOPOLOGY.ep_size == 1:
            _TOPOLOGY = MeshTopology(pp=_TOPOLOGY.pp_size,
                                     dp=-1,
                                     ep=ep_size,
                                     sp=_TOPOLOGY.sp_size,
                                     tp=_TOPOLOGY.tp_size,
                                     devices=devices)
    return _TOPOLOGY


def get_topology():
    global _TOPOLOGY
    if _TOPOLOGY is None:
        _TOPOLOGY = build_topology()
    return _TOPOLOGY


def reset():
    global _TOPOLOGY
    _TOPOLOGY = None


def get_mesh():
    return get_topology().mesh


# --- getter surface mirroring utils/groups.py:397-487 ---
def get_data_parallel_world_size():
    return get_topology().data_parallel_size


def get_model_parallel_world_size():
    return get_topology().tp_size


def get_tensor_model_parallel_world_size():
    return get_topology().tp_size


def get_expert_parallel_world_size(group_name=None):
    return get_topology().ep_size


def get_expert_data_parallel_world_size(group_name=None):
    t = get_topology()
    return t.dpr_size * t.dp_size * t.sp_size


def get_sequence_parallel_world_size():
    return get_topology().sp_size


def get_pipe_parallel_world_size():
    return get_topology().pp_size


def get_world_size():
    return get_topology().world_size()
