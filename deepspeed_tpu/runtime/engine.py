"""DeepSpeedEngine — the TPU-native training engine.

Mirrors the capability surface of the reference ``DeepSpeedEngine``
(``deepspeed/runtime/engine.py:180``): ``forward`` (:1794) / ``backward``
(:1933) / ``step`` (:2132), gradient accumulation with boundary semantics,
mixed precision (fp16 dynamic loss scaling / bf16), ZeRO 0-3, gradient
clipping, LR scheduling, checkpoint save/load (:3056/:2712), monitoring and
wall-clock timers.

Architecture (deliberately NOT a transliteration): the reference drives eager
PyTorch with backward hooks, bucketed NCCL reduce-scatter and stream juggling.
Here the whole micro-step (forward+backward+grad-accumulate) and the whole
apply-step (unscale, clip, optimizer, loss-scale update, recast) are each ONE
jitted XLA program over a sharded state pytree; ZeRO partitioning is a set of
GSPMD sharding constraints (see ``runtime/zero/partition.py``) and XLA emits
the reduce-scatters/all-gathers the reference issues by hand. The
forward/backward/step imperative API is preserved on top: ``forward`` runs the
fused micro-step and stages the result, ``backward`` commits it, ``step``
applies the optimizer at the gradient-accumulation boundary.
"""

import json
import os
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deepspeed_tpu.utils import jax_compat  # noqa: F401  installs jax.shard_map on old jax
from deepspeed_tpu.ops.adam import build_optimizer, set_lr
from deepspeed_tpu.resilience import CorruptCheckpointError, faults as _faults
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
from deepspeed_tpu.runtime.fp16.loss_scaler import (LossScaleState, init_loss_scale_state,
                                                    update_loss_scale)
from deepspeed_tpu.runtime.lr_schedules import LRSchedulerShim, get_lr_schedule
from deepspeed_tpu.runtime.utils import (clip_grads_by_global_norm, constrain_tree,
                                         count_parameters, global_norm, has_overflow,
                                         tree_cast, tree_where, tree_zeros_like)
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER,
                                       STEP_GLOBAL_TIMER, SynchronizedWallClockTimer,
                                       ThroughputTimer)


class TrainState(NamedTuple):
    """The engine's entire training state as one sharded pytree."""
    params: Any            # working precision (bf16/fp16/fp32)
    master: Any            # fp32 master copy (None in pure-fp32 training)
    opt_state: Any
    grad_acc: Any          # gradient accumulation buffer (grad_accum_dtype)
    scale: LossScaleState
    global_step: jnp.ndarray
    skipped: jnp.ndarray   # overflow-skipped step count (device-side: no per-step host sync)
    rng: jnp.ndarray
    qgz_residual: Any = None  # qgZ error-feedback carry (stacked grad layout)


class StepStats(NamedTuple):
    grad_norm: jnp.ndarray
    overflow: jnp.ndarray
    lr: jnp.ndarray
    loss_scale: jnp.ndarray


class OptimizerShim:
    """Minimal object with the torch-optimizer surface the reference returns
    from initialize() — param_groups for LR introspection/HF compat.

    ``state_dict``/``load_state_dict`` round-trip the real optimizer state so
    HF-Trainer-side checkpointing does not silently drop it."""

    def __init__(self, engine, base_lr):
        self._engine = engine
        self.param_groups = [{"lr": base_lr}]

    @staticmethod
    def _fetch(leaf):
        # multi-host safe: leaves spanning non-addressable devices need the
        # cross-process gather; device_get alone raises there
        if getattr(leaf, "is_fully_addressable", True):
            return jax.device_get(leaf)
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(leaf, tiled=True)

    def state_dict(self):
        st = self._engine.state
        if st is None:
            logger.warning("OptimizerShim.state_dict(): engine state not yet "
                           "initialized; returning empty dict")
            return {}
        sd = {"opt_state": jax.tree.map(self._fetch, st.opt_state),
              "global_step": int(self._fetch(st.global_step)),
              "scale": jax.tree.map(self._fetch, st.scale),
              "skipped": int(self._fetch(st.skipped))}
        if self._engine._offload is not None:
            # ZeRO-Offload: most (ratio=1.0: all) moments live in the host tier
            sd["offload"] = self._engine._offload.state_dict()
        if self._engine._param_store is not None:
            # ZeRO-Infinity param tier: streamed masters + moments are host-side
            sd["param_offload"] = self._engine._param_store.state_dict()
        return sd

    def load_state_dict(self, sd):
        if not sd:
            return
        st = self._engine.state
        if st is None:
            # lazy init (no model_parameters yet): defer and apply at init
            self._engine._pending_opt_state = sd
            return
        opt = jax.tree.map(
            lambda cur, new: jax.device_put(jnp.asarray(new, cur.dtype), cur.sharding),
            st.opt_state, sd["opt_state"])
        gs = jax.device_put(jnp.int32(sd.get("global_step", 0)),
                            st.global_step.sharding)
        repl = {"opt_state": opt, "global_step": gs}
        if "scale" in sd:
            repl["scale"] = jax.tree.map(
                lambda cur, new: jax.device_put(jnp.asarray(new, cur.dtype),
                                                cur.sharding),
                st.scale, LossScaleState(*sd["scale"]))
            repl["skipped"] = jax.device_put(jnp.int32(sd.get("skipped", 0)),
                                             st.skipped.sharding)
        self._engine.state = st._replace(**repl)
        if "offload" in sd and self._engine._offload is not None:
            self._engine._offload.load_state_dict(sd["offload"])
            self._engine._refresh_working_from_master()
        if "param_offload" in sd and self._engine._param_store is not None:
            self._engine._param_store.load_state_dict(sd["param_offload"])

    def zero_grad(self, set_to_none=True):
        pass  # grads live in the engine's accumulation buffer

    def step(self):
        raise RuntimeError("Call engine.step() — the engine owns the optimizer step")


# optimizer-name constants (reference runtime/engine.py:84)
ADAM_OPTIMIZER = "adam"
LAMB_OPTIMIZER = "lamb"


class DeepSpeedEngine:

    def __init__(self,
                 config=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mesh=None,
                 collate_fn=None,
                 rng=None,
                 param_specs=None,
                 dont_change_device=False):
        self.config = config if isinstance(config, DeepSpeedConfig) else DeepSpeedConfig(config)
        self.module = model
        self._user_param_specs = param_specs

        # --- topology (reference engine.py:1094 _configure_distributed_model) ---
        if mesh is not None:
            if isinstance(mesh, MeshTopology):
                self.topology = mesh
                # the explicit mesh IS the process topology: install it so
                # model-level groups.get_topology() consumers (ring attention,
                # MoE group getters) see the same axes as the engine
                groups.initialize(mesh_topology=mesh)
            else:
                raise ValueError("pass a deepspeed_tpu.parallel.topology.MeshTopology")
        else:
            self.topology = groups.initialize(ep_size=self.config.expert_parallel_size,
                                              config=self.config)
        self.mesh = self.topology.mesh

        # --- elasticity enforcement (reference engine.py:243, elasticity.py:233) ---
        if self.config.elasticity_config.enabled:
            from deepspeed_tpu.elasticity import compute_elastic_config
            from deepspeed_tpu.elasticity.elasticity import ElasticityError
            ec = self.config.elasticity_config
            has_batch_info = (self.config.train_batch_size is not None
                              or self.config.train_micro_batch_size_per_gpu is not None
                              or self.config.gradient_accumulation_steps is not None)
            if has_batch_info and not ec.ignore_non_elastic_batch_info:
                raise ElasticityError(
                    "elasticity is enabled but the config also fixes batch sizes; "
                    "set ignore_non_elastic_batch_info to override (reference "
                    "elasticity/config.py semantics)")
            world = self.topology.data_parallel_size
            fb, _, mbs = compute_elastic_config(self.config._param_dict,
                                                world_size=world,
                                                return_microbatch=True)
            self.config.train_batch_size = fb
            self.config.train_micro_batch_size_per_gpu = mbs
            self.config.gradient_accumulation_steps = fb // (mbs * world)

        # --- batch arithmetic (reference config.py:789) ---
        tb, mb, gas = self.config.resolve_batch_params(self.topology.data_parallel_size)
        self.train_batch_size_value = tb
        self.micro_batch_size = mb
        self.gradient_accumulation_steps_value = gas

        # --- precision ---
        self.fp16_enabled = self.config.fp16.enabled
        self.bf16_enabled = self.config.bf16.enabled
        if self.fp16_enabled:
            self.working_dtype = jnp.float16
        elif self.bf16_enabled:
            self.working_dtype = jnp.bfloat16
        else:
            self.working_dtype = jnp.float32
        self.mixed_precision = self.working_dtype != jnp.float32
        self.dynamic_loss_scale = self.fp16_enabled and not (self.config.fp16.loss_scale > 0)
        gad = self.config.data_types.grad_accum_dtype
        self.grad_accum_dtype = {None: jnp.float32, "fp32": jnp.float32,
                                 "fp16": jnp.float16, "bf16": jnp.bfloat16}[gad]

        # --- model fn normalization ---
        self._model_fn = self._normalize_model_fn(model)

        # --- optimizer (reference engine.py:1228 _configure_optimizer) ---
        # Accepts: a name string, an optax.GradientTransformation (the functional
        # analog of the reference's client torch optimizer), a zero-arg/params
        # factory returning one, or None (use the config section).
        opt_cfg = self.config.optimizer
        self._tx = None
        if optimizer is not None and not isinstance(optimizer, str):
            tx = optimizer
            if callable(tx) and not isinstance(tx, optax.GradientTransformation):
                try:
                    tx = tx(model_parameters)
                except TypeError:
                    tx = tx()
            if not isinstance(tx, optax.GradientTransformation):
                raise ValueError(
                    "client optimizer must be an optax.GradientTransformation or a "
                    f"factory returning one, got {type(optimizer)}")
            self._tx, self._base_lr = tx, opt_cfg.params.get("lr", 1e-3)
        else:
            opt_name = optimizer if isinstance(optimizer, str) else opt_cfg.type
            self._tx, self._base_lr = build_optimizer(opt_name, opt_cfg.params)
        self.optimizer = OptimizerShim(self, self._base_lr)

        # --- LR schedule (reference engine.py:914) ---
        # Accepts: a name string, a callable step->lr (client schedule), or None.
        if lr_scheduler is not None and not isinstance(lr_scheduler, str):
            if not callable(lr_scheduler):
                raise ValueError("client lr_scheduler must be callable: step -> lr")
            self._schedule_fn = lr_scheduler
        else:
            sched_name = lr_scheduler if isinstance(lr_scheduler, str) else self.config.scheduler.type
            self._schedule_fn = get_lr_schedule(sched_name, self.config.scheduler.params,
                                                base_lr=opt_cfg.params.get("lr", self._base_lr))
        self.lr_scheduler = LRSchedulerShim(self._schedule_fn, engine=self)

        # --- dataloader (reference engine.py:1699 deepspeed_io) ---
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = DeepSpeedDataLoader(
                training_data, batch_size=self.micro_batch_size * self.topology.data_parallel_size,
                collate_fn=collate_fn, topology=self.topology)
            if self.config.prefetch_batches:
                # background assembly + ahead-of-time sharded device_put:
                # the host input pipeline overlaps the device step
                from deepspeed_tpu.runtime.dataloader import PrefetchLoader
                self.training_dataloader = PrefetchLoader(
                    self.training_dataloader,
                    sharding=self.topology.batch_sharding(),
                    depth=self.config.prefetch_batches)

        # --- monitoring / timers (reference engine.py:252, 2238) ---
        from deepspeed_tpu.monitor.monitor import MonitorMaster
        self.monitor = MonitorMaster(self.config)
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=tb, steps_per_output=self.config.steps_per_print,
            logging_fn=lambda m: log_dist(m, ranks=[0]))
        self.wall_clock_breakdown = self.config.wall_clock_breakdown

        # comms logging
        import deepspeed_tpu.comm as dist
        dist.configure(comms_config=self.config.comms_config)

        # unified telemetry (docs/OBSERVABILITY.md): configure the
        # process-global pipeline ONLY when this config enables it — a
        # disabled section must not clobber a pipeline another caller
        # (tests, benches) already switched on
        from deepspeed_tpu import telemetry
        if self.config.telemetry_config.enabled:
            telemetry.configure(config=self.config.telemetry_config)
        self._telemetry_monitor = bool(self.config.telemetry_config.monitor)

        # resilience (docs/RESILIENCE.md): fault injection, preemption-aware
        # save, step watchdog. Fault arming is config-driven here; the
        # DS_TPU_FAULTS env arms lazily even without a config section.
        rcfg = self.config.resilience_config
        if rcfg.faults:
            _faults.configure(rcfg.faults, seed=rcfg.fault_seed)
        # flight recorder (telemetry/flightrec.py): point bundles at the
        # configured destination and snapshot a config digest into every
        # bundle this process flushes
        from deepspeed_tpu.telemetry import flightrec as _flightrec
        if rcfg.postmortem_dir:
            _flightrec.configure(dir=rcfg.postmortem_dir)
        _flightrec.register_collector("engine/config", self._config_digest)
        self._last_save_dir = None
        self._preemption = None
        if rcfg.preemption.enabled:
            from deepspeed_tpu.resilience import PreemptionHandler
            self._preemption = PreemptionHandler().install()
        self._watchdog = None
        if rcfg.watchdog.enabled:
            from deepspeed_tpu.resilience import StepWatchdog
            wd = rcfg.watchdog
            self._watchdog = StepWatchdog(
                hang_factor=wd.hang_factor, min_interval_s=wd.min_interval_s,
                poll_interval_s=wd.poll_interval_s, window=wd.window,
                abort=wd.abort, exit_code=wd.exit_code,
                dump_file=wd.dump_file or None).start()

        # remat policy for model blocks (models read it at trace time)
        from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
        checkpointing.configure(deepspeed_config=self.config)

        # --- counters (reference engine bookkeeping) ---
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self._step_applied = False
        self._last_stats: Optional[StepStats] = None
        self._staged_loss = None
        self._data_iterator = None  # persistent iterator for train_batch()
        self._host_sync_count = 0   # blocking device->host fetches (see _host_fetch)

        # --- state init ---
        self._rng_seed = rng if rng is not None else self.config.seed
        self.partitioner = None
        self.state: Optional[TrainState] = None
        self._micro_step_fn = None
        self._apply_step_fn = None
        self._fused_step_fn = None
        self._fused_gas_step_fn = None
        self._pending_fused_stats = None
        self._eval_step_fn = None
        self._offload = None  # ZeRO-Offload host tier (zero/offload.py)
        self._param_store = None  # ZeRO-Infinity param tier (zero/param_offload.py)
        self.quantized_weights = False  # ZeRO++ qwZ (set in _init_state)
        self._qgz_plan = None  # ZeRO++ qgZ (set in _init_state, zero/qgz.py)
        self._pending_opt_state = None  # OptimizerShim.load_state_dict pre-init
        self._async_ckpt_engine = None  # lazy (save_checkpoint(async_save=True))
        self.flops_profiler = None  # lazy (profiling/flops_profiler)
        self._param_transform = None  # compression hook (compression/compress.py)
        # trace-level correctness guards (runtime/guards.py)
        self._guards = None
        self._last_guard_batch = None
        if self.config.correctness_guards["enabled"]:
            from deepspeed_tpu.runtime.guards import TraceStabilityGuard
            self._guards = dict(self.config.correctness_guards,
                                snapshot=None, trace=TraceStabilityGuard())
        # legacy seqlen curriculum (reference engine.py:1826 curriculum hook)
        self.curriculum_scheduler = None
        if self.config.curriculum_enabled_legacy:
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
                CurriculumScheduler)
            self.curriculum_scheduler = CurriculumScheduler(
                self.config.curriculum_learning)
        # data_efficiency umbrella (reference data_pipeline/config.py):
        # random-LTD scheduler exposed for model code to query kept tokens
        self.random_ltd_scheduler = None
        de = self.config.data_efficiency
        routing = de.get("data_routing", {})
        if routing.get("enabled") and routing.get("random_ltd", {}).get("enabled"):
            from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
                RandomLTDScheduler)
            self.random_ltd_scheduler = RandomLTDScheduler(
                routing["random_ltd"])
        if model_parameters is not None:
            self._init_state(model_parameters)

        log_dist(
            f"DeepSpeedEngine: zero_stage={self.zero_optimization_stage()} "
            f"dtype={self.working_dtype.__name__} batch=({tb},{mb},{gas}) "
            f"topology={self.topology}", ranks=[0])

    # ------------------------------------------------------------------
    # setup
    # ------------------------------------------------------------------
    def _normalize_model_fn(self, model):
        if model is None:
            raise ValueError("deepspeed_tpu.initialize requires a model")
        if hasattr(model, "apply") and hasattr(model, "init"):  # flax module
            def model_fn(params, batch, rng, training=True):
                rngs = {"dropout": rng} if (rng is not None and training) else None
                kwargs = {}
                try:
                    out = model.apply({"params": params}, batch, rngs=rngs,
                                      deterministic=not training, **kwargs)
                except TypeError:
                    out = model.apply({"params": params}, batch, rngs=rngs, **kwargs)
                return out
            return model_fn
        if callable(model):
            def model_fn(params, batch, rng, training=True):
                try:
                    return model(params, batch, rng)
                except TypeError:
                    return model(params, batch)
            return model_fn
        raise ValueError(f"unsupported model type {type(model)}")

    def _resolve_param_specs(self, params):
        if self._user_param_specs is not None:
            return self._user_param_specs
        if self.module is not None and hasattr(self.module, "param_specs"):
            try:
                return self.module.param_specs(params)
            except Exception:
                return None
        return None

    def _offload_device(self):
        zc = self.config.zero_config
        if zc.cpu_offload:  # deprecated alias (reference zero/config.py)
            return "cpu"
        return zc.offload_optimizer_device

    def _init_state(self, model_parameters):
        # Force a copy: the engine's state buffers are donated to compiled steps,
        # so they must never alias the caller's arrays (astype/device_put return
        # the input unchanged when dtype+sharding already match).
        model_parameters = jax.tree.map(lambda x: jnp.array(x, copy=True), model_parameters)
        params_f32 = tree_cast(model_parameters, jnp.float32)
        self.partitioner = ZeroPartitioner(self.topology, self.config.zero_config,
                                           param_specs=self._resolve_param_specs(params_f32))
        self.partitioner.describe(params_f32)
        if self.config.zero_config.offload_param_device in ("cpu", "nvme"):
            # ZeRO-Infinity parameter tier: working params stream from
            # host/NVMe per scan block (zero/param_offload.py); subsumes the
            # optimizer-offload path for the streamed leaves
            return self._init_state_param_offload(params_f32)
        if self._offload_device() in ("cpu", "nvme"):
            if self.config.zero_config.zero_quantized_weights:
                raise ValueError("zero_quantized_weights cannot be combined with "
                                 "offload_optimizer")
            if self.config.zero_config.zero_quantized_gradients:
                raise ValueError("zero_quantized_gradients cannot be combined "
                                 "with offload_optimizer")
            return self._init_state_offload(params_f32)

        # ZeRO++ qwZ (reference zero_quantized_weights, zero/config.py:40):
        # the stage-3 working copy is stored as int8 + per-group scales, so
        # XLA's per-use all-gathers move int8 over the wire and HBM holds
        # half the bytes. Dequantization happens in-trace at use sites.
        qwz = bool(self.config.zero_config.zero_quantized_weights
                   and self.zero_optimization_stage() >= 3)
        # ZeRO++ hpZ composition: with a secondary partition the working copy
        # stays FULL precision sharded only over the ICI-local param axes
        # (per-use all-gathers ride ICI in bf16); only the primary
        # master->working exchange — the leg that crosses DCN — is quantized,
        # in _apply_core_builder. Without hpZ, qwZ keeps the int8 working
        # copy so XLA's per-use gathers move int8.
        self._qwz_hpz = bool(qwz and self.topology.zero_hierarchy == "hpz")
        self.quantized_weights = qwz and not self._qwz_hpz
        if qwz and not self.mixed_precision:
            raise ValueError("zero_quantized_weights requires fp16/bf16 training "
                             "(the fp32 master holds full precision)")

        working = tree_cast(params_f32, self.working_dtype)
        param_sh = self.partitioner.param_sharding(working)
        master_sh = self.partitioner.master_sharding(params_f32)
        grad_sh = self.partitioner.grad_sharding(params_f32)

        working = jax.tree.map(jax.device_put, working, param_sh)
        if self.quantized_weights:
            param_sh = self._qweight_sharding(param_sh, working)
            working = jax.jit(self._quantize_working)(working)
            working = jax.tree.map(jax.device_put, working, param_sh,
                                   is_leaf=self._is_qleaf)
        if self.mixed_precision:
            master = jax.tree.map(jax.device_put, params_f32, master_sh)
        else:
            master = None
            working = jax.tree.map(jax.device_put, params_f32, master_sh) \
                if self.zero_optimization_stage() >= 3 else working

        opt_target = master if master is not None else working
        opt_state = self._tx.init(opt_target)
        opt_sh = self.partitioner.opt_state_sharding(opt_state, params_f32)
        opt_state = jax.tree.map(jax.device_put, opt_state, opt_sh)

        # qgZ (ZeRO++ zero_quantized_gradients, reference stage3.py:1249):
        # gradients accumulate locally per device in a stacked buffer and are
        # quantize-reduced at the GAS boundary (zero/qgz.py)
        self._qgz_plan = None
        self._qgz_feedback = False
        qgz_residual = None
        if self.config.zero_config.zero_quantized_gradients:
            if self.zero_optimization_stage() < 2:
                raise ValueError("zero_quantized_gradients requires ZeRO stage >= 2 "
                                 "(gradients must be partitioned)")
            if self.quantized_weights and not self._qwz_hpz:
                # qwZ+qgZ would quantize BOTH legs of every exchange across
                # every axis; the composed ZeRO++ path keeps the secondary
                # (ICI) parameter traffic full-precision via hpZ
                raise ValueError(
                    "zero_quantized_gradients + zero_quantized_weights "
                    "requires a secondary parameter partition: set "
                    "zero_hpz_partition_size > 1 (ZeRO++ hpZ)")
            from deepspeed_tpu.runtime.zero.qgz import QgzPlan
            self._qgz_plan = QgzPlan(self.topology, self.partitioner, params_f32)
            grad_acc = self._qgz_plan.stacked_zeros(params_f32, self.grad_accum_dtype)
            grad_sh = self._qgz_plan.stacked_shardings(params_f32)
            self._qgz_feedback = bool(
                self.config.zero_config.zero_quantized_gradients_error_feedback)
            if self._qgz_feedback:
                # fp32 regardless of grad_accum_dtype: the carry is the small
                # difference the wire format dropped
                qgz_residual = self._qgz_plan.stacked_zeros(params_f32,
                                                            jnp.float32)
        else:
            grad_acc = tree_zeros_like(params_f32, self.grad_accum_dtype)
            grad_acc = jax.tree.map(jax.device_put, grad_acc, grad_sh)

        self._shardings = dict(params=param_sh, master=master_sh, grad=grad_sh,
                               opt=opt_sh,
                               use=self.partitioner.use_sharding(params_f32))
        rep = self.topology.replicated()
        scale = init_loss_scale_state(self.config.fp16) if self.fp16_enabled \
            else LossScaleState(jnp.float32(1.0), jnp.int32(0), jnp.int32(0))
        rng_key = jax.random.PRNGKey(self._rng_seed) if isinstance(self._rng_seed, int) \
            else self._rng_seed
        self.state = TrainState(
            params=working,
            master=master,
            opt_state=opt_state,
            grad_acc=grad_acc,
            scale=jax.tree.map(lambda x: jax.device_put(x, rep), scale),
            global_step=jax.device_put(jnp.int32(0), rep),
            skipped=jax.device_put(jnp.int32(0), rep),
            rng=jax.device_put(rng_key, rep),
            qgz_residual=qgz_residual,
        )
        n = count_parameters(params_f32)
        log_dist(f"model parameters: {n/1e6:.2f}M", ranks=[0])
        if self._pending_opt_state is not None:
            sd, self._pending_opt_state = self._pending_opt_state, None
            self.optimizer.load_state_dict(sd)

    def _init_state_offload(self, params_f32):
        """ZeRO-Offload/Infinity state layout (zero/offload.py): the offloaded
        leaves' fp32 master + Adam moments live on the host (DRAM or NVMe);
        only the non-offloaded remainder keeps a device-resident master/optax
        state. Mirrors reference ``offload_optimizer`` cpu/nvme paths."""
        from deepspeed_tpu.runtime.zero.offload import (HostOffloadOptimizer,
                                                        select_offload_leaves)
        zc = self.config.zero_config
        off_cfg = zc.offload_optimizer
        opt_cfg = self.config.optimizer
        opt_name = (opt_cfg.type or "adamw").lower()
        if opt_name not in ("adam", "adamw", "adagrad", "lion"):
            raise ValueError(
                f"offload_optimizer supports adam/adamw/adagrad/lion host steps "
                f"(csrc/adam/cpu_adam.cpp kernels); got {opt_name!r}")
        ratio = off_cfg.ratio if off_cfg.device != "none" else 1.0
        host_keys, _, _ = select_offload_leaves(params_f32, ratio)

        flat_items = jax.tree_util.tree_flatten_with_path(params_f32)[0]
        self._flat_keys = [jax.tree_util.keystr(p) for p, _ in flat_items]
        self._offload_host_indices = [i for i, k in enumerate(self._flat_keys)
                                      if k in host_keys]
        self._offload_device_indices = [i for i, k in enumerate(self._flat_keys)
                                        if k not in host_keys]

        working = tree_cast(params_f32, self.working_dtype)
        param_sh = self.partitioner.param_sharding(working)
        master_sh_full = self.partitioner.master_sharding(params_f32)
        grad_sh = self.partitioner.grad_sharding(params_f32)
        self._flat_param_sh = [s for s in jax.tree_util.tree_leaves(param_sh)]

        working = jax.tree.map(jax.device_put, working, param_sh)

        flat_f32 = [l for _, l in flat_items]
        flat_master_sh = jax.tree_util.tree_leaves(master_sh_full)
        master_d = {self._flat_keys[i]: jax.device_put(flat_f32[i], flat_master_sh[i])
                    for i in self._offload_device_indices}
        self._master_sh_d = {self._flat_keys[i]: flat_master_sh[i]
                             for i in self._offload_device_indices}
        host_leaves = {self._flat_keys[i]: np.asarray(jax.device_get(flat_f32[i]))
                       for i in self._offload_host_indices}
        opt_params = dict(opt_cfg.params or {})
        self._offload = HostOffloadOptimizer(host_leaves, off_cfg, opt_params,
                                             self.working_dtype,
                                             opt_name=opt_name)

        opt_state = self._tx.init(master_d)
        rep = self.topology.replicated()
        # sharding via the same partitioner logic as the non-offload path,
        # scoped to the device-resident subset
        if self.partitioner.param_specs is None:
            specs_d = None
        else:
            from jax.sharding import PartitionSpec as _P
            flat_specs = jax.tree_util.tree_flatten(
                self.partitioner.param_specs,
                is_leaf=lambda x: x is None or isinstance(x, _P))[0]
            specs_d = {self._flat_keys[i]: flat_specs[i]
                       for i in self._offload_device_indices}
        sub_partitioner = ZeroPartitioner(self.topology, zc, param_specs=specs_d)
        master_d_f32 = {self._flat_keys[i]: flat_f32[i]
                        for i in self._offload_device_indices}
        opt_sh = sub_partitioner.opt_state_sharding(opt_state, master_d_f32)
        opt_state = jax.tree.map(jax.device_put, opt_state, opt_sh)

        grad_acc = tree_zeros_like(params_f32, self.grad_accum_dtype)
        grad_acc = jax.tree.map(jax.device_put, grad_acc, grad_sh)
        self._shardings = dict(params=param_sh, master=self._master_sh_d,
                               grad=grad_sh, opt=opt_sh,
                               use=self.partitioner.use_sharding(params_f32))

        scale = init_loss_scale_state(self.config.fp16) if self.fp16_enabled \
            else LossScaleState(jnp.float32(1.0), jnp.int32(0), jnp.int32(0))
        rng_key = jax.random.PRNGKey(self._rng_seed) if isinstance(self._rng_seed, int) \
            else self._rng_seed
        self.state = TrainState(
            params=working, master=master_d, opt_state=opt_state, grad_acc=grad_acc,
            scale=jax.tree.map(lambda x: jax.device_put(x, rep), scale),
            global_step=jax.device_put(jnp.int32(0), rep),
            skipped=jax.device_put(jnp.int32(0), rep),
            rng=jax.device_put(rng_key, rep))
        n = count_parameters(params_f32)
        log_dist(f"model parameters: {n/1e6:.2f}M (offload={off_cfg.device}, "
                 f"ratio={ratio})", ranks=[0])
        if self._pending_opt_state is not None:
            sd, self._pending_opt_state = self._pending_opt_state, None
            self.optimizer.load_state_dict(sd)

    def _init_state_param_offload(self, params_f32):
        """ZeRO-Infinity parameter tier (zero/param_offload.py): the scan-
        stacked block parameters live on host DRAM or NVMe and stream through
        the compiled step per block; their fp32 masters + moments are host-side
        (CPU Adam). Small non-stacked leaves (embeddings, head, final norm)
        stay device-resident with the normal optax path — the
        ``stage3_param_persistence_threshold`` analog. Mirrors the reference's
        ``AsyncPartitionedParameterSwapper``/``DeepSpeedZeRoOffload`` stack
        (``swap_tensor/partitioned_param_swapper.py:36``,
        ``zero/parameter_offload.py:83``)."""
        from deepspeed_tpu.runtime.zero.param_offload import (BlockParamStore,
                                                              make_streaming_fetch)
        zc = self.config.zero_config
        if self.zero_optimization_stage() < 3:
            raise ValueError("offload_param requires ZeRO stage 3 (reference "
                             "zero/config.py: param offload is a stage-3 feature)")
        if zc.zero_quantized_weights or zc.zero_quantized_gradients:
            # neither tier exists in this mode: working params live host-side
            # (not as int8 device shards) and grads leave via host callbacks
            raise ValueError("zero_quantized_weights/zero_quantized_gradients "
                             "cannot be combined with offload_param")
        mod = self.module
        if not (hasattr(mod, "streaming_plan") and mod.streaming_plan()):
            raise ValueError(
                "offload_param needs a model exposing the streaming protocol "
                "(streaming_plan/streaming_split/streaming_apply, with "
                f"scan_layers=True); {type(mod).__name__} does not")
        opt_cfg = self.config.optimizer
        opt_name = (opt_cfg.type or "adamw").lower()
        if opt_name not in ("adam", "adamw", "adagrad", "lion"):
            raise ValueError(f"offload_param supports adam/adamw/adagrad/lion "
                             f"host steps, got {opt_name!r}")

        resident_f32, stacked_f32 = mod.streaming_split(params_f32)
        stacked_np = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x), np.float32), stacked_f32)
        self._param_store = BlockParamStore(
            stacked_np, zc.offload_param, zc.offload_optimizer,
            dict(opt_cfg.params or {}), self.working_dtype, opt_name=opt_name)
        self._streaming_fetch = make_streaming_fetch(self._param_store)

        # resident leaves: the standard device path, partitioned over the same
        # topology (a dedicated partitioner — specs pattern-match names, so
        # they apply unchanged to the resident subset)
        res_specs = None
        if hasattr(mod, "param_specs"):
            try:
                res_specs = mod.param_specs(resident_f32)
            except Exception:
                res_specs = None
        self._res_partitioner = ZeroPartitioner(self.topology, zc,
                                                param_specs=res_specs)
        working = tree_cast(resident_f32, self.working_dtype)
        param_sh = self._res_partitioner.param_sharding(working)
        master_sh = self._res_partitioner.master_sharding(resident_f32)
        grad_sh = self._res_partitioner.grad_sharding(resident_f32)
        working = jax.tree.map(jax.device_put, working, param_sh)
        if self.mixed_precision:
            master = jax.tree.map(jax.device_put, resident_f32, master_sh)
        else:
            master = None
            working = jax.tree.map(jax.device_put, resident_f32, master_sh)
        opt_target = master if master is not None else working
        opt_state = self._tx.init(opt_target)
        opt_sh = self._res_partitioner.opt_state_sharding(opt_state, resident_f32)
        opt_state = jax.tree.map(jax.device_put, opt_state, opt_sh)
        grad_acc = tree_zeros_like(resident_f32, self.grad_accum_dtype)
        grad_acc = jax.tree.map(jax.device_put, grad_acc, grad_sh)
        self._shardings = dict(params=param_sh, master=master_sh, grad=grad_sh,
                               opt=opt_sh,
                               use=self._res_partitioner.use_sharding(resident_f32))
        rep = self.topology.replicated()
        scale = init_loss_scale_state(self.config.fp16) if self.fp16_enabled \
            else LossScaleState(jnp.float32(1.0), jnp.int32(0), jnp.int32(0))
        rng_key = jax.random.PRNGKey(self._rng_seed) if isinstance(self._rng_seed, int) \
            else self._rng_seed
        self.state = TrainState(
            params=working, master=master, opt_state=opt_state, grad_acc=grad_acc,
            scale=jax.tree.map(lambda x: jax.device_put(x, rep), scale),
            global_step=jax.device_put(jnp.int32(0), rep),
            skipped=jax.device_put(jnp.int32(0), rep),
            rng=jax.device_put(rng_key, rep))
        n = count_parameters(params_f32)
        n_res = count_parameters(resident_f32)
        log_dist(f"model parameters: {n/1e6:.2f}M ({(n-n_res)/1e6:.2f}M streamed "
                 f"from {zc.offload_param_device}, {n_res/1e6:.2f}M resident)",
                 ranks=[0])
        if self._pending_opt_state is not None:
            sd, self._pending_opt_state = self._pending_opt_state, None
            self.optimizer.load_state_dict(sd)

    def _ensure_initialized(self, batch):
        if self.state is not None:
            return
        self.init_params(batch)

    def init_params(self, sample_batch, rng=None):
        """Sharded (partition-at-construction) initialization — the ``zero.Init``
        analog (reference ``zero/partition_parameters.py:783``). The model's
        init is shape-evaluated abstractly, shardings are derived from the
        partitioner, and the real init runs under jit with those out_shardings
        so parameters are born sharded: no device ever holds the full tree."""
        if not (hasattr(self.module, "init")):
            raise ValueError("model_parameters required for non-flax models")
        from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
        from deepspeed_tpu.runtime.zero.sharded_init import (abstract_params,
                                                             materialize_sharded)
        if rng is None:
            rng = jax.random.PRNGKey(
                self._rng_seed if isinstance(self._rng_seed, int) else 0)
        abstract = abstract_params(self.module, sample_batch, rng)
        partitioner = ZeroPartitioner(self.topology, self.config.zero_config,
                                      param_specs=self._resolve_param_specs(abstract))
        params = materialize_sharded(self.module, sample_batch, partitioner, rng,
                                     abstract=abstract)
        self._init_state(params)

    # ------------------------------------------------------------------
    # qwZ working-weight quantization (ZeRO++; ops/quantizer.py)
    # ------------------------------------------------------------------
    @staticmethod
    def _is_qleaf(x):
        return isinstance(x, dict) and "q" in x and "scale" in x

    def _should_quantize(self, leaf):
        return (hasattr(leaf, "ndim") and leaf.ndim >= 2
                and jnp.issubdtype(leaf.dtype, jnp.floating)
                and leaf.size >= self.config.zero_config.stage3_param_persistence_threshold)

    def _quantize_working(self, working):
        from deepspeed_tpu.ops.quantizer import quantize_lastdim

        def q(leaf):
            if self._should_quantize(leaf):
                qv, s = quantize_lastdim(leaf)
                return {"q": qv, "scale": s}
            return leaf

        return jax.tree.map(q, working)

    def _dequantize_working(self, params):
        from deepspeed_tpu.ops.quantizer import dequantize_lastdim
        wd = self.working_dtype

        def dq(leaf):
            if self._is_qleaf(leaf):
                return dequantize_lastdim(leaf["q"], leaf["scale"], dtype=wd)
            return leaf

        return jax.tree.map(dq, params, is_leaf=self._is_qleaf)

    def _qweight_sharding(self, param_sh, working):
        """Sharding tree matching the quantized structure: q inherits the
        leaf's sharding (same shape/layout), scales are replicated (tiny)."""
        rep = self.topology.replicated()

        def sh(leaf, s):
            if self._should_quantize(leaf):
                return {"q": s, "scale": rep}
            return s

        return jax.tree.map(sh, working, param_sh)

    # ------------------------------------------------------------------
    # compiled step functions
    # ------------------------------------------------------------------
    def _loss_closures(self):
        """Shared captures for every grad-computing step (micro and fused)."""
        prescale = self.config.prescale_gradients
        predivide = self.config.gradient_predivide_factor
        fp16 = self.fp16_enabled
        model_fn = self._model_fn
        # PipelineEngine pre-multiplies: its one fused call already averages over
        # the GAS microbatches, so the apply-step's /gas must cancel
        mult = float(getattr(self, "_grad_scale_multiplier", 1.0))

        dq = self._dequantize_working if getattr(self, "quantized_weights", False) \
            else (lambda p: p)
        ptx = self._param_transform
        # ZeRO: params are STORED sharded over the zero axes but USED gathered
        # (model-parallel specs only) — the constraint makes GSPMD emit the
        # per-use all-gather and keeps the storage sharding out of the
        # activation sharding inference (partition.py use_sharding). The same
        # applies to raw gradients at stage >= 2: they are COMPUTED in use
        # sharding and resharded (reduce-scattered) only at the accumulator
        # write, or the grad storage sharding back-propagates through the
        # weight-grad matmuls into activations.
        grad_use_sh = self._shardings.get("use")
        use_sh = grad_use_sh if self.zero_optimization_stage() >= 3 else None

        def make_loss_fn(batch, sub, loss_scale, global_step):
            def loss_fn(p):
                if use_sh is not None:
                    p = constrain_tree(p, use_sh)
                if ptx is not None:
                    # compression transform inside the grad: QAT quant uses
                    # STE, pruning masks the gradient (compression/compress.py)
                    p = ptx(p, global_step)
                loss = model_fn(p, batch, sub, True)
                if isinstance(loss, tuple):
                    loss = loss[0]
                scaled = loss.astype(jnp.float32)
                if mult != 1.0:
                    scaled = scaled * mult
                if fp16:
                    scaled = scaled * loss_scale
                if prescale and predivide != 1.0:
                    scaled = scaled / predivide
                return scaled, loss
            return loss_fn

        return make_loss_fn, dq, grad_use_sh

    def _overlap_streaming_ready(self, plan):
        """Can the overlap schedule's prefetch leg run? Needs the qgZ manual
        path, a model speaking the streaming protocol, and no compression
        transform (ptx operates on the whole param tree, which a block-streamed
        forward never materializes). Bucketized grad reduce works regardless."""
        ov = self.config.overlap_config
        if not (ov.schedule and plan is not None):
            return False
        mod = self.module
        ok = (mod is not None and hasattr(mod, "streaming_plan")
              and hasattr(mod, "streaming_apply") and mod.streaming_plan()
              and self._param_transform is None)
        if not ok:
            logger.warning(
                "overlap.schedule: param prefetch disabled — model lacks the "
                "streaming protocol (streaming_plan/streaming_split/"
                "streaming_apply) or a compression transform is active; the "
                "bucketized grad exchange still applies")
        return bool(ok)

    def _build_micro_step(self):
        grad_sh = self._shardings["grad"]
        accum_dtype = self.grad_accum_dtype
        make_loss_fn, dq, grad_use_sh = self._loss_closures()

        plan = self._qgz_plan
        if plan is not None and self._overlap_streaming_ready(plan):
            return self._build_scheduled_micro_step(plan)
        if plan is not None:
            # qgZ: manual over the ZeRO data axes — per-device local grads
            # accumulated unreduced in the stacked buffer (zero/qgz.py)
            def micro_step(state: TrainState, batch):
                rng, sub = jax.random.split(state.rng)

                def body(params_local, acc_local, batch_local, loss_scale,
                         key, gstep):
                    # distinct dropout/noise per data-parallel replica (the
                    # auto path draws bits over the global batch shape)
                    idx = jnp.int32(0)
                    for a in plan.axes:
                        idx = idx * plan.sizes[a] + jax.lax.axis_index(a)
                    key = jax.random.fold_in(key, idx)
                    p = plan.gather_params(params_local)
                    loss_fn = make_loss_fn(batch_local, key, loss_scale, gstep)
                    (_, loss), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(p)
                    new_acc = jax.tree.map(
                        lambda a, g: a + g.astype(accum_dtype)[None],
                        acc_local, grads)
                    return new_acc, loss.astype(jnp.float32).reshape(1)

                from jax.sharding import PartitionSpec as P
                fn = jax.shard_map(
                    body, mesh=plan.mesh,
                    in_specs=(plan.param_in_specs(state.params),
                              plan.stacked_specs(state.grad_acc, project=True),
                              P(plan.axes), P(), P(), P()),
                    out_specs=(plan.stacked_specs(state.grad_acc, project=True),
                               P(plan.axes)),
                    axis_names=plan.manual, check_vma=False)
                new_acc, losses = fn(state.params, state.grad_acc, batch,
                                     state.scale.loss_scale, sub,
                                     state.global_step)
                # equal per-device micro-batch slices -> global mean
                return state._replace(grad_acc=new_acc, rng=rng), losses.mean()

            return jax.jit(micro_step, donate_argnums=(0,))

        def micro_step(state: TrainState, batch):
            rng, sub = jax.random.split(state.rng)
            loss_fn = make_loss_fn(batch, sub, state.scale.loss_scale,
                                   state.global_step)
            # qwZ: grads are taken w.r.t. the dequantized working weights
            # (XLA gathers the int8 shards, dequantizes at the use site)
            (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                dq(state.params))
            if grad_use_sh is not None:
                grads = constrain_tree(grads, grad_use_sh)
            grads = tree_cast(grads, accum_dtype)
            acc = jax.tree.map(lambda a, g: a + g, state.grad_acc, grads)
            acc = constrain_tree(acc, grad_sh)
            return state._replace(grad_acc=acc, rng=rng), loss

        return jax.jit(micro_step, donate_argnums=(0,))

    def _build_scheduled_micro_step(self, plan):
        """qgZ micro-step under ``overlap.schedule`` (zero/overlap_schedule.py).

        Differences from the unscheduled qgZ body, same math:

        - **Double-buffered prefetch.** Only the resident (non-block) leaves
          are gathered at step entry; each scan block's params are gathered
          per layer via ``plan.gather_block`` inside
          ``streaming_apply(prefetch_depth=D)`` — the scan carry holds the
          next D gathered blocks and each iteration issues block ``i+D``'s
          all-gather before block ``i``'s compute, so XLA's async-collective
          scheduling can hide the exchange under the previous layer's math.
        - **Shadow-input trick.** The stacked accumulator needs FULL-shape
          unreduced local grads, but differentiating through the per-block
          all-gather would make AD transpose it into a full-precision
          psum_scatter during backward — bypassing the quantized boundary
          exchange. So the gathers run on stop-gradient values and each
          fetched block adds a zeros "shadow" slice differentiated instead:
          ``fetch(i) = gather_block(stop_grad(stacked), i) + shadow[i]``.
          d(loss)/d(shadow) is exactly the stacked full-shape local grads.
        """
        accum_dtype = self.grad_accum_dtype
        prescale = self.config.prescale_gradients
        predivide = self.config.gradient_predivide_factor
        fp16 = self.fp16_enabled
        mult = float(getattr(self, "_grad_scale_multiplier", 1.0))
        mod = self.module
        ov = self.config.overlap_config
        depth = max(int(ov.prefetch_depth), 0)
        n_blocks = int(mod.streaming_plan()["num_blocks"])
        use_sh = (self._shardings.get("use")
                  if self.zero_optimization_stage() >= 3 else None)
        use_res = mod.streaming_split(use_sh)[0] if use_sh is not None else None
        resident_specs, stacked_specs = mod.streaming_split(plan.param_specs)
        log_dist(f"overlap.schedule on: prefetch_depth={depth} "
                 f"grad_buckets={int(ov.grad_buckets)} over {n_blocks} blocks",
                 ranks=[0])
        from jax.sharding import PartitionSpec as P

        def micro_step(state: TrainState, batch):
            rng, sub = jax.random.split(state.rng)

            def body(params_local, acc_local, batch_local, loss_scale,
                     key, gstep):
                idx = jnp.int32(0)
                for a in plan.axes:
                    idx = idx * plan.sizes[a] + jax.lax.axis_index(a)
                key = jax.random.fold_in(key, idx)
                resident_local, stacked_local = mod.streaming_split(
                    params_local)
                p_res = plan.gather_params(resident_local,
                                           specs=resident_specs)
                stacked_sg = jax.tree.map(jax.lax.stop_gradient,
                                          stacked_local)

                def full_zeros(x, spec):
                    shape = list(x.shape)
                    if spec is not None:
                        for d, e in enumerate(spec):
                            if e is None or d >= len(shape):
                                continue
                            for a in (e if isinstance(e, tuple) else (e,)):
                                if a in plan.manual:
                                    shape[d] *= plan.sizes[a]
                    return jnp.zeros(shape, x.dtype)

                shadow0 = jax.tree.map(full_zeros, stacked_local,
                                       stacked_specs)

                def loss_fn(args):
                    p_r, shadow = args
                    if use_res is not None:
                        p_r = constrain_tree(p_r, use_res)

                    def fetch(i):
                        blk = plan.gather_block(stacked_sg, stacked_specs, i)
                        return jax.tree.map(
                            lambda b, s: b + jax.lax.dynamic_index_in_dim(
                                s, i, axis=0, keepdims=False), blk, shadow)

                    loss = mod.streaming_apply(p_r, fetch, batch_local,
                                               deterministic=False, rng=key,
                                               prefetch_depth=depth)
                    if isinstance(loss, tuple):
                        loss = loss[0]
                    scaled = loss.astype(jnp.float32)
                    if mult != 1.0:
                        scaled = scaled * mult
                    if fp16:
                        scaled = scaled * loss_scale
                    if prescale and predivide != 1.0:
                        scaled = scaled / predivide
                    return scaled, loss

                (_, loss), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)((p_res, shadow0))
                g_full = mod.streaming_merge(*grads)
                new_acc = jax.tree.map(
                    lambda a, g: a + g.astype(accum_dtype)[None],
                    acc_local, g_full)
                return new_acc, loss.astype(jnp.float32).reshape(1)

            fn = jax.shard_map(
                body, mesh=plan.mesh,
                in_specs=(plan.param_in_specs(state.params),
                          plan.stacked_specs(state.grad_acc, project=True),
                          P(plan.axes), P(), P(), P()),
                out_specs=(plan.stacked_specs(state.grad_acc, project=True),
                           P(plan.axes)),
                axis_names=plan.manual, check_vma=False)
            new_acc, losses = fn(state.params, state.grad_acc, batch,
                                 state.scale.loss_scale, sub,
                                 state.global_step)
            return state._replace(grad_acc=new_acc, rng=rng), losses.mean()

        return jax.jit(micro_step, donate_argnums=(0,))

    def _apply_core_builder(self):
        """Shared optimizer-apply body: mean f32 grads -> new state + stats.
        Used by the standalone apply-step (grads from the accumulator) and
        the fused step (grads straight from backward, never materialized to
        the HBM accumulator)."""
        fp16 = self.fp16_enabled
        clip = self.config.gradient_clipping
        tx = self._tx
        param_sh = self._shardings["params"]
        master_sh = self._shardings["master"]
        working_dtype = self.working_dtype
        mixed = self.mixed_precision
        fp16_cfg = self.config.fp16
        dynamic = self.dynamic_loss_scale
        quantized = getattr(self, "quantized_weights", False)
        quantize_fn = self._quantize_working
        hpz_quant = getattr(self, "_qwz_hpz", False)
        should_q = self._should_quantize

        def hpz_exchange(working):
            """qwZ under hpZ: the primary master->working reshard (the one
            leg that crosses DCN — master is dp x dpr sharded, working only
            dp) moves int8 + scales; the working copy lands full precision so
            every later ICI gather is full precision."""
            from deepspeed_tpu import telemetry
            from deepspeed_tpu.ops.quantizer import (dequantize_lastdim,
                                                     quantize_lastdim)
            logical = wire = 0

            def ex(leaf, s):
                nonlocal logical, wire
                if not should_q(leaf):
                    return jax.lax.with_sharding_constraint(leaf, s)
                q, sc = quantize_lastdim(leaf)
                q = jax.lax.with_sharding_constraint(q, s)  # int8 over DCN
                logical += leaf.size * jnp.dtype(leaf.dtype).itemsize
                wire += q.size + sc.size * 4
                out = dequantize_lastdim(q, sc, dtype=working_dtype)
                return jax.lax.with_sharding_constraint(out, s)

            out = jax.tree.map(ex, working, param_sh)
            if telemetry.enabled():
                telemetry.record_comm("hpz_primary_exchange", int(logical),
                                      0.0, axis="dpr", traced=True,
                                      wire_bytes=int(wire))
            return out

        def core(state: TrainState, grads, lr):
            overflow = has_overflow(grads) if fp16 else jnp.asarray(False)
            safe_grads = jax.tree.map(lambda g: jnp.where(overflow, jnp.zeros_like(g), g), grads)
            norm = global_norm(safe_grads)
            if clip and clip > 0:
                safe_grads, norm = clip_grads_by_global_norm(safe_grads, clip, norm=norm)

            target = state.master if mixed else state.params
            opt_state = set_lr(state.opt_state, lr)
            updates, new_opt = tx.update(safe_grads, opt_state, target)
            new_target = optax.apply_updates(target, updates)
            # fp16 overflow => skip (keep old state) without host sync
            new_target = tree_where(overflow, target, new_target)
            new_opt = tree_where(overflow, opt_state, new_opt)
            new_target = constrain_tree(new_target, master_sh)

            if mixed:
                new_working = tree_cast(new_target, working_dtype)
                if quantized:
                    new_working = quantize_fn(new_working)
                    new_params = jax.tree.map(
                        lambda l, s: jax.lax.with_sharding_constraint(l, s),
                        new_working, param_sh, is_leaf=DeepSpeedEngine._is_qleaf)
                elif hpz_quant:
                    new_params = hpz_exchange(new_working)
                else:
                    new_params = constrain_tree(new_working, param_sh)
                new_master = new_target
            else:
                new_params = new_target
                new_master = None

            new_scale = update_loss_scale(state.scale, overflow, fp16_cfg, dynamic)
            new_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            new_state = TrainState(params=new_params, master=new_master, opt_state=new_opt,
                                   grad_acc=new_acc, scale=new_scale,
                                   global_step=state.global_step + 1,
                                   skipped=state.skipped + overflow.astype(jnp.int32),
                                   rng=state.rng)
            stats = StepStats(grad_norm=norm, overflow=overflow, lr=jnp.asarray(lr, jnp.float32),
                              loss_scale=state.scale.loss_scale)
            return new_state, stats

        return core

    def _grad_denom(self, state, gas):
        denom = jnp.float32(gas)
        if self.fp16_enabled:
            denom = denom * state.scale.loss_scale
        predivide = self.config.gradient_predivide_factor
        if self.config.prescale_gradients and predivide != 1.0:
            denom = denom / jnp.float32(predivide)
        return denom

    def _build_apply_step(self):
        gas = self.gradient_accumulation_steps_value
        plan = self._qgz_plan
        feedback = getattr(self, "_qgz_feedback", False)
        core = self._apply_core_builder()
        # overlap.schedule: split the boundary exchange into byte-balanced
        # bucket chains XLA can pipeline against each other and the backward
        # epilogue (zero/overlap_schedule.py; bit-identical per leaf)
        ov = self.config.overlap_config
        buckets = max(int(ov.grad_buckets), 1) if ov.schedule else 1

        def apply_step(state: TrainState, lr):
            denom = self._grad_denom(state, gas)
            new_res = None
            if plan is not None:
                # qgZ boundary: quantized hierarchical reduction of the stacked
                # local grads (zero/qgz.py). The sum over the world of local
                # batch-means is world x the global mean — fold into the denom.
                if feedback:
                    summed, new_res = plan.reduce(
                        state.grad_acc, residual=state.qgz_residual,
                        return_residual=True, buckets=buckets)
                else:
                    summed = plan.reduce(state.grad_acc, buckets=buckets)
                qdenom = denom * jnp.float32(plan.world)
                grads = jax.tree.map(lambda g: g / qdenom, summed)
            else:
                grads = jax.tree.map(lambda g: g.astype(jnp.float32) / denom,
                                     state.grad_acc)
            new_state, stats = core(state, grads, lr)
            if new_res is not None:
                # overflow-skipped steps discarded the gradients the fresh
                # residual belongs to — keep the previous carry
                new_res = tree_where(stats.overflow, state.qgz_residual,
                                     new_res)
                new_state = new_state._replace(qgz_residual=new_res)
            return new_state, stats

        return jax.jit(apply_step, donate_argnums=(0,))

    def _build_fused_step(self):
        """One jit for grad computation + optimizer apply (``fused_step``
        config, GAS=1 only): gradients flow from backward straight into the
        update without the accumulator's HBM round-trip, and XLA schedules
        the update against the backward epilogue. forward() applies the
        optimizer at the boundary; step() consumes the staged stats."""
        make_loss_fn, dq, grad_use_sh = self._loss_closures()
        core = self._apply_core_builder()

        def fused_step(state: TrainState, batch, lr):
            rng, sub = jax.random.split(state.rng)
            loss_fn = make_loss_fn(batch, sub, state.scale.loss_scale,
                                   state.global_step)
            (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                dq(state.params))
            if grad_use_sh is not None:
                grads = constrain_tree(grads, grad_use_sh)
            denom = self._grad_denom(state, 1)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) / denom, grads)
            new_state, stats = core(state._replace(rng=rng), grads, lr)
            return new_state, loss, stats

        return jax.jit(fused_step, donate_argnums=(0,))

    def _fused_enabled(self):
        return (self.config.fused_step
                and self.gradient_accumulation_steps_value == 1
                and self._qgz_plan is None and self._offload is None
                and self._param_store is None)

    def _fused_gas_enabled(self):
        """Fused whole-window step: available through ``train_batch`` only —
        the imperative forward/backward/step API hands over one micro-batch at
        a time, but ``train_batch`` owns the window and can run it as a single
        compiled scan. The seqlen curriculum reshapes batches per step inside
        ``forward`` — that path must keep per-micro-step dispatch."""
        return (self.config.fused_step
                and self.gradient_accumulation_steps_value > 1
                and self._qgz_plan is None and self._offload is None
                and self._param_store is None
                and self.curriculum_scheduler is None)

    def _build_fused_gas_step(self):
        """One jit for the WHOLE gradient-accumulation window (``fused_step``
        at GAS>1): ``lax.scan`` over the stacked micro-batches accumulates
        grads in the scan carry — XLA aliases the carry buffers in place, so
        accumulation stops round-tripping a separate accumulator through HBM
        between dispatches, and the optimizer apply fuses with the last
        backward. The reference's analog is bucketed comm/compute overlap
        during backward (``zero/stage_1_and_2.py:922``); under XLA the
        scheduler owns overlap once everything is one program."""
        make_loss_fn, dq, grad_use_sh = self._loss_closures()
        core = self._apply_core_builder()
        gas = self.gradient_accumulation_steps_value
        accum_dtype = self.grad_accum_dtype

        def fused_gas_step(state: TrainState, batches, lr):
            rng, sub = jax.random.split(state.rng)

            # STATIC unroll over the window, not lax.scan: gas is small and
            # known at trace time, and an XLA while-loop would carry the
            # params-sized accumulator tree as loop state (copied at every
            # iteration boundary when aliasing fails — measured 1.7x SLOWER
            # than per-micro dispatch on the CPU mesh). Straight-line code
            # lets XLA alias the accumulate in place and fuse freely.
            acc, key, losses = state.grad_acc, sub, []
            for i in range(gas):
                mb = jax.tree.map(lambda x: x[i], batches)
                key, k = jax.random.split(key)
                loss_fn = make_loss_fn(mb, k, state.scale.loss_scale,
                                       state.global_step)
                (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                    dq(state.params))
                if grad_use_sh is not None:
                    grads = constrain_tree(grads, grad_use_sh)
                acc = jax.tree.map(lambda a, g: a + g.astype(accum_dtype),
                                   acc, grads)
                losses.append(loss.astype(jnp.float32))

            denom = self._grad_denom(state, gas)
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) / denom, acc)
            new_state, stats = core(state._replace(rng=rng), grads, lr)
            return new_state, jnp.stack(losses), stats

        return jax.jit(fused_gas_step, donate_argnums=(0,))

    def _shard_stacked_batches(self, batches):
        """Stack ``gas`` micro-batches along a new leading axis and shard:
        axis 0 (the window) replicated, axis 1 (the batch) over dp."""
        stacked = jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]),
                               *batches)
        sharding = self.topology.stacked_batch_sharding()

        def put(x):
            x = jnp.asarray(x)
            try:
                return jax.device_put(x, sharding)
            except Exception:
                return jax.device_put(x, self.topology.replicated())

        return jax.tree.map(put, stacked)

    def _build_eval_step(self):
        model_fn = self._model_fn
        dq = self._dequantize_working if getattr(self, "quantized_weights", False) \
            else (lambda p: p)
        ptx = self._param_transform

        use_sh = self._shardings.get("use") \
            if self.zero_optimization_stage() >= 3 else None

        def eval_step(state: TrainState, batch):
            p = dq(state.params)
            if use_sh is not None:
                p = constrain_tree(p, use_sh)
            if ptx is not None:
                p = ptx(p, state.global_step)
            out = model_fn(p, batch, None, False)
            return out

        return jax.jit(eval_step)

    def set_param_transform(self, fn):
        """Install a pure (params, step) -> params transform applied inside
        the jitted steps (compression QAT/pruning hook). Forces recompile."""
        self._param_transform = fn
        self._micro_step_fn = None
        self._apply_step_fn = None
        self._fused_step_fn = None
        self._fused_gas_step_fn = None
        self._pending_fused_stats = None
        self._eval_step_fn = None

    def _build_offload_fns(self):
        """Compiled pieces of the offloaded apply-step: a grad-stats reduction
        (overflow + global norm, one tiny host sync) and the device-side
        update of the non-offloaded remainder (which also zeroes the grad
        buffer and advances counters/loss scale)."""
        fp16 = self.fp16_enabled
        tx = self._tx
        keys = self._flat_keys
        d_idx = self._offload_device_indices
        master_sh_d = self._master_sh_d
        param_sh = self._shardings["params"]
        working_dtype = self.working_dtype
        fp16_cfg = self.config.fp16
        dynamic = self.dynamic_loss_scale

        def grad_stats(grad_acc):
            g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grad_acc)
            overflow = has_overflow(g32) if fp16 else jnp.asarray(False)
            return overflow, global_norm(g32)

        def device_apply(state: TrainState, lr, inv_scale, overflow):
            flat_g = jax.tree_util.tree_leaves(state.grad_acc)
            grads_d = {keys[i]: flat_g[i].astype(jnp.float32) * inv_scale
                       for i in d_idx}
            opt_state = set_lr(state.opt_state, lr)
            updates, new_opt = tx.update(grads_d, opt_state, state.master)
            new_master = optax.apply_updates(state.master, updates)
            new_master = tree_where(overflow, state.master, new_master)
            new_opt = tree_where(overflow, opt_state, new_opt)
            new_master = constrain_tree(new_master, master_sh_d)

            flat_p, pdef = jax.tree_util.tree_flatten(state.params)
            new_flat_p = list(flat_p)
            for i in d_idx:
                new_flat_p[i] = new_master[keys[i]].astype(working_dtype)
            new_params = constrain_tree(
                jax.tree_util.tree_unflatten(pdef, new_flat_p), param_sh)
            new_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            new_scale = update_loss_scale(state.scale, overflow, fp16_cfg, dynamic)
            return TrainState(params=new_params, master=new_master, opt_state=new_opt,
                              grad_acc=new_acc, scale=new_scale,
                              global_step=state.global_step + 1,
                              skipped=state.skipped + overflow.astype(jnp.int32),
                              rng=state.rng)

        self._offload_stats_fn = jax.jit(grad_stats)
        self._offload_apply_fn = jax.jit(device_apply, donate_argnums=(0,))

    def _offload_step(self, lr):
        """Apply-step under ZeRO-Offload: device handles the retained leaves
        and bookkeeping; the host tier (zero/offload.py) runs CPU Adam over
        the offloaded leaves and streams back the working copy. The device
        program is dispatched *before* the host update so XLA execution and
        host compute/PCIe overlap (the reference's stream overlap analog)."""
        gas = self.gradient_accumulation_steps_value
        overflow_a, raw_norm_a = self._offload_stats_fn(self.state.grad_acc)
        overflow = bool(jax.device_get(overflow_a))
        raw_norm = float(jax.device_get(raw_norm_a))
        scale_before = self.cur_scale  # the scale this step actually ran at
        denom = float(gas)
        if self.fp16_enabled:
            denom *= scale_before
        if self.config.prescale_gradients and self.config.gradient_predivide_factor != 1.0:
            denom /= float(self.config.gradient_predivide_factor)
        norm = raw_norm / denom
        clip = self.config.gradient_clipping
        clip_coef = 1.0
        if clip and clip > 0 and norm > clip:
            clip_coef = clip / (norm + 1e-6)
        inv_scale = clip_coef / denom

        host_grads = None
        if not overflow and self._offload_host_indices:
            flat_g = jax.tree_util.tree_leaves(self.state.grad_acc)
            host_grads = jax.device_get(
                {self._flat_keys[i]: flat_g[i] for i in self._offload_host_indices})
        # dispatch the device-side update first (async), then run host Adam
        new_state = self._offload_apply_fn(self.state, jnp.float32(lr),
                                           jnp.float32(inv_scale),
                                           jnp.asarray(overflow))
        if host_grads is not None:
            new_working = self._offload.step(
                {k: np.asarray(v, dtype=np.float32) for k, v in host_grads.items()},
                lr, inv_scale)
            flat_p, pdef = jax.tree_util.tree_flatten(new_state.params)
            for i in self._offload_host_indices:
                # copy: the host optimizer reuses its output buffers in place
                # next step, and device_put on CPU backends can be zero-copy —
                # params must never alias host memory (see _init_state note)
                leaf = np.array(new_working[self._flat_keys[i]], copy=True)
                flat_p[i] = jax.device_put(leaf, self._flat_param_sh[i])
            new_state = new_state._replace(
                params=jax.tree_util.tree_unflatten(pdef, flat_p))
        self.state = new_state
        return StepStats(grad_norm=jnp.float32(norm), overflow=jnp.asarray(overflow),
                         lr=jnp.float32(lr), loss_scale=jnp.float32(scale_before))

    def _build_param_offload_fns(self):
        """Compiled pieces of the ZeRO-Infinity param-tier step: the streaming
        micro-step (block fetches + host grad writes ride the compiled scan),
        device-side stats over the resident accumulator, the resident apply,
        and a streaming eval step."""
        fp16 = self.fp16_enabled
        mult = float(getattr(self, "_grad_scale_multiplier", 1.0))
        model = self.module
        fetch = self._streaming_fetch
        accum_dtype = self.grad_accum_dtype
        grad_sh = self._shardings["grad"]
        param_sh = self._shardings["params"]
        master_sh = self._shardings["master"]
        use_sh = self._shardings.get("use")
        tx = self._tx
        mixed = self.mixed_precision
        working_dtype = self.working_dtype
        fp16_cfg = self.config.fp16
        dynamic = self.dynamic_loss_scale
        ptx = self._param_transform

        def micro_step(state: TrainState, batch):
            rng, sub = jax.random.split(state.rng)

            def loss_fn(args):
                p, tok = args
                if use_sh is not None:
                    p = constrain_tree(p, use_sh)
                if ptx is not None:
                    p = ptx(p, state.global_step)
                loss = model.streaming_apply(p, lambda i: fetch(i, tok), batch,
                                             deterministic=False, rng=sub)
                if isinstance(loss, tuple):
                    loss = loss[0]
                scaled = loss.astype(jnp.float32)
                if mult != 1.0:
                    scaled = scaled * mult
                if fp16:
                    scaled = scaled * state.scale.loss_scale
                return scaled, loss

            (_, loss), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                (state.params, jnp.zeros((), jnp.float32)))
            gp, _ = grads  # the token cotangent is a dummy
            acc = jax.tree.map(lambda a, g: a + g.astype(accum_dtype),
                               state.grad_acc, gp)
            acc = constrain_tree(acc, grad_sh)
            return state._replace(grad_acc=acc, rng=rng), loss

        def grad_stats(grad_acc):
            g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grad_acc)
            overflow = has_overflow(g32) if fp16 else jnp.asarray(False)
            return overflow, global_norm(g32) ** 2

        def device_apply(state: TrainState, lr, inv_scale, overflow):
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv_scale,
                                 state.grad_acc)
            target = state.master if mixed else state.params
            opt_state = set_lr(state.opt_state, lr)
            updates, new_opt = tx.update(grads, opt_state, target)
            new_target = optax.apply_updates(target, updates)
            new_target = tree_where(overflow, target, new_target)
            new_opt = tree_where(overflow, opt_state, new_opt)
            new_target = constrain_tree(new_target, master_sh)
            if mixed:
                new_params = constrain_tree(tree_cast(new_target, working_dtype),
                                            param_sh)
                new_master = new_target
            else:
                new_params, new_master = new_target, None
            new_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            new_scale = update_loss_scale(state.scale, overflow, fp16_cfg, dynamic)
            return TrainState(params=new_params, master=new_master,
                              opt_state=new_opt, grad_acc=new_acc,
                              scale=new_scale,
                              global_step=state.global_step + 1,
                              skipped=state.skipped + overflow.astype(jnp.int32),
                              rng=state.rng)

        def eval_step(state: TrainState, batch):
            p = state.params
            if use_sh is not None:
                p = constrain_tree(p, use_sh)
            if ptx is not None:
                p = ptx(p, state.global_step)
            return model.streaming_apply(
                p, lambda i: fetch(i, jnp.zeros((), jnp.float32)), batch)

        self._micro_step_fn = jax.jit(micro_step, donate_argnums=(0,))
        self._po_stats_fn = jax.jit(grad_stats)
        self._po_apply_fn = jax.jit(device_apply, donate_argnums=(0,))
        self._eval_step_fn = jax.jit(eval_step)

    def _param_offload_step(self, lr):
        """Apply-step with the ZeRO-Infinity param tier: device applies the
        resident leaves; the host tier (CPU Adam over fp32 masters) consumes
        the grad accumulators the backward callbacks filled, then publishes
        the new working bytes for the next step's fetches. Global grad norm
        and fp16 overflow merge both tiers."""
        gas = self.gradient_accumulation_steps_value
        # join every micro-step's backward grad-write callbacks before
        # reading the host accumulators
        jax.effects_barrier()
        overflow_a, sq_a = self._po_stats_fn(self.state.grad_acc)
        overflow = bool(jax.device_get(overflow_a))
        dev_sq = float(jax.device_get(sq_a))
        host_sq, host_finite = self._param_store.grad_sq_and_finite()
        if self.fp16_enabled and not host_finite:
            overflow = True
        scale_before = self.cur_scale
        denom = float(gas)
        if self.fp16_enabled:
            denom *= scale_before
        if self.config.prescale_gradients and self.config.gradient_predivide_factor != 1.0:
            denom /= float(self.config.gradient_predivide_factor)
        norm = (dev_sq + host_sq) ** 0.5 / denom
        clip = self.config.gradient_clipping
        clip_coef = 1.0
        if clip and clip > 0 and norm > clip:
            clip_coef = clip / (norm + 1e-6)
        inv_scale = clip_coef / denom
        # dispatch the resident device update first (async), then run the
        # host-tier optimizer while the device works
        new_state = self._po_apply_fn(self.state, jnp.float32(lr),
                                      jnp.float32(inv_scale),
                                      jnp.asarray(overflow))
        if overflow:
            self._param_store.zero_grads()
        else:
            self._param_store.step(lr, inv_scale)
        self.state = new_state
        return StepStats(grad_norm=jnp.float32(norm), overflow=jnp.asarray(overflow),
                         lr=jnp.float32(lr), loss_scale=jnp.float32(scale_before))

    def _compiled(self):
        if self._micro_step_fn is None:
            if self._param_store is not None:
                self._build_param_offload_fns()
                self._fused_step_fn = None
                self._apply_step_fn = None
                return
            if self._fused_enabled():
                self._fused_step_fn = self._build_fused_step()
                self._micro_step_fn = self._build_micro_step()  # eval/GAS path
                self._apply_step_fn = self._build_apply_step()
            else:
                self._fused_step_fn = None
                self._micro_step_fn = self._build_micro_step()
                if self._offload is not None:
                    self._build_offload_fns()
                    self._apply_step_fn = None
                else:
                    self._apply_step_fn = self._build_apply_step()
            if self._fused_gas_enabled():
                self._fused_gas_step_fn = self._build_fused_gas_step()
            self._eval_step_fn = self._build_eval_step()
        elif self._apply_step_fn is None and self._offload is None:
            # invalidated (e.g. set_train_batch_size changed the baked-in
            # GAS denominator) — rebuild just the apply step
            self._apply_step_fn = self._build_apply_step()
            if self._fused_gas_enabled():
                self._fused_gas_step_fn = self._build_fused_gas_step()
            if self._fused_enabled():
                self._fused_step_fn = self._build_fused_step()
            else:
                self._fused_step_fn = None

    # ------------------------------------------------------------------
    # public API (reference engine.py:1794/1933/2132)
    # ------------------------------------------------------------------
    def _shard_batch(self, batch):
        sharding = self.topology.batch_sharding()

        def put(x):
            x = jnp.asarray(x)
            try:
                return jax.device_put(x, sharding)
            except Exception:
                return jax.device_put(x, self.topology.replicated())

        return jax.tree.map(put, batch)

    def forward(self, batch):
        """Run the fused forward+backward+accumulate micro-step and commit it.
        Returns the (unscaled) loss.

        Note on semantics vs the reference: eager PyTorch separates forward
        (activations) from backward (grads); one fused XLA program is both
        faster and simpler, so grads are accumulated here and ``backward`` is
        bookkeeping. The state is committed immediately — the old state buffers
        are donated to the compiled step, so holding the previous ``state``
        reference is invalid either way."""
        if self.curriculum_scheduler is not None and \
                self.curriculum_scheduler.curriculum_type == "seqlen":
            # curriculum BEFORE init/compile/profiling so every consumer sees
            # the real step shape. Difficulties are bucketed to powers of two
            # by default: a jitted step recompiles per distinct shape, so raw
            # per-step lengths would mean O(curriculum_steps) XLA compiles —
            # bucketing bounds it at log2(max/min) (set
            # curriculum_learning.tpu_shape_buckets=false for exact lengths).
            from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
                apply_seqlen_curriculum)
            seqlen = self.curriculum_scheduler.update_difficulty(self.global_steps)
            if self.config.curriculum_learning.get("tpu_shape_buckets", True):
                bucket = 1 << max(0, (int(seqlen) - 1).bit_length())
                seqlen = min(bucket, self.curriculum_scheduler.max_difficulty)
            batch = apply_seqlen_curriculum(batch, seqlen)
        self._ensure_initialized(batch)
        self._compiled()
        # flops profiler (reference engine.py:1823 profile-step hook)
        if self.config.flops_profiler_config.enabled:
            if self.flops_profiler is None:
                from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
                self.flops_profiler = FlopsProfiler(self)
            if self.flops_profiler.should_profile(self.global_steps):
                self.flops_profiler.profile_engine_step(batch)
        if self.wall_clock_breakdown:
            self.timers(FORWARD_GLOBAL_TIMER).start()
        self.tput_timer.start()
        from deepspeed_tpu import telemetry
        _span = telemetry.span_begin(FORWARD_GLOBAL_TIMER)
        batch = self._shard_batch(batch)
        if self._guards is not None and self._guards["checkify_on_overflow"]:
            self._last_guard_batch = batch  # for overflow localization
        try:
            if getattr(self, "_fused_step_fn", None) is not None:
                # fused_step config: grads + optimizer apply in ONE jit (GAS=1).
                # The update is applied HERE; step() consumes the staged stats.
                lr = self._schedule_fn(self.global_steps)
                self.state, loss, stats = self._fused_step_fn(self.state, batch, lr)
                self._pending_fused_stats = stats
            else:
                self.state, loss = self._micro_step_fn(self.state, batch)
        except Exception as e:
            telemetry.maybe_oom_postmortem(e)
            raise
        self._staged_loss = loss
        # device-side running mean across the GAS window (reference averages
        # micro-step losses before the train_loss event; no host sync here)
        if self.monitor.enabled:
            if getattr(self, "_loss_accum", None) is None:
                self._loss_accum, self._loss_accum_n = loss, 1
            else:
                self._loss_accum = self._loss_accum + loss
                self._loss_accum_n += 1
        _span.end(token=loss)
        if self.wall_clock_breakdown:
            self.timers(FORWARD_GLOBAL_TIMER).stop(token=loss)
        return loss

    __call__ = forward

    def backward(self, loss=None, retain_graph=False):
        """API-parity shim: gradient computation/reduction already ran fused
        inside ``forward`` (see note there). The ``bwd`` telemetry span
        therefore measures the wait for the in-flight fused program (its
        token sync), not a separate grad pass."""
        assert self._staged_loss is not None, "backward() called before forward()"
        from deepspeed_tpu import telemetry
        with telemetry.span(BACKWARD_GLOBAL_TIMER) as _sp:
            staged_loss = self._staged_loss
            self._staged_loss = None
            _sp.token = staged_loss
        return staged_loss

    def is_gradient_accumulation_boundary(self):
        """reference engine.py:2153 semantics. ``_gas_offset`` rebases the
        window after an elastic ``set_train_batch_size`` resize."""
        rel = self.micro_steps - getattr(self, "_gas_offset", 0)
        return (rel + 1) % self.gradient_accumulation_steps_value == 0

    # --- sparse (embedding) gradient reduction -------------------------
    # reference engine.py:2470-2539: embedding grads travel as (indices,
    # values) pairs. On TPU the in-step reduction is GSPMD-emitted, so the
    # factored exchange is exposed two ways: host-side over SparseTensors
    # (this API, the reference's surface) and in-jit for shard_map grad paths
    # (runtime/comm/sparse_collectives.py).
    def sparse_allreduce_bucket(self, sparse_tensors):
        """Reduce a bucket of per-rank SparseTensors to their summed, deduped
        form (reference ``sparse_allreduce_bucket``)."""
        from deepspeed_tpu.runtime.sparse_tensor import sparse_all_reduce
        return sparse_all_reduce(sparse_tensors)

    def sparse_allreduce(self, sparse_tensor, ids=None, axis_name="dp"):
        """Factored allreduce of one embedding gradient.

        Host path (``SparseTensor``): dedupe via the rendezvous math.
        Device path: ``sparse_tensor`` = stacked per-device local grads
        [world, V, D] (sharded over ``axis_name``), ``ids`` = their token ids
        [world, N]; runs the static-shape factored exchange over the engine
        mesh — ``N x (D+1)`` traffic instead of ``V x D``
        (comm/sparse_collectives). Returns the dense [V, D] sum.
        """
        from deepspeed_tpu.runtime.sparse_tensor import SparseTensor
        if isinstance(sparse_tensor, SparseTensor):
            return sparse_tensor.deduplicate()
        assert ids is not None, "device-path sparse_allreduce needs token ids"
        cache = getattr(self, "_sparse_ar_fns", None)
        if cache is None:
            cache = self._sparse_ar_fns = {}
        fn = cache.get(axis_name)
        if fn is None:
            # built once per axis: jit caches by function identity
            from jax.sharding import PartitionSpec as P
            from deepspeed_tpu.runtime.comm.sparse_collectives import (
                sparse_all_reduce)
            fn = cache[axis_name] = jax.jit(jax.shard_map(
                lambda g, i: sparse_all_reduce(g[0], i[0], axis_name),
                mesh=self.topology.mesh, in_specs=(P(axis_name), P(axis_name)),
                out_specs=P(), check_vma=False))
        return fn(sparse_tensor, ids)

    def _host_fetch(self, value, what):
        """THE accounted device->host fetch. Every blocking d2h transfer the
        engine issues on its own behalf goes through here so the steady-state
        no-sync contract is auditable: ``host_sync_count`` must stay flat
        between ``steps_per_print``/monitor boundaries (enforced by the
        transfer-guard regression test). Do not call jax.device_get / float()
        on device values elsewhere in the train loop."""
        self._host_sync_count += 1
        from deepspeed_tpu import telemetry
        if telemetry.enabled():
            telemetry.count("host_sync", what=what)
        return jax.device_get(value)

    @property
    def host_sync_count(self):
        """Cumulative engine-issued blocking device->host fetches (bench's
        ``extra.host_sync_count``). Steady-state steps contribute zero."""
        return self._host_sync_count

    def step(self):
        """Optimizer step at the gradient-accumulation boundary (engine.py:2132)."""
        self._step_applied = False
        _faults.set_step(self.global_steps)
        _faults.maybe_fail("step.hang")
        try:
            # a whole slice dying mid-step: BEFORE the apply, so the fault
            # can never leave a half-applied optimizer step behind
            _faults.maybe_fail("slice.lost")
        except _faults.InjectedFault as e:
            self._handle_slice_loss(e)
        from deepspeed_tpu import telemetry
        _span = telemetry.span_begin(STEP_GLOBAL_TIMER)
        if self.wall_clock_breakdown:
            self.timers(STEP_GLOBAL_TIMER).start()
        if self.is_gradient_accumulation_boundary():
            old_state = self.state if self._guards is not None else None
            staged = getattr(self, "_pending_fused_stats", None)
            if staged is not None:
                stats = staged  # fused step already applied in forward()
                self._pending_fused_stats = None
                old_state = None  # forward() already replaced the state
            elif self._param_store is not None:
                stats = self._param_offload_step(self._schedule_fn(self.global_steps))
            elif self._offload is not None:
                stats = self._offload_step(self._schedule_fn(self.global_steps))
            else:
                lr = self._schedule_fn(self.global_steps)
                try:
                    self.state, stats = self._apply_step_fn(self.state, lr)
                except Exception as e:
                    telemetry.maybe_oom_postmortem(e)
                    raise
            if self._guards is not None:
                self._run_guards(old_state, stats)
            self._last_stats = stats
            self._step_applied = True
            self.global_steps += 1
            # NOTE: no per-step host sync on overflow — the skipped counter
            # lives in device state and is read lazily (skipped_steps property)
            self.lr_scheduler.step()
            if self.monitor.enabled and self.global_steps % self.config.steps_per_print == 0:
                events = [
                    ("Train/Samples/lr",
                     float(self._host_fetch(stats.lr, "monitor/lr")),
                     self.global_samples),
                    ("Train/Samples/loss_scale",
                     float(self._host_fetch(stats.loss_scale, "monitor/loss_scale")),
                     self.global_samples),
                ]
                if getattr(self, "_loss_accum", None) is not None:
                    # reference engine.py:1961 Train/Samples/train_loss —
                    # the GAS-window mean; fetch only at monitor cadence
                    mean = float(self._host_fetch(self._loss_accum,
                                                  "monitor/train_loss")) / \
                        self._loss_accum_n
                    events.insert(0, ("Train/Samples/train_loss", mean,
                                      self.global_samples))
                if self._telemetry_monitor and telemetry.enabled():
                    events.extend(telemetry.monitor_events(self.global_samples))
                self.monitor.write_events(events)
            self._loss_accum, self._loss_accum_n = None, 0
        self.micro_steps += 1
        self.global_samples += self.micro_batch_size * self.topology.data_parallel_size
        if self.wall_clock_breakdown:
            self.timers(STEP_GLOBAL_TIMER).stop()
        _span.end(token=self._last_stats.loss_scale
                  if (self._step_applied and self._last_stats is not None) else None)
        if self._step_applied and telemetry.enabled():
            # goodput/MFU ledger mark + HBM sample, once per optimizer step
            telemetry.ledger_step(step=self.global_steps)
            telemetry.record_memory("step", step=self.global_steps)
        self.tput_timer.stop(global_step=self._step_applied)
        if self._step_applied and self.global_steps % self.config.steps_per_print == 0:
            log_dist(f"step={self.global_steps}, skipped={self.skipped_steps}, "
                     f"lr={self.get_lr()}, loss_scale={self.cur_scale}", ranks=[0])
        self._resilience_step_boundary()

    def _config_digest(self):
        """Postmortem-bundle collector: a stable digest + key shape facts
        of the user config, enough to tell WHICH config crashed without
        shipping the whole (possibly large) dict."""
        import hashlib
        raw = json.dumps(self.config._param_dict, sort_keys=True,
                         default=str)
        return {"sha256": hashlib.sha256(raw.encode()).hexdigest(),
                "keys": sorted(self.config._param_dict),
                "global_steps": self.global_steps,
                "train_batch_size": getattr(
                    self.config, "train_batch_size", None)}

    def _resilience_step_boundary(self):
        """Post-step resilience hooks (docs/RESILIENCE.md): feed the
        watchdog heartbeat, and honor a pending preemption request — save
        an emergency checkpoint, then exit with the clean-preemption code
        the elastic agent does not count against its restart budget."""
        if self._watchdog is not None:
            self._watchdog.beat()
        pre = self._preemption
        if pre is None or not pre.requested():
            return
        from deepspeed_tpu import telemetry
        cfg = self.config.resilience_config.preemption
        telemetry.record("Fault/preemption", 1, kind="counter",
                         signum=pre.signal_received, step=self.global_steps)
        save_dir = cfg.save_dir or self._last_save_dir
        if save_dir:
            with telemetry.span("recovery/emergency_save",
                                step=self.global_steps):
                path = self.save_checkpoint(save_dir, tag=cfg.tag)
            logger.warning(f"preemption (signal {pre.signal_received}): "
                           f"emergency checkpoint {path}; exiting "
                           f"{cfg.exit_code} (clean preemption)")
        else:
            logger.warning(f"preemption (signal {pre.signal_received}): no "
                           f"save_dir configured or used yet — exiting "
                           f"{cfg.exit_code} WITHOUT an emergency checkpoint")
        telemetry.flush_postmortem(
            "preemption",
            detail=f"signal {pre.signal_received} at step {self.global_steps}",
            exit_code=int(cfg.exit_code))
        raise SystemExit(int(cfg.exit_code))

    def _handle_slice_loss(self, fault):
        """A slice-loss fault (``slice.lost`` / ``comm.partition``) reached
        the step boundary. With ``resilience.elastic.enabled`` the engine
        performs the process-level hand-off: emergency *universal*
        checkpoint (topology-independent, so the relaunched gang can
        reshard it onto the survivors) then ``SystemExit(84)`` — the
        elastic agent's "reshardable slice loss" exit code
        (docs/RESILIENCE.md). Disabled, the fault propagates so an
        in-process ElasticReshardController can catch it and reshard
        without a relaunch."""
        ecfg = self.config.resilience_config.elastic
        if not ecfg.enabled:
            raise fault
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.checkpoint.universal import save_universal_checkpoint
        telemetry.record("Fault/slice_lost", 1, kind="counter",
                         point=fault.point, step=self.global_steps)
        save_dir = ecfg.save_dir or self._last_save_dir
        if save_dir:
            with telemetry.span("recovery/emergency_save",
                                step=self.global_steps):
                path = save_universal_checkpoint(
                    self, save_dir, tag=f"ustep{self.global_steps}")
            logger.warning(
                f"slice loss ({fault.point}): emergency universal "
                f"checkpoint {path}; exiting {ecfg.exit_code} "
                f"(reshardable slice loss)")
        else:
            logger.warning(
                f"slice loss ({fault.point}): no save_dir configured or "
                f"used yet — exiting {ecfg.exit_code} WITHOUT an "
                f"emergency checkpoint")
        telemetry.flush_postmortem(
            "slice_loss",
            detail=f"{fault.point} at step {self.global_steps}",
            exit_code=int(ecfg.exit_code))
        raise SystemExit(int(ecfg.exit_code))

    def _run_guards(self, old_state, stats):
        """Boundary-time correctness guards (runtime/guards.py): donation
        audit, sharding-drift check, retrace detection, and — on overflow —
        checkify-based NaN source localization (the reference's safe-mode
        re-verification, ``stage3.py:1249``)."""
        from deepspeed_tpu.runtime import guards as G
        g = self._guards
        # donation audit: only where XLA actually supports buffer aliasing
        # (CPU backends never donate — every leaf would "fail" the audit)
        if old_state is not None and jax.default_backend() != "cpu":
            G.check_donation(old_state, self.state)
        fns = dict(micro=self._micro_step_fn, apply=self._apply_step_fn,
                   fused=self._fused_step_fn, fused_gas=self._fused_gas_step_fn)
        g["boundaries"] = g.get("boundaries", 0) + 1
        if g["snapshot"] is None:
            g["snapshot"] = G.ShardingSnapshot(self.state)
        elif g["boundaries"] == 2:
            # trace baseline at the SECOND boundary: the first step's outputs
            # feed the second step with settled (non-weak) types, so the one
            # benign warmup retrace never counts as a storm
            g["trace"].record(**fns)
        elif self.global_steps % max(1, g["check_every"]) == 0:
            g["snapshot"].verify(self.state)
            g["trace"].verify(**fns)
        if (g["checkify_on_overflow"]
                and bool(self._host_fetch(stats.overflow, "guards/overflow"))
                and self._last_guard_batch is not None
                and self._param_store is None
                and not getattr(self, "quantized_weights", False)):
            report = G.locate_nonfinite(self._model_fn, self.state.params,
                                        self._last_guard_batch,
                                        rng=self.state.rng)
            if report:
                logger.warning(f"overflow localized (checkify float_checks): "
                               f"{report[:800]}")
            self._last_overflow_report = report

    def train_batch(self, data_iter=None):
        """Full GAS cycle — PipelineEngine-parity API (pipe/engine.py:327).

        With ``fused_step`` at GAS>1 the whole window runs as ONE compiled
        scan over the stacked micro-batches (``_build_fused_gas_step``)."""
        if data_iter is None:
            assert self.training_dataloader is not None
            if self._data_iterator is None:
                from deepspeed_tpu.runtime.dataloader import RepeatingLoader
                self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._data_iterator
        gas = self.gradient_accumulation_steps_value
        if self._fused_gas_enabled():
            rel = self.micro_steps - getattr(self, "_gas_offset", 0)
            if rel % gas != 0:
                raise RuntimeError(
                    "fused train_batch mid-accumulation-window: finish the "
                    "window with forward/backward/step first")
            from deepspeed_tpu import telemetry
            with telemetry.span("dataloader", gas=gas):
                batches = [next(data_iter) for _ in range(gas)]
            self._ensure_initialized(batches[0])
            self._compiled()
            self.tput_timer.start()
            stacked = self._shard_stacked_batches(batches)
            lr = self._schedule_fn(self.global_steps)
            old_state = self.state if self._guards is not None else None
            self.state, losses, stats = self._fused_gas_step_fn(
                self.state, stacked, lr)
            self._last_stats = stats
            self._step_applied = True
            if self._guards is not None:
                self._run_guards(old_state, stats)
            self.micro_steps += gas
            self.global_steps += 1
            self.global_samples += self.micro_batch_size * \
                self.topology.data_parallel_size * gas
            self.lr_scheduler.step()
            mean = losses.mean()
            if self.monitor.enabled and \
                    self.global_steps % self.config.steps_per_print == 0:
                events = [
                    ("Train/Samples/train_loss",
                     float(self._host_fetch(mean, "monitor/train_loss")),
                     self.global_samples),
                    ("Train/Samples/lr",
                     float(self._host_fetch(stats.lr, "monitor/lr")),
                     self.global_samples),
                    ("Train/Samples/loss_scale",
                     float(self._host_fetch(stats.loss_scale,
                                            "monitor/loss_scale")),
                     self.global_samples)]
                if self._telemetry_monitor and telemetry.enabled():
                    events.extend(telemetry.monitor_events(self.global_samples))
                self.monitor.write_events(events)
            self.tput_timer.stop(global_step=True)
            self._resilience_step_boundary()
            # device-resident window mean: train_batch itself never blocks on
            # the result (reference returns the loss tensor, not a float) —
            # the caller decides when/whether to pay the d2h sync
            return mean
        from deepspeed_tpu import telemetry
        losses = []
        for _ in range(gas):
            with telemetry.span("dataloader"):
                batch = next(data_iter)
            loss = self.forward(batch)
            self.backward(loss)
            self.step()
            losses.append(loss)
        # device-side mean: one fused add chain, no per-micro-step d2h sync
        return sum(losses[1:], losses[0]) / len(losses)

    def eval_batch(self, batch):
        self._ensure_initialized(batch)
        self._compiled()
        from deepspeed_tpu import telemetry
        with telemetry.span("eval") as _sp:
            out = self._eval_step_fn(self.state, self._shard_batch(batch))
            _sp.token = out
        return out

    def write_events(self, event_list):
        """Forward (name, value, step) event tuples to the monitor fan-out
        (reference ``engine.py:2273``) — the hook telemetry exporters and
        user code share."""
        self.monitor.write_events(event_list)

    # ------------------------------------------------------------------
    # introspection (reference engine getter surface)
    # ------------------------------------------------------------------
    def zero_optimization_stage(self):
        return self.config.zero_config.stage

    def zero_optimization(self):
        return self.zero_optimization_stage() > 0

    def get_lr(self):
        return [float(self._host_fetch(self._last_stats.lr, "get_lr"))] \
            if self._last_stats is not None \
            else [float(self._schedule_fn(self.global_steps))]

    def get_global_grad_norm(self):
        return float(self._host_fetch(self._last_stats.grad_norm,
                                      "grad_norm")) \
            if self._last_stats is not None else 0.0

    def set_lr(self, lr):
        """Override the learning rate from here on (reference engine
        ``set_lr``): pins the schedule to a constant until changed again."""
        value = float(lr[0] if isinstance(lr, (list, tuple)) else lr)
        self._schedule_fn = lambda step: value
        # keep the scheduler shim's surface consistent with what is applied
        if hasattr(self.lr_scheduler, "schedule_fn"):
            self.lr_scheduler.schedule_fn = self._schedule_fn

    def get_mom(self):
        """reference ``get_mom``: first momentum coefficient (Adam beta1 /
        SGD momentum) from the optimizer config."""
        params = dict(getattr(self.config.optimizer, "params", {}) or {})
        opt_type = str(getattr(self.config.optimizer, "type", "")).lower()
        if "sgd" in opt_type:
            # matches the builder default (ops/adam.py): sgd momentum 0.0
            return [params.get("momentum", 0.0)]
        betas = params.get("betas", (0.9, 0.999))  # adam-family default
        return [list(betas)]

    def set_train_batch_size(self, train_batch_size):
        """Adjust the global batch size by changing gradient-accumulation
        steps; the micro-batch size is untouched (reference engine.py:411 —
        the elasticity resize hook). Only legal at an accumulation boundary
        (a mid-window resize would mis-scale the partial window)."""
        if getattr(self, "_grad_scale_multiplier", 1.0) != 1.0:
            raise NotImplementedError(
                "set_train_batch_size on PipelineEngine: the pipeline "
                "micro-batch count is baked into the compiled schedule")
        rel = self.micro_steps - getattr(self, "_gas_offset", 0)
        if rel % self.gradient_accumulation_steps_value != 0:
            raise RuntimeError(
                "set_train_batch_size mid-accumulation-window: call it only "
                "right after step() completed a window")
        mbs = self.train_micro_batch_size_per_gpu()
        dp = self.topology.data_parallel_size
        if train_batch_size % (mbs * dp) != 0:
            raise ValueError(
                f"train_batch_size {train_batch_size} not divisible by "
                f"micro_batch ({mbs}) x dp ({dp})")
        self.gradient_accumulation_steps_value = train_batch_size // (mbs * dp)
        self.train_batch_size_value = train_batch_size
        self.config.train_batch_size = train_batch_size
        self.config.gradient_accumulation_steps = \
            self.gradient_accumulation_steps_value
        self._gas_offset = self.micro_steps  # rebase the window
        # the fused apply-step bakes the GAS denominator in: invalidate and
        # let _compiled() rebuild lazily (offload keeps its own path; an
        # uninitialized engine has no shardings to build against yet). A
        # staged fused result from a pre-resize forward() is stale — dropping
        # it means that window's step is skipped, never double-applied.
        self._apply_step_fn = None
        self._fused_step_fn = None
        self._fused_gas_step_fn = None  # bakes gas as denominator AND scan length
        self._pending_fused_stats = None

    @property
    def skipped_steps(self):
        """Overflow-skipped optimizer steps (device counter, synced on read)."""
        return int(self._host_fetch(self.state.skipped, "skipped_steps")) \
            if self.state is not None else 0

    @property
    def cur_scale(self):
        return float(self._host_fetch(self.state.scale.loss_scale,
                                      "loss_scale")) \
            if self.state is not None else 1.0

    def loss_scale(self):
        return self.cur_scale

    def was_step_applied(self):
        return self._step_applied

    def train_micro_batch_size_per_gpu(self):
        return self.micro_batch_size

    def train_batch_size(self):
        return self.train_batch_size_value

    def gradient_accumulation_steps(self):
        return self.gradient_accumulation_steps_value

    def get_model_parameters(self, dtype=jnp.float32):
        """Gathered full-precision parameters (analog of
        ``zero_gather_16bit_weights_on_model_save`` / zero_to_fp32)."""
        rep = self.topology.replicated()
        if self._param_store is not None:
            # ZeRO-Infinity param tier: streamed blocks from host masters,
            # resident leaves from device
            src = self.state.master if self.state.master is not None \
                else self.state.params
            resident = jax.tree.map(
                lambda x: np.asarray(jax.device_get(jax.device_put(x, rep)),
                                     dtype=dtype), src)
            stacked = self._param_store.stacked_params(dtype=dtype)
            return self.module.streaming_merge(resident, stacked)
        if self._offload is not None:
            # merge device-resident masters with the host tier
            pdef = jax.tree_util.tree_structure(self.state.params)
            out = []
            for i, k in enumerate(self._flat_keys):
                if k in self.state.master:
                    out.append(np.asarray(jax.device_get(
                        jax.device_put(self.state.master[k], rep)), dtype=dtype))
                else:
                    out.append(self._offload.masters[k].reshape(
                        self._offload.shapes[k]).astype(dtype))
            return jax.tree_util.tree_unflatten(pdef, out)
        src = self.state.master if self.state.master is not None else self.state.params
        return jax.tree.map(lambda x: np.asarray(jax.device_put(x, rep), dtype=dtype), src)

    def _refresh_working_from_master(self):
        """Recompute the working-precision params from the fp32 masters (all
        tiers) — used after external master edits (tensor-fragment sets,
        universal checkpoint load)."""
        if self._param_store is not None:
            if self.state.master is not None:
                working = tree_cast(self.state.master, self.working_dtype)
                working = jax.tree.map(jax.device_put, working,
                                       self._shardings["params"])
                self.state = self.state._replace(params=working)
            self._param_store._publish_from_masters()
        elif self._offload is not None:
            flat_p, pdef = jax.tree_util.tree_flatten(self.state.params)
            for i, k in enumerate(self._flat_keys):
                if k in self.state.master:
                    leaf = self.state.master[k].astype(self.working_dtype)
                else:
                    leaf = jnp.asarray(
                        self._offload.masters[k].reshape(self._offload.shapes[k]),
                        dtype=self.working_dtype)
                flat_p[i] = jax.device_put(leaf, self._flat_param_sh[i])
            self.state = self.state._replace(
                params=jax.tree_util.tree_unflatten(pdef, flat_p))
        elif self.state.master is not None:
            working = tree_cast(self.state.master, self.working_dtype)
            if self.quantized_weights:
                working = jax.jit(self._quantize_working)(working)
            working = jax.tree.map(jax.device_put, working,
                                   self._shardings["params"],
                                   is_leaf=self._is_qleaf)
            self.state = self.state._replace(params=working)
        # pure-fp32: params ARE the masters; nothing to refresh

    # ------------------------------------------------------------------
    # checkpointing (reference engine.py:3056 save / :2712 load)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True,
                        async_save=False):
        """``async_save=True`` uses the background-writer engine (the Nebula
        analog): training resumes after the device->host fetch; call
        ``commit_checkpoints()`` (or the next save/load) to join writes."""
        from deepspeed_tpu import telemetry
        with telemetry.span("ckpt/save", tag=str(tag) if tag else None,
                            async_save=async_save):
            path = self._save_checkpoint(save_dir, tag=tag,
                                         client_state=client_state,
                                         save_latest=save_latest,
                                         async_save=async_save)
        telemetry.record_memory("ckpt/save", step=self.global_steps)
        return path

    def _save_checkpoint(self, save_dir, tag=None, client_state=None,
                         save_latest=True, async_save=False):
        from deepspeed_tpu.runtime.checkpoint_engine.native_engine import (
            AsyncCheckpointEngine, NativeCheckpointEngine, atomic_write_text)
        tag = tag or f"global_step{self.global_steps}"
        self._last_save_dir = save_dir  # emergency-save target on preemption
        if async_save:
            if self._async_ckpt_engine is None:
                self._async_ckpt_engine = AsyncCheckpointEngine()
            engine = self._async_ckpt_engine
        else:
            # a sync save must order after any in-flight async publishes, or a
            # late async worker could move 'latest' back to an older tag
            self.commit_checkpoints()
            engine = NativeCheckpointEngine()
        path = os.path.join(save_dir, str(tag))
        meta = {
            "counters": {
                "global_steps": self.global_steps,
                "global_samples": self.global_samples,
                "micro_steps": self.micro_steps,
                "skipped_steps": self.skipped_steps,
                # accumulation-window rebase after set_train_batch_size —
                # without it a resumed resized engine misaligns boundaries
                "gas_offset": getattr(self, "_gas_offset", 0),
            },
            "lr_scheduler": self.lr_scheduler.state_dict(),
            "client_state": client_state or {},
            "ds_config": self.config._param_dict,
        }
        if async_save:
            # host-tier snapshot and the in-dir/post-publish writes run in the
            # worker: the tag dir only exists after the atomic publish, and
            # 'latest' must not point at an unpublished checkpoint. Deep-copy
            # the blobs — the host tier updates masters/moments in place while
            # the write is in flight.
            offload_blobs = None
            if self._offload is not None:
                offload_blobs = {k: np.array(v, copy=True)
                                 for k, v in self._offload.state_dict().items()}
            param_tier_blobs = None
            if self._param_store is not None:
                param_tier_blobs = {k: np.array(v, copy=True)
                                    for k, v in self._param_store.state_dict().items()}

            def in_dir(p):
                if offload_blobs is not None:
                    np.savez(os.path.join(p, "host_optimizer_states.npz"),
                             **offload_blobs)
                if param_tier_blobs is not None:
                    np.savez(os.path.join(p, "host_param_tier.npz"),
                             **param_tier_blobs)

            def after_publish():
                if save_latest:
                    atomic_write_text(os.path.join(save_dir, "latest"),
                                      str(tag))

            engine.save(self.state, path, meta=meta, extra_writer=in_dir,
                        on_published=after_publish)
            log_dist(f"async checkpoint {path} scheduled", ranks=[0])
            return path

        def in_dir_sync(p):
            # host-tier blobs land inside the tmp dir so the checksum
            # manifest covers them and the publish stays all-or-nothing
            if self._offload is not None:
                self._offload.save(os.path.join(p, "host_optimizer_states.npz"))
            if self._param_store is not None:
                np.savez(os.path.join(p, "host_param_tier.npz"),
                         **self._param_store.state_dict())

        engine.save(self.state, path, meta=meta, extra_writer=in_dir_sync)
        if save_latest:
            atomic_write_text(os.path.join(save_dir, "latest"), str(tag))
        log_dist(f"saved checkpoint {path}", ranks=[0])
        return path

    def commit_checkpoints(self):
        """Join outstanding async checkpoint writes (reference Nebula commit);
        raises if any background write failed."""
        if self._async_ckpt_engine is not None:
            return self._async_ckpt_engine.commit(None)
        return True

    @staticmethod
    def _checkpoint_tags(load_dir):
        """Candidate checkpoint tags in ``load_dir``, newest first.
        Numbered tags (trailing integer, e.g. ``global_step12``) order by
        step and rank above unnumbered ones, which order by mtime.
        Quarantined (``.corrupt``) and in-flight (``.tmp.``/``.old.``)
        directories are never candidates."""
        import re
        out = []
        for name in os.listdir(load_dir):
            p = os.path.join(load_dir, name)
            if not os.path.isdir(p) or ".corrupt" in name \
                    or ".tmp." in name or ".old." in name:
                continue
            if not os.path.exists(os.path.join(p, "meta.json")):
                continue
            m = re.search(r"(\d+)$", name)
            key = (1, int(m.group(1))) if m else (0, os.path.getmtime(p))
            out.append((key, name))
        return [n for _, n in sorted(out, reverse=True)]

    @staticmethod
    def _quarantine(path):
        """Move a corrupt tag aside to ``<tag>.corrupt`` (never deleted —
        it is forensic evidence) so tag listings skip it."""
        dst = f"{path}.corrupt"
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = f"{path}.corrupt.{n}"
        try:
            os.replace(path, dst)
        except OSError:
            return None
        return dst

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True, load_module_only=False):
        """Load a checkpoint; on :class:`CorruptCheckpointError` the corrupt
        tag is quarantined (renamed ``<tag>.corrupt``) and the load falls
        back to the newest prior valid tag automatically
        (docs/RESILIENCE.md recovery matrix)."""
        from deepspeed_tpu import telemetry
        with telemetry.span("ckpt/load", tag=str(tag) if tag else None):
            out = self._load_checkpoint(
                load_dir, tag=tag,
                load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states,
                load_module_only=load_module_only)
        telemetry.record_memory("ckpt/load", step=self.global_steps)
        return out

    def _load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                         load_lr_scheduler_states=True,
                         load_module_only=False):
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.runtime.checkpoint_engine.native_engine import (
            NativeCheckpointEngine, atomic_write_text)
        self.commit_checkpoints()  # never read a tag with writes in flight
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.exists(latest):
                logger.warning(f"no 'latest' file in {load_dir}; nothing loaded")
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        engine = NativeCheckpointEngine()
        assert self.state is not None, "engine state must be initialized before load"
        attempted, _rec_span = [], None
        while True:
            path = os.path.join(load_dir, str(tag))
            try:
                new_state = engine.load(path, template=self.state)
                meta = engine.load_meta(path)
                break
            except CorruptCheckpointError as e:
                if _rec_span is None:  # fault→recovery interval in the trace
                    _rec_span = telemetry.span_begin("recovery/ckpt_fallback")
                attempted.append(str(tag))
                telemetry.record("Fault/ckpt_corrupt", 1, kind="counter",
                                 tag=str(tag), file=e.file or "")
                q = self._quarantine(path) if os.path.isdir(path) else None
                logger.error(f"checkpoint {path} corrupt: {e}"
                             + (f" — quarantined to {q}" if q else ""))
                telemetry.flush_postmortem(
                    "corrupt_ckpt", detail=f"{path}: {e}"[:300],
                    extra={"quarantined": q, "tag": str(tag)})
                candidates = [t for t in self._checkpoint_tags(load_dir)
                              if t not in attempted]
                if not candidates:
                    logger.error(f"no prior valid checkpoint tag left in "
                                 f"{load_dir} (tried {attempted})")
                    raise
                tag = candidates[0]
                logger.warning(f"falling back to checkpoint tag {tag!r}")
        if attempted:
            # repair 'latest' so the NEXT restart goes straight to the tag
            # that actually loads
            atomic_write_text(os.path.join(load_dir, "latest"), str(tag))
            telemetry.record("Recovery/ckpt_fallback", 1, kind="counter",
                             tag=str(tag), skipped=len(attempted))
            _rec_span.end()
        if load_module_only or not load_optimizer_states:
            new_state = self.state._replace(params=new_state.params, master=new_state.master)
        # restore device placement/shardings
        shard_template = self.state
        new_state = jax.tree.map(
            lambda new, old: jax.device_put(jnp.asarray(new), old.sharding)
            if hasattr(old, "sharding") else new,
            new_state, shard_template)
        self.state = new_state
        host_states = os.path.join(path, "host_optimizer_states.npz")
        if self._offload is not None and load_optimizer_states and \
                os.path.exists(host_states):
            self._offload.load(host_states)
        host_params = os.path.join(path, "host_param_tier.npz")
        if self._param_store is not None and os.path.exists(host_params):
            data = np.load(host_params)
            self._param_store.load_state_dict(
                {name: data[name] for name in data.files})
        c = meta.get("counters", {"global_steps": 0, "global_samples": 0,
                                  "micro_steps": 0, "skipped_steps": 0})
        self.global_steps = int(c["global_steps"])
        self.global_samples = int(c["global_samples"])
        self.micro_steps = int(c["micro_steps"])
        self._gas_offset = int(c.get("gas_offset", 0))
        # skipped count travels inside the device state (TrainState.skipped)
        if load_lr_scheduler_states and "lr_scheduler" in meta:
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"loaded checkpoint {path} (step {self.global_steps})", ranks=[0])
        return path, meta.get("client_state", {})

    def save_universal_checkpoint(self, out_dir, tag=None):
        """Universal (topology-independent) checkpoint (checkpoint/universal.py)."""
        from deepspeed_tpu.checkpoint import save_universal_checkpoint
        return save_universal_checkpoint(self, out_dir, tag=tag)

    def load_universal_checkpoint(self, universal_dir, load_optimizer_states=True):
        from deepspeed_tpu.checkpoint import load_universal_checkpoint
        return load_universal_checkpoint(self, universal_dir,
                                         load_optimizer_states=load_optimizer_states)

    def save_16bit_model(self, save_dir, save_filename=None):
        """reference engine ``save_16bit_model`` — gathered half-precision dump.

        For the in-tree model families (llama/mistral/qwen2/gpt2/opt/mixtral)
        this writes a real HF checkpoint (``model.safetensors`` +
        ``config.json``) that ``transformers.from_pretrained`` loads
        (checkpoint/hf.py export). Other models get an honest flax npz
        (``model_weights.npz`` — NOT named like a torch file)."""
        os.makedirs(save_dir, exist_ok=True)
        # fp16 stays 16-bit end to end; bf16 exports fp32 (numpy/safetensors
        # have no native bfloat16 — documented widening, not a silent one)
        dtype = np.float16 if self.fp16_enabled else np.float32
        params = self.get_model_parameters(dtype=dtype)
        cfg = getattr(self.module, "config", None)
        if save_filename is None and cfg is not None:
            from deepspeed_tpu.checkpoint import hf as hf_interop
            try:
                return hf_interop.export_pretrained(params, cfg, save_dir,
                                                    dtype=dtype)
            except hf_interop.UnsupportedModelError:
                pass  # unknown family -> npz fallback (real errors propagate)
        save_filename = save_filename or "model_weights.npz"
        flat = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            flat[jax.tree_util.keystr(path)] = leaf
        np.savez(os.path.join(save_dir, save_filename), **flat)
        return os.path.join(save_dir, save_filename)

    def load_hf_weights(self, model_dir):
        """Load a HuggingFace checkpoint directory into the live engine (the
        ``load_checkpoint(load_module_only=True)`` analog for HF checkpoints;
        reference ``module_inject/replace_module.py:182`` checkpoint path).
        The converted tree replaces params/master in place (shapes must match
        the engine's model)."""
        from deepspeed_tpu.checkpoint import hf as hf_interop
        _, params = hf_interop.load_pretrained(model_dir)
        if self.state is None:
            self._init_state(params)
            return params
        if self._offload is not None:
            raise NotImplementedError("load_hf_weights with offload_optimizer: "
                                      "load before the first step instead")
        if self.state.master is not None:
            master = jax.tree.map(
                lambda cur, new: jax.device_put(
                    jnp.asarray(new, cur.dtype), cur.sharding),
                self.state.master, params)
            self.state = self.state._replace(master=master)
            self._refresh_working_from_master()
        else:
            working = jax.tree.map(
                lambda cur, new: jax.device_put(
                    jnp.asarray(new, cur.dtype), cur.sharding),
                self.state.params, params)
            self.state = self.state._replace(params=working)
        return params
