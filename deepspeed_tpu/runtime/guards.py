"""Trace-level correctness guards — the jit-world analog of the reference's
safe-mode re-verification.

Reference capabilities being replaced (not translated):
- non-static trace detection + invalidation
  (``runtime/zero/partitioned_param_coordinator.py:149-160``): the reference
  records the module fetch order and falls back to a safe path when a later
  iteration diverges. Under jit the equivalent failure is a **recompilation
  storm** — a shape/dtype drifting between steps silently retraces the step
  program every iteration.
- grad-reduction re-verification in safe mode (``stage3.py:1249``).

The jit world adds its own failure classes, each with a guard here:

- **Donation safety** (``check_donation``): every step donates the old state
  buffers. Two silent bug classes: (a) a donated buffer XLA could NOT alias
  (layout/sharding mismatch) degrades to a copy — a 2x-memory perf bug the
  runtime only surfaces as a warning; (b) external code holding a reference to
  a pre-step state leaf reads deleted memory (JAX raises at use, far from the
  cause). The guard reports both right at the step.
- **Sharding drift** (``ShardingSnapshot``): the state's shardings are an
  invariant of the training run. A checkpoint load, tensor-fragment edit, or
  engine-surgery bug that flips a leaf to replicated multiplies memory and
  comm without changing numerics — nothing else would ever notice.
- **Recompilation storm** (``TraceStabilityGuard``): the step functions must
  compile once per config. Cache growth across steps means the input pipeline
  leaks distinct shapes (the curriculum bucketing bug class).
- **NaN source localization** (``locate_nonfinite``): when the loss-scaler
  reports overflow, re-run the window under ``jax.experimental.checkify``
  float checks — the error names the exact primitive and source line that
  produced the first non-finite value, instead of "overflow somewhere".
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def _leaves_with_paths(tree):
    return jax.tree_util.tree_flatten_with_path(tree)[0]


def check_donation(old_state, new_state, where="step"):
    """Post-step donation audit. Returns (undonated, dead_new) path lists.

    ``undonated``: old-state leaves still alive after a donating call — XLA
    fell back to a copy (per-leaf 2x memory; the silent perf bug class).
    ``dead_new``: new-state leaves already deleted — an aliasing bug that will
    crash at first use, reported here at its cause instead.
    """
    undonated, dead_new = [], []
    for path, leaf in _leaves_with_paths(old_state):
        if hasattr(leaf, "is_deleted") and not leaf.is_deleted():
            undonated.append(jax.tree_util.keystr(path))
    for path, leaf in _leaves_with_paths(new_state):
        if hasattr(leaf, "is_deleted") and leaf.is_deleted():
            dead_new.append(jax.tree_util.keystr(path))
    if undonated:
        logger.warning(
            f"donation guard ({where}): {len(undonated)} state buffer(s) were "
            f"NOT donated (XLA copied instead of aliasing): "
            f"{undonated[:5]}{'...' if len(undonated) > 5 else ''}")
    if dead_new:
        raise RuntimeError(
            f"donation guard ({where}): new state contains deleted buffers "
            f"{dead_new[:5]} — an aliasing bug (the donated input leaked into "
            f"the output tree)")
    return undonated, dead_new


class ShardingSnapshot:
    """Captures the state tree's (path → sharding spec, shape, dtype) and
    verifies later states against it (drift detection between steps)."""

    def __init__(self, state):
        self._spec = self._fingerprint(state)

    @staticmethod
    def _fingerprint(state):
        out = {}
        for path, leaf in _leaves_with_paths(state):
            if not hasattr(leaf, "sharding"):
                continue
            sh = leaf.sharding
            spec = str(getattr(sh, "spec", sh))
            out[jax.tree_util.keystr(path)] = (spec, tuple(leaf.shape),
                                               str(leaf.dtype))
        return out

    def verify(self, state, raise_on_drift=False):
        """Compare ``state`` to the snapshot; returns a {path: (was, now)}
        drift report (empty = clean)."""
        now = self._fingerprint(state)
        drift = {}
        for k, v in self._spec.items():
            if k in now and now[k] != v:
                drift[k] = (v, now[k])
        msg = None
        if drift:
            msg = (f"sharding drift on {len(drift)} leaves: " +
                   "; ".join(f"{k}: {was} -> {cur}"
                             for k, (was, cur) in list(drift.items())[:3]))
        if msg and raise_on_drift:
            raise RuntimeError(f"sharding guard: {msg}")
        if msg:
            logger.warning(f"sharding guard: {msg}")
        return drift


class TraceStabilityGuard:
    """Detects recompilation storms: after warmup, the engine's jitted step
    functions must stop accumulating new traces (the reference's non-static
    trace-order check, ``partitioned_param_coordinator.py:149``)."""

    def __init__(self):
        self._baseline = {}

    @staticmethod
    def _cache_size(fn):
        try:
            return fn._cache_size()
        except Exception:
            return None

    def record(self, **fns):
        """Snapshot cache sizes after warmup (first boundary)."""
        for name, fn in fns.items():
            if fn is None:
                continue
            n = self._cache_size(fn)
            if n is not None:
                self._baseline[name] = n

    def verify(self, **fns):
        """Returns {name: (baseline, now)} for fns that retraced since
        ``record`` — each retrace means a new input shape/dtype/sharding
        reached the step (input-pipeline leak; every retrace is a multi-
        second XLA compile on TPU)."""
        grew = {}
        for name, fn in fns.items():
            if fn is None or name not in self._baseline:
                continue
            n = self._cache_size(fn)
            if n is not None and n > self._baseline[name]:
                grew[name] = (self._baseline[name], n)
        if grew:
            logger.warning(
                f"trace guard: step functions retraced since warmup {grew} — "
                f"the input pipeline is feeding varying shapes/dtypes "
                f"(each retrace recompiles on TPU)")
        return grew


def locate_nonfinite(model_fn, params, batch, rng=None):
    """Safe-mode NaN localization: re-run the forward under checkify float
    checks. Returns None when clean, else a string naming the first primitive
    + source line that produced inf/nan (the actionable version of an
    overflow flag)."""
    from jax.experimental import checkify

    def fwd(p, b, key):
        out = model_fn(p, b, key, True)
        return out[0] if isinstance(out, tuple) else out

    try:
        checked = checkify.checkify(fwd, errors=checkify.float_checks)
        if rng is None:
            rng = jax.random.PRNGKey(0)  # models with dropout need a key
        err, _ = jax.jit(checked)(params, batch, rng)
    except Exception as e:
        # a diagnostic must never kill the run it is diagnosing
        return f"(checkify re-run itself failed: {type(e).__name__}: {e})"
    try:
        err.throw()
    except Exception as e:  # checkify.JaxRuntimeError
        return str(e)
    return None


def nonfinite_leaves(tree):
    """Which leaves of a (grad) tree are non-finite — the cheap first half of
    overflow localization, run on the accumulator before re-verification."""
    bad = []
    for path, leaf in _leaves_with_paths(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            if not bool(jax.device_get(jnp.isfinite(leaf).all())):
                bad.append(jax.tree_util.keystr(path))
    return bad
