"""Checkpoint engines.

Mirrors the reference's pluggable ``CheckpointEngine`` interface
(``runtime/checkpoint_engine/checkpoint_engine.py:9``: create/save/load/commit).
``NativeCheckpointEngine`` is the torch-engine analog: it persists an arbitrary
pytree (including engine TrainState) to a directory of .npz shards + a JSON
manifest, gathering sharded arrays to host. Multi-host / async engines slot in
behind the same interface (the Nebula-engine analog).

Crash consistency + integrity (docs/RESILIENCE.md): every save builds the
tag in a ``<path>.tmp.<pid>`` directory, fsyncs, and atomically
``os.replace``s it into place — a crash at ANY instant leaves either the
old complete tag or the new complete tag, never a torn mix. The manifest
carries per-file SHA-256 checksums and the leaf count; ``load`` verifies
them and raises :class:`~deepspeed_tpu.resilience.CorruptCheckpointError`
(instead of bare ``KeyError``/``FileNotFoundError``) so the engine can
quarantine the tag and fall back. Fault points ``ckpt.write`` /
``ckpt.publish`` / ``io.host`` make every crash window drillable on CPU.
"""

import hashlib
import json
import os
import pickle
import shutil
import zipfile

import jax
import numpy as np

from deepspeed_tpu import telemetry
from deepspeed_tpu.resilience import CorruptCheckpointError, InjectedFault, faults
from deepspeed_tpu.utils.retry import retry_call


class CheckpointEngine:
    """reference checkpoint_engine.py:9 interface."""

    def create(self, tag):
        pass

    def save(self, state_dict, path):
        raise NotImplementedError

    def load(self, path, template=None, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        return True


def _flatten(tree):
    flat, treedef = jax.tree_util.tree_flatten(tree)
    return flat, treedef


# ---------------------------------------------------------------------------
# durable host I/O helpers
# ---------------------------------------------------------------------------

def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    """Make a rename/create durable: fsync the containing directory."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without O_RDONLY dirs; rename still atomic
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _host_write(write_fn):
    """Run one host-side checkpoint write through the ``io.host`` fault
    point and the shared retry policy (utils/retry.py) — transient blips
    (NFS/GCS hiccups, injected once-faults) are absorbed; persistent
    failures surface after the retries as RetryError."""
    def attempt():
        faults.maybe_fail("io.host")
        return write_fn()
    return retry_call(attempt, retries=2, base_delay=0.05, max_delay=0.5,
                      retry_on=(OSError, InjectedFault))


def _sha256_file(path, chunk=1 << 20):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def atomic_write_text(path, text):
    """Crash-consistent small-file write (the 'latest' tag pointer): tmp in
    the same directory + fsync + atomic ``os.replace`` + dir fsync, so a
    crash never leaves a truncated/empty file at ``path``."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(d)


def _publish_dir(tmp, path):
    """Atomically swap a fully-written ``tmp`` directory into ``path``.
    Never destroys the existing durable checkpoint before the new one is in
    place: move aside (atomic rename), swap in, reap; restore on failure."""
    faults.maybe_fail("ckpt.publish")
    parent = os.path.dirname(os.path.abspath(path))
    old = None
    if os.path.isdir(path):
        old = f"{path}.old.{os.getpid()}"
        os.replace(path, old)
    try:
        os.replace(tmp, path)
    except Exception:
        if old is not None:
            os.replace(old, path)
        raise
    _fsync_dir(parent)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)
    # black box: publish edges bracket the crash-sensitive window — a
    # postmortem ring that ends between "publish" events names the torn tag
    telemetry.flight_record("ckpt", "ckpt/publish", {"path": path})


class NativeCheckpointEngine(CheckpointEngine):
    """Two buckets: ``state`` (array pytree, loaded against a structure
    template) and ``meta`` (free-form counters/client state, loaded verbatim)."""

    ARRAYS = "arrays.npz"
    META = "meta.json"
    AUX = "aux.pkl"
    FREE = "meta_state.pkl"
    FORMAT_VERSION = 2  # 2 = checksummed manifest; 1 loads unverified

    def save(self, state_dict, path, meta=None, extra_writer=None,
             _publish=True):
        """``extra_writer(dir)`` adds extra in-checkpoint files before the
        manifest is sealed, so they are covered by the checksums and by the
        atomic publish. ``_publish=False`` writes directly into ``path``
        for a caller that owns its own tmp-dir + swap (the async engine's
        worker) — the data is still fsynced and checksummed."""
        if _publish:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            shutil.rmtree(tmp, ignore_errors=True)  # stale crash leftovers
        else:
            tmp = path
        os.makedirs(tmp, exist_ok=True)
        try:
            if meta is not None:
                def _write_free():
                    with open(os.path.join(tmp, self.FREE), "wb") as f:
                        pickle.dump(meta, f)
                _host_write(_write_free)
            flat, treedef = _flatten(state_dict)
            arrays, aux, kinds, dtypes = {}, [], [], []
            for i, leaf in enumerate(flat):
                if isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
                    arr = np.asarray(jax.device_get(leaf))
                    dtypes.append(arr.dtype.name)
                    if arr.dtype.kind == "V" or arr.dtype.name in ("bfloat16",) or \
                            arr.dtype.name.startswith("float8"):
                        # numpy can't round-trip ml_dtypes through savez; store raw bytes
                        arr = arr.view(np.uint8 if arr.dtype.itemsize == 1 else np.uint16)
                    arrays[f"a{i}"] = arr
                    kinds.append("array")
                    aux.append(None)
                else:
                    kinds.append("aux")
                    dtypes.append(None)
                    aux.append(leaf)
            _host_write(
                lambda: np.savez(os.path.join(tmp, self.ARRAYS), **arrays))
            # the crash-mid-save window: shards on disk, manifest not yet
            faults.maybe_fail("ckpt.write")
            def _write_aux():
                with open(os.path.join(tmp, self.AUX), "wb") as f:
                    pickle.dump(aux, f)
            _host_write(_write_aux)
            if extra_writer is not None:
                extra_writer(tmp)
            # seal: checksum every file written so far, then the manifest
            checksums = {name: _sha256_file(os.path.join(tmp, name))
                         for name in sorted(os.listdir(tmp))
                         if os.path.isfile(os.path.join(tmp, name))}
            def _write_meta():
                with open(os.path.join(tmp, self.META), "w") as f:
                    json.dump({"num_leaves": len(flat), "kinds": kinds,
                               "dtypes": dtypes, "checksums": checksums,
                               "format_version": self.FORMAT_VERSION}, f)
            _host_write(_write_meta)
            for name in os.listdir(tmp):
                p = os.path.join(tmp, name)
                if os.path.isfile(p):
                    _fsync_file(p)
            _fsync_dir(tmp)
            if _publish:
                _publish_dir(tmp, path)
        except BaseException:
            if _publish:
                shutil.rmtree(tmp, ignore_errors=True)
            raise

    # -- integrity -------------------------------------------------------
    def _read_manifest(self, path):
        meta_p = os.path.join(path, self.META)
        if not os.path.isdir(path):
            raise CorruptCheckpointError(path,
                                         reason="checkpoint directory missing")
        try:
            with open(meta_p) as f:
                return json.load(f)
        except FileNotFoundError:
            raise CorruptCheckpointError(path, self.META,
                                         "manifest missing") from None
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise CorruptCheckpointError(
                path, self.META, f"manifest unreadable: {e}") from e

    def verify(self, path, meta=None):
        """Checksum + leaf-count verification against the manifest. Raises
        :class:`CorruptCheckpointError` naming the failing file; returns the
        parsed manifest. Format-1 checkpoints (no checksums) pass through
        unverified."""
        meta = meta if meta is not None else self._read_manifest(path)
        if len(meta.get("kinds", [])) != meta.get("num_leaves"):
            raise CorruptCheckpointError(
                path, self.META,
                f"manifest leaf count {meta.get('num_leaves')} != "
                f"{len(meta.get('kinds', []))} recorded kinds")
        for name, want in meta.get("checksums", {}).items():
            p = os.path.join(path, name)
            if not os.path.isfile(p):
                raise CorruptCheckpointError(path, name,
                                             "file missing from checkpoint")
            got = _sha256_file(p)
            if got != want:
                raise CorruptCheckpointError(
                    path, name, f"checksum mismatch (manifest {want[:12]}…, "
                                f"disk {got[:12]}…)")
        return meta

    def load_meta(self, path):
        p = os.path.join(path, self.FREE)
        if not os.path.exists(p):
            return {}
        try:
            with open(p, "rb") as f:
                return pickle.load(f)
        except (pickle.UnpicklingError, EOFError, OSError) as e:
            raise CorruptCheckpointError(
                path, self.FREE, f"client state unreadable: {e}") from e

    def load(self, path, template=None, map_location=None):
        meta = self.verify(path)
        try:
            data = np.load(os.path.join(path, self.ARRAYS),
                           allow_pickle=False)
        except FileNotFoundError:
            raise CorruptCheckpointError(path, self.ARRAYS,
                                         "array shards missing") from None
        except (zipfile.BadZipFile, OSError, ValueError) as e:
            raise CorruptCheckpointError(
                path, self.ARRAYS, f"array shards unreadable "
                f"(truncated write?): {e}") from e
        try:
            with open(os.path.join(path, self.AUX), "rb") as f:
                aux = pickle.load(f)
        except FileNotFoundError:
            raise CorruptCheckpointError(path, self.AUX,
                                         "aux leaves missing") from None
        except (pickle.UnpicklingError, EOFError) as e:
            raise CorruptCheckpointError(
                path, self.AUX, f"aux leaves unreadable: {e}") from e
        import ml_dtypes
        flat = []
        for i, kind in enumerate(meta["kinds"]):
            if kind != "array":
                flat.append(aux[i])
                continue
            try:
                arr = data[f"a{i}"]
            except KeyError:
                raise CorruptCheckpointError(
                    path, self.ARRAYS,
                    f"shard a{i} missing ({meta['num_leaves']} leaves in "
                    f"manifest)") from None
            want = meta.get("dtypes", [None] * len(meta["kinds"]))[i]
            if want is not None and arr.dtype.name != want:
                arr = arr.view(np.dtype(getattr(ml_dtypes, want, want)))
            flat.append(arr)
        assert template is not None, "NativeCheckpointEngine.load needs a structure template"
        _, treedef = _flatten(template)
        assert treedef.num_leaves == len(flat), (
            f"checkpoint has {len(flat)} leaves but template has {treedef.num_leaves} — "
            f"model/optimizer structure changed since save")
        return jax.tree_util.tree_unflatten(treedef, flat)


class AsyncCheckpointEngine(CheckpointEngine):
    """Asynchronous checkpointing (the Nebula-engine analog, reference
    ``nebula_checkpoint_engine.py:107``): ``save`` fetches the (sharded)
    arrays to host synchronously — cheap next to serialization — then a
    background thread does the compress/serialize/write while training
    proceeds; ``commit`` joins outstanding writes and atomically publishes
    the tag. On TPU the device->host fetch is the only part that must be on
    the training thread (it synchronizes the device stream); everything
    after is pure host I/O the step loop need not wait for."""

    def __init__(self, max_inflight=2):
        import itertools
        import threading
        self._threads = []
        self._errors = []
        self._lock = threading.Lock()
        self._max_inflight = max_inflight
        self._inner = NativeCheckpointEngine()
        self._seq = itertools.count()
        self._published_seq = {}  # publish_key -> highest seq whose on_published ran
        self._path_seq = {}       # path -> newest seq scheduled for that path

    def _drain(self, limit):
        alive = []
        for t in self._threads:
            if t.is_alive():
                alive.append(t)
            else:
                t.join()
        self._threads = alive
        while len(self._threads) >= max(limit, 1):
            t = self._threads.pop(0)
            t.join()

    def save(self, state_dict, path, meta=None, extra_writer=None,
             on_published=None, publish_key=None):
        """``extra_writer(tmp_path)`` runs in the worker before the atomic
        publish (extra in-checkpoint files — sealed into the checksum
        manifest); ``on_published()`` runs after it (e.g. updating the
        'latest' tag — never before the data is durable). ``publish_key``
        scopes the out-of-order-completion guard: among saves sharing a key
        (e.g. the same save_dir), only the newest one's ``on_published``
        runs; saves to unrelated targets don't suppress each other.
        Defaults to ``path``'s parent directory."""
        import copy
        import threading
        self._drain(self._max_inflight)
        # device->host fetch on the caller's thread: jax arrays are not
        # guaranteed safe to device_get concurrently with donated updates
        host_state = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, jax.Array) else x, state_dict)
        # snapshot meta too: callers routinely mutate client_state post-save
        meta = copy.deepcopy(meta) if meta is not None else None
        seq = next(self._seq)
        key = publish_key if publish_key is not None else os.path.dirname(path)
        self._path_seq[path] = seq  # caller thread: newest intent for path
        tmp = f"{path}.tmp.{os.getpid()}.{seq}"

        def work():
            old = None
            try:
                # the worker owns tmp-dir atomicity here (_publish=False):
                # data + extras + sealed manifest land in tmp, fsynced
                self._inner.save(host_state, tmp, meta=meta,
                                 extra_writer=extra_writer, _publish=False)
                # the crash window the fault drill kills the writer in:
                # a complete tmp exists but the live tag is untouched
                faults.maybe_fail("ckpt.publish")
                # the swap runs under the lock: (a) workers finishing out of
                # order must not let an OLDER save clobber a newer one's data
                # at the same path; (b) concurrent renames of the same path
                # would interleave. Never destroy the existing durable
                # checkpoint before the new one is in place: move aside
                # (atomic rename), swap in, reap; restore on failure.
                with self._lock:
                    if self._path_seq.get(path, seq) > seq:
                        shutil.rmtree(tmp, ignore_errors=True)
                        return  # superseded by a newer save to this path
                    if os.path.isdir(path):
                        old = f"{path}.old.{os.getpid()}.{seq}"
                        os.replace(path, old)
                    try:
                        os.replace(tmp, path)
                    except Exception:
                        if old is not None:
                            os.replace(old, path)
                            old = None
                        raise
                    _fsync_dir(os.path.dirname(os.path.abspath(path)))
                    # 'latest'-tag callback must never move backwards either
                    publish = seq > self._published_seq.get(key, -1)
                    if publish:
                        self._published_seq[key] = seq
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
                if publish and on_published is not None:
                    on_published()
            except Exception as e:  # surfaced at commit()
                shutil.rmtree(tmp, ignore_errors=True)
                with self._lock:
                    self._errors.append(f"{type(e).__name__}: {e}")

        t = threading.Thread(target=work, daemon=True)
        t.start()
        self._threads.append(t)

    def load(self, path, template=None, map_location=None):
        self.commit(None)  # never read a tag with writes still in flight
        return self._inner.load(path, template=template,
                                map_location=map_location)

    def commit(self, tag):
        for t in self._threads:
            t.join()
        self._threads = []
        with self._lock:
            errors, self._errors = self._errors, []
        if errors:
            raise IOError(f"async checkpoint writes failed: {errors}")
        return True
