"""Checkpoint engines.

Mirrors the reference's pluggable ``CheckpointEngine`` interface
(``runtime/checkpoint_engine/checkpoint_engine.py:9``: create/save/load/commit).
``NativeCheckpointEngine`` is the torch-engine analog: it persists an arbitrary
pytree (including engine TrainState) to a directory of .npz shards + a JSON
manifest, gathering sharded arrays to host. Multi-host / async engines slot in
behind the same interface (the Nebula-engine analog).
"""

import json
import os
import pickle

import jax
import numpy as np


class CheckpointEngine:
    """reference checkpoint_engine.py:9 interface."""

    def create(self, tag):
        pass

    def save(self, state_dict, path):
        raise NotImplementedError

    def load(self, path, template=None, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        return True


def _flatten(tree):
    flat, treedef = jax.tree_util.tree_flatten(tree)
    return flat, treedef


class NativeCheckpointEngine(CheckpointEngine):
    """Two buckets: ``state`` (array pytree, loaded against a structure
    template) and ``meta`` (free-form counters/client state, loaded verbatim)."""

    ARRAYS = "arrays.npz"
    META = "meta.json"
    AUX = "aux.pkl"
    FREE = "meta_state.pkl"

    def save(self, state_dict, path, meta=None):
        os.makedirs(path, exist_ok=True)
        if meta is not None:
            with open(os.path.join(path, self.FREE), "wb") as f:
                pickle.dump(meta, f)
        flat, treedef = _flatten(state_dict)
        arrays, aux, kinds, dtypes = {}, [], [], []
        for i, leaf in enumerate(flat):
            if isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
                arr = np.asarray(jax.device_get(leaf))
                dtypes.append(arr.dtype.name)
                if arr.dtype.kind == "V" or arr.dtype.name in ("bfloat16",) or \
                        arr.dtype.name.startswith("float8"):
                    # numpy can't round-trip ml_dtypes through savez; store raw bytes
                    arr = arr.view(np.uint8 if arr.dtype.itemsize == 1 else np.uint16)
                arrays[f"a{i}"] = arr
                kinds.append("array")
                aux.append(None)
            else:
                kinds.append("aux")
                dtypes.append(None)
                aux.append(leaf)
        np.savez(os.path.join(path, self.ARRAYS), **arrays)
        with open(os.path.join(path, self.AUX), "wb") as f:
            pickle.dump(aux, f)
        with open(os.path.join(path, self.META), "w") as f:
            json.dump({"num_leaves": len(flat), "kinds": kinds, "dtypes": dtypes,
                       "format_version": 1}, f)

    def load_meta(self, path):
        p = os.path.join(path, self.FREE)
        if not os.path.exists(p):
            return {}
        with open(p, "rb") as f:
            return pickle.load(f)

    def load(self, path, template=None, map_location=None):
        with open(os.path.join(path, self.META)) as f:
            meta = json.load(f)
        data = np.load(os.path.join(path, self.ARRAYS), allow_pickle=False)
        with open(os.path.join(path, self.AUX), "rb") as f:
            aux = pickle.load(f)
        import ml_dtypes
        flat = []
        for i, kind in enumerate(meta["kinds"]):
            if kind != "array":
                flat.append(aux[i])
                continue
            arr = data[f"a{i}"]
            want = meta.get("dtypes", [None] * len(meta["kinds"]))[i]
            if want is not None and arr.dtype.name != want:
                arr = arr.view(np.dtype(getattr(ml_dtypes, want, want)))
            flat.append(arr)
        assert template is not None, "NativeCheckpointEngine.load needs a structure template"
        _, treedef = _flatten(template)
        assert treedef.num_leaves == len(flat), (
            f"checkpoint has {len(flat)} leaves but template has {treedef.num_leaves} — "
            f"model/optimizer structure changed since save")
        return jax.tree_util.tree_unflatten(treedef, flat)


class AsyncCheckpointEngine(CheckpointEngine):
    """Asynchronous checkpointing (the Nebula-engine analog, reference
    ``nebula_checkpoint_engine.py:107``): ``save`` fetches the (sharded)
    arrays to host synchronously — cheap next to serialization — then a
    background thread does the compress/serialize/write while training
    proceeds; ``commit`` joins outstanding writes and atomically publishes
    the tag. On TPU the device->host fetch is the only part that must be on
    the training thread (it synchronizes the device stream); everything
    after is pure host I/O the step loop need not wait for."""

    def __init__(self, max_inflight=2):
        import itertools
        import threading
        self._threads = []
        self._errors = []
        self._lock = threading.Lock()
        self._max_inflight = max_inflight
        self._inner = NativeCheckpointEngine()
        self._seq = itertools.count()
        self._published_seq = {}  # publish_key -> highest seq whose on_published ran
        self._path_seq = {}       # path -> newest seq scheduled for that path

    def _drain(self, limit):
        alive = []
        for t in self._threads:
            if t.is_alive():
                alive.append(t)
            else:
                t.join()
        self._threads = alive
        while len(self._threads) >= max(limit, 1):
            t = self._threads.pop(0)
            t.join()

    def save(self, state_dict, path, meta=None, extra_writer=None,
             on_published=None, publish_key=None):
        """``extra_writer(tmp_path)`` runs in the worker before the atomic
        publish (extra in-checkpoint files); ``on_published()`` runs after it
        (e.g. updating the 'latest' tag — never before the data is durable).
        ``publish_key`` scopes the out-of-order-completion guard: among saves
        sharing a key (e.g. the same save_dir), only the newest one's
        ``on_published`` runs; saves to unrelated targets don't suppress each
        other. Defaults to ``path``'s parent directory."""
        import copy
        import threading
        self._drain(self._max_inflight)
        # device->host fetch on the caller's thread: jax arrays are not
        # guaranteed safe to device_get concurrently with donated updates
        host_state = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, jax.Array) else x, state_dict)
        # snapshot meta too: callers routinely mutate client_state post-save
        meta = copy.deepcopy(meta) if meta is not None else None
        seq = next(self._seq)
        key = publish_key if publish_key is not None else os.path.dirname(path)
        self._path_seq[path] = seq  # caller thread: newest intent for path
        tmp = f"{path}.tmp.{os.getpid()}.{seq}"

        def work():
            import shutil
            old = None
            try:
                self._inner.save(host_state, tmp, meta=meta)
                if extra_writer is not None:
                    extra_writer(tmp)
                # the swap runs under the lock: (a) workers finishing out of
                # order must not let an OLDER save clobber a newer one's data
                # at the same path; (b) concurrent renames of the same path
                # would interleave. Never destroy the existing durable
                # checkpoint before the new one is in place: move aside
                # (atomic rename), swap in, reap; restore on failure.
                with self._lock:
                    if self._path_seq.get(path, seq) > seq:
                        shutil.rmtree(tmp, ignore_errors=True)
                        return  # superseded by a newer save to this path
                    if os.path.isdir(path):
                        old = f"{path}.old.{os.getpid()}.{seq}"
                        os.replace(path, old)
                    try:
                        os.replace(tmp, path)
                    except Exception:
                        if old is not None:
                            os.replace(old, path)
                            old = None
                        raise
                    # 'latest'-tag callback must never move backwards either
                    publish = seq > self._published_seq.get(key, -1)
                    if publish:
                        self._published_seq[key] = seq
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
                if publish and on_published is not None:
                    on_published()
            except Exception as e:  # surfaced at commit()
                shutil.rmtree(tmp, ignore_errors=True)
                with self._lock:
                    self._errors.append(f"{type(e).__name__}: {e}")

        t = threading.Thread(target=work, daemon=True)
        t.start()
        self._threads.append(t)

    def load(self, path, template=None, map_location=None):
        self.commit(None)  # never read a tag with writes still in flight
        return self._inner.load(path, template=template,
                                map_location=map_location)

    def commit(self, tag):
        for t in self._threads:
            t.join()
        self._threads = []
        with self._lock:
            errors, self._errors = self._errors, []
        if errors:
            raise IOError(f"async checkpoint writes failed: {errors}")
        return True
