"""Power-iteration eigenvalue estimation (reference ``runtime/eigenvalue.py``).

The reference estimates the largest |eigenvalue| of the loss Hessian w.r.t.
each layer block via power iteration with double-backward; the values drive
compression-aware quantization scheduling. JAX makes the Hessian-vector
product a one-liner (``jvp`` of ``grad``), and the whole iteration jits.
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class Eigenvalue:

    def __init__(self, verbose=False, max_iter=100, tol=1e-2, stability=1e-6,
                 gas_boundary_resolution=1, layer_name="", layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def normalize(self, v):
        norm = jnp.sqrt(sum(jnp.vdot(x, x) for x in jax.tree_util.tree_leaves(v)))
        return jax.tree.map(lambda x: x / (norm + self.stability), v), norm

    def compute_eigenvalue(self, loss_fn, params, rng=None):
        """Largest |eigenvalue| of H = d2 loss / d params2 (per whole tree).

        ``loss_fn(params) -> scalar``. Returns a python float."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        keys = jax.random.split(rng, len(jax.tree_util.tree_leaves(params)))
        flat, treedef = jax.tree_util.tree_flatten(params)
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, x.shape, jnp.float32)
                      for k, x in zip(keys, flat)])
        v, _ = self.normalize(v)

        @jax.jit
        def step(v):
            hv = hvp(v)
            eig = sum(jnp.vdot(a, b) for a, b in
                      zip(jax.tree_util.tree_leaves(v),
                          jax.tree_util.tree_leaves(hv)))
            return hv, eig

        prev = 0.0
        eig = 0.0
        for i in range(self.max_iter):
            hv, eig_j = step(v)
            eig = float(jax.device_get(eig_j))
            v, norm = self.normalize(hv)
            if abs(eig - prev) <= self.tol * max(abs(eig), 1e-12):
                break
            prev = eig
        if self.verbose:
            logger.info(f"eigenvalue converged in {i+1} iters: {eig:.4e}")
        return abs(eig)

    def compute_layer_eigenvalues(self, loss_fn, params):
        """Per-top-level-block eigenvalues (the reference's per-layer values):
        holds all other blocks fixed."""
        out = {}
        for key in params:
            def block_loss(block, key=key):
                merged = dict(params)
                merged[key] = block
                return loss_fn(merged)

            out[key] = self.compute_eigenvalue(block_loss, params[key])
        return out
