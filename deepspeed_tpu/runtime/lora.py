"""LoRA — low-rank adapters with fuse/unfuse for the hybrid (RLHF) engine.

Reference: the hybrid engine's LoRA handling
(``runtime/hybrid_engine.py:126-173``: ``fuse_lora_weight`` /
``unfuse_lora_weight`` around each generate, so rollout reads merged weights
while training updates only the adapters).

TPU design: adapters are a separate pytree mirroring the selected kernel
leaves. "Fusing" is a jitted functional merge ``W + (alpha/r) * A @ B``
producing the generation-time view — no in-place mutation, no unfuse
needed for correctness (the training params are never touched); explicit
``fuse``/``unfuse`` are still provided for checkpoint-export parity with the
reference.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np


DEFAULT_TARGETS = r"(q_proj|k_proj|v_proj|o_proj|gate_proj|up_proj|down_proj|c_attn|c_proj|c_fc)$"


def _iter_kernels(params, targets):
    """Yield (path tuple, leaf) for 2D kernels whose parent module matches."""
    pat = re.compile(targets)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        keys = tuple(getattr(p, "key", getattr(p, "idx", None)) for p in path)
        if keys and keys[-1] == "kernel" and hasattr(leaf, "ndim") \
                and leaf.ndim == 2 and len(keys) >= 2 \
                and pat.search(str(keys[-2])):
            yield keys, leaf


def init_lora(params, rank=8, alpha=16.0, targets=DEFAULT_TARGETS, rng=None,
              dtype=jnp.float32):
    """Build the adapter pytree: {"/".join(path): {"a": [in, r], "b": [r, out]}}.

    ``a`` is gaussian, ``b`` zeros (standard LoRA init: the merged delta
    starts at exactly zero). ``alpha/rank`` is the merge scaling."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    adapters = {}
    for keys, leaf in _iter_kernels(params, targets):
        d_in, d_out = leaf.shape
        rng, sub = jax.random.split(rng)
        adapters["/".join(map(str, keys))] = {
            "a": jax.random.normal(sub, (d_in, rank), dtype) / np.sqrt(d_in),
            "b": jnp.zeros((rank, d_out), dtype),
        }
    return {"adapters": adapters, "scaling": float(alpha) / float(rank)}


def _merge_one(leaf, ab, scaling, sign=1.0):
    delta = (ab["a"].astype(jnp.float32) @ ab["b"].astype(jnp.float32))
    return (leaf.astype(jnp.float32) + sign * scaling * delta).astype(leaf.dtype)


def _map_targets(params, lora, fn):
    adapters, scaling = lora["adapters"], lora["scaling"]

    def walk(tree, prefix):
        if isinstance(tree, dict):
            return {k: walk(v, prefix + (str(k),)) for k, v in tree.items()}
        key = "/".join(prefix)
        return fn(tree, adapters[key], scaling) if key in adapters else tree

    return walk(params, ())


def fuse_lora(params, lora):
    """W <- W + (alpha/r) A@B on every adapted leaf (reference
    ``fuse_lora_weight``); returns a new pytree."""
    return _map_targets(params, lora, lambda w, ab, s: _merge_one(w, ab, s, 1.0))


def unfuse_lora(params, lora):
    """Inverse of :func:`fuse_lora` (reference ``unfuse_lora_weight``)."""
    return _map_targets(params, lora,
                        lambda w, ab, s: _merge_one(w, ab, s, -1.0))


def merged_view(params, lora):
    """Jit-friendly merged view for generation — same math as fuse_lora but
    intended to be traced inside the decode program (XLA fuses the low-rank
    delta into the weight load; training params remain untouched)."""
    return fuse_lora(params, lora)


def trainable_filter(lora):
    """Set of adapted leaf paths — used to freeze base weights when doing
    adapter-only training (optax.masked-style masks)."""
    return set(lora["adapters"].keys())
