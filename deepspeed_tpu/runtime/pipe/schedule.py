"""Pipeline schedules.

Mirrors reference ``deepspeed/runtime/pipe/schedule.py``: ``TrainSchedule``
(:189) / ``InferenceSchedule`` yield per-clock instruction lists
(LoadMicroBatch/ForwardPass/SendActivation/...). On TPU the schedule is not
*executed* instruction-by-instruction — the collective pipeline in
``pipe/engine.py`` compiles the whole rotation into one XLA program and
autodiff produces the reverse schedule — but the instruction stream is kept
for parity, introspection and tick math (bubble accounting, tests).
"""


class PipeInstruction:
    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


class PipeSchedule:
    """reference schedule.py PipeSchedule base."""

    def __init__(self, micro_batches, stages, stage_id):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @property
    def num_micro_batches(self):
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def _valid_micro_batch(self, micro_batch_id):
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id):
        return 0 <= stage_id < self.stages

    def num_pipe_buffers(self):
        return self.micro_batches

    def steps(self):
        raise NotImplementedError

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference schedule.py InferenceSchedule)."""

    def steps(self):
        total = self.micro_batches + self.stages - 1
        for t in range(total):
            cmds = []
            mb = t - self.stage_id
            if self._valid_micro_batch(mb):
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=mb % self.num_pipe_buffers()))
                else:
                    cmds.append(RecvActivation(buffer_id=mb % self.num_pipe_buffers()))
                cmds.append(ForwardPass(buffer_id=mb % self.num_pipe_buffers()))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=mb % self.num_pipe_buffers()))
            yield cmds


class TrainSchedule(PipeSchedule):
    """1F1B schedule description (reference schedule.py:189). Yields the
    interleaved forward/backward instruction stream per clock tick."""

    def steps(self):
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds = []
            # communication (reference ordering: recv before compute)
            if self._valid_micro_batch(micro_batch_id):
                if is_forward:
                    if self.is_first_stage:
                        cmds.append(LoadMicroBatch(buffer_id=self._buffer_idx(micro_batch_id)))
                    else:
                        cmds.append(RecvActivation(buffer_id=self._buffer_idx(micro_batch_id)))
                    cmds.append(ForwardPass(buffer_id=self._buffer_idx(micro_batch_id)))
                    if not self.is_last_stage:
                        cmds.append(SendActivation(buffer_id=self._buffer_idx(micro_batch_id)))
                else:
                    if not self.is_last_stage:
                        cmds.append(RecvGrad(buffer_id=self._buffer_idx(micro_batch_id)))
                    cmds.append(BackwardPass(buffer_id=self._buffer_idx(micro_batch_id)))
                    if not self.is_first_stage:
                        cmds.append(SendGrad(buffer_id=self._buffer_idx(micro_batch_id)))
            # boundary step
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds

    def _step_to_micro_batch(self, step_id):
        # reference TrainSchedule._step_to_micro_batch: even ticks forward,
        # odd ticks backward, offset by stage
        if _is_even(step_id) and _is_even(self.stage_id):
            return self._even_step_forward_id(step_id), True
        if _is_odd(step_id) and _is_odd(self.stage_id):
            return self._odd_step_forward_id(step_id), True
        if _is_even(step_id) and _is_odd(self.stage_id):
            return self._even_step_backward_id(step_id), False
        return self._odd_step_backward_id(step_id), False

    def _even_step_forward_id(self, step_id):
        return step_id // 2 - self.stage_id // 2

    def _odd_step_forward_id(self, step_id):
        return (step_id - 1) // 2 - self.stage_id // 2

    def _even_step_backward_id(self, step_id):
        return step_id // 2 - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id):
        return (step_id - 1) // 2 - self.stages + 1 + self.stage_id // 2

    def _buffer_idx(self, micro_batch_id):
        return micro_batch_id % self.num_pipe_buffers()

    def num_pipe_buffers(self):
        # reference: min(stages - stage_id, micro_batches), >= 2
        buffers = min(self.stages - self.stage_id, self.micro_batches)
        return max(2, buffers)


def _is_even(x):
    return x % 2 == 0


def _is_odd(x):
    return x % 2 != 0
