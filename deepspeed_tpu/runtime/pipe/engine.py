"""Pipeline-parallel engine.

Mirrors the reference ``PipelineEngine`` (``runtime/pipe/engine.py:327``
``train_batch`` / :416 ``eval_batch``) — but where the reference interprets a
1F1B instruction stream with explicit p2p sends (``pipe/p2p.py:46,67``), the
TPU engine compiles the entire pipeline rotation into ONE XLA program:

- stage s holds block parameters [L/S, ...] (leading stacked-layer axis sharded
  over the ``pp`` mesh axis)
- each clock tick every stage applies its blocks to its current microbatch and
  the activations rotate to the next stage via ``lax.ppermute`` on ICI
- fill/drain bubbles are masked compute (SPMD requires uniform programs)
- JAX autodiff of the scan-of-ppermute program IS the backward schedule: the
  transpose of ppermute is the reverse rotation, so backward pipelining comes
  for free, and ``jax.checkpoint`` on the block gives the standard
  activation-recompute memory profile

Embed/head (first/last-stage-only roles in the reference) run under plain
GSPMD outside the rotation.
"""

from typing import Any

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.utils.logging import log_dist


def pipeline_ticks(num_micro, num_stages, virtual_stages=1):
    """Clock length of the compiled rotation. V=1: M+S-1. V>1: microbatches
    feed in groups of S, each activation circles the ring V times; the clock
    ends when the LAST job retires — (M-1)//S full group windows, then the
    final job's last pass entry (V-1)*S + (M-1)%S, then its S-tick traversal
    (= M*V + S - 1 when S | M; shorter for a partial final group, where the
    naive ceil formula would run extra full-compute ticks on masked data)."""
    M, S, V = num_micro, num_stages, virtual_stages
    if V == 1:
        return M + S - 1
    return ((M - 1) // S) * S * V + (V - 1) * S + (M - 1) % S + S


def ideal_bubble_fraction(num_micro, num_stages, virtual_stages=1):
    """Idle fraction of the schedule. Each stage performs M*V useful
    chunk-works over ``pipeline_ticks`` ticks, so the bubble is
    1 - M*V/ticks. V=1 reduces to the classic (S-1)/(M+S-1); interleaving V
    chunks per device shrinks it toward (S-1)/(M*V) (reference interleaved
    ``TrainSchedule``, ``runtime/pipe/schedule.py:189``)."""
    M, S, V = num_micro, num_stages, virtual_stages
    return 1.0 - (M * V) / pipeline_ticks(M, S, V)


def interleaved_schedule(num_micro, num_stages, virtual_stages):
    """Static per-tick schedule table for the grouped interleaved rotation.

    Jobs are (microbatch m, pass v); stage s processes chunk (s, v) — layers
    [(v*S+s)*K', ...). Microbatches enter in groups of S: job (m, v) enters
    stage 0 at tick (m//S)*S*V + v*S + (m%S). Within a group window of S*V
    ticks the first S ticks feed NEW microbatches; on every other tick slot 0
    receives the wrap-around from stage S-1 (pass v -> v+1). The job leaving
    stage S-1 on a feed tick is always at v=V-1 (it retires), so feeds and
    wrap-arounds never compete — see the validity test
    (tests/test_pipeline_interleaved.py) which simulates the ring.

    Returns numpy arrays over ticks T = pipeline_ticks(M, S, V):
      feed [T] bool, feed_idx [T] i32   — slot-0 NEW-microbatch feeds
      retire [T] bool, retire_idx [T] i32 — out[S-1] finished microbatches
      vpass [T, S] i32                  — which pass each stage is on
    """
    M, S, V = num_micro, num_stages, virtual_stages
    T = pipeline_ticks(M, S, V)
    t = np.arange(T)
    if V == 1:
        feed_idx = np.clip(t, 0, M - 1)
        feed = t < M
        retire_idx = np.clip(t - (S - 1), 0, M - 1)
        retire = t - (S - 1) >= 0
        vpass = np.zeros((T, S), np.int32)
    else:
        g, r = t // (S * V), t % (S * V)
        feed_idx = np.clip(g * S + r, 0, M - 1)
        feed = (r < S) & (g * S + r < M)
        # job leaving stage S-1 at tick t entered slot 0 at e = t-(S-1)
        e = t - (S - 1)
        ge, re = e // (S * V), e % (S * V)
        ve, ie = re // S, re % S
        m_e = ge * S + ie
        retire = (e >= 0) & (ve == V - 1) & (m_e < M)
        retire_idx = np.clip(m_e, 0, M - 1)
        # stage s at tick t runs the job that entered at e_s = t - s
        es = t[:, None] - np.arange(S)[None, :]
        vpass = ((np.maximum(es, 0) % (S * V)) // S).astype(np.int32)
    return {"feed": feed, "feed_idx": feed_idx.astype(np.int32),
            "retire": retire, "retire_idx": retire_idx.astype(np.int32),
            "vpass": vpass}


def collective_pipeline(block_apply, blocks_params, x_micro, mesh, *,
                        num_stages, remat=True, pp_axis="pp", extra=None,
                        num_layers=None, virtual_stages=1):
    """Run M microbatches through the rotated block pipeline — pure GSPMD form.

    block_apply: (params_one_layer, x, extra) -> x
    blocks_params: stacked [L, ...] pytree (L = num_layers), pp-sharded on axis 0
    x_micro: [M, ...activation shape] (dp/sp shardings compose automatically)
    virtual_stages: V>1 = interleaved schedule (reference ``TrainSchedule``,
        ``runtime/pipe/schedule.py:189``): each device holds V non-contiguous
        layer chunks and every activation circles the ring V times with 1/V
        the per-tick compute, shrinking the fill/drain bubble from
        (S-1)/(M+S-1) toward (S-1)/(M*V) at the cost of V× more rotations.
    Returns: [M, ...] outputs after all L layers.

    Mechanics: activations live in a stage-stacked buffer [S, ...] whose leading
    axis is sharded over ``pp``; per-tick compute is ``vmap`` over that axis (so
    each device runs only its stage — the layer chunks differ only in the
    pp-sharded parameter slice) and the stage hand-off is ``jnp.roll`` on the
    sharded axis, which XLA lowers to a collective-permute over ICI. No manual
    region is needed, so tp/sp GSPMD inside the block composes untouched, and
    autodiff of the scan yields the reverse-rotation backward schedule.
    The schedule itself (feed/retire/pass indices) is a trace-time numpy
    table (``interleaved_schedule``) threaded through the scan as constants.
    """
    body = jax.checkpoint(block_apply) if remat else block_apply
    S = num_stages
    V = virtual_stages
    M = x_micro.shape[0]

    # non-uniform partitioning: the stored stack is padded to a multiple of
    # S*V (PipelineModule.init_params) so the pp sharding divides evenly;
    # padded slots are masked no-ops here. With a homogeneous interior,
    # balanced partitioning (reference partition_method="parameters") ==
    # uniform slots.
    total = jax.tree.leaves(blocks_params)[0].shape[0]
    assert total % (S * V) == 0, (
        f"padded layer stack {total} must divide stages*virtual {S}*{V}")
    K = total // (S * V)          # layers per chunk
    L = num_layers if num_layers is not None else total

    if V == 1:
        valid = (jnp.arange(S * K) < L).reshape(S, 1, K)
        blocks = jax.tree.map(
            lambda a: a.reshape((S, 1, K) + a.shape[1:]), blocks_params)
    else:
        # chunk (s, v) holds layers [(v*S+s)*K, (v*S+s+1)*K): device s's
        # chunks are STRIDED in layer order, so permute the stacked axis at
        # trace time (static indices; XLA reshards once per step, amortized
        # over the V*M rotation ticks)
        perm = ((np.arange(V)[None, :, None] * S +
                 np.arange(S)[:, None, None]) * K +
                np.arange(K)[None, None, :])          # [S, V, K]
        valid = jnp.asarray(perm < L)
        blocks = jax.tree.map(
            lambda a: jnp.take(a, perm.reshape(-1), axis=0).reshape(
                (S, V, K) + a.shape[1:]), blocks_params)
    blocks = jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(
            a, jax.NamedSharding(mesh, P(pp_axis))), blocks)

    sched = interleaved_schedule(M, S, V)

    def apply_stage(stage_blocks, stage_valid, v, x):
        # [V, K, ...] chunk stack; this tick runs pass v's K layers
        chunk = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
            (stage_blocks, stage_valid))
        cb, cv = chunk

        def layer(h, pv):
            p, vv = pv
            out = body(p, h, extra)
            # padded slot -> identity (out from zero params stays finite for
            # standard blocks, so the where-grad is clean)
            return jnp.where(vv, out, h), None
        out, _ = lax.scan(layer, x, (cb, cv))
        return out

    stage_vmap = jax.vmap(apply_stage, in_axes=(0, 0, 0, 0), out_axes=0)
    buf_spec = P(pp_axis)

    def tick(carry, xs):
        buf, outputs = carry  # buf: [S, ...] pp-sharded
        feed_on, feed_idx, retire_on, retire_idx, vpass = xs
        feed = lax.dynamic_index_in_dim(x_micro, feed_idx, 0, keepdims=False)
        # non-feed ticks keep the wrap-around (pass v -> v+1) that jnp.roll
        # already placed in slot 0; V=1 always feeds (or zeros in the drain)
        slot0 = jnp.where(feed_on, feed,
                          buf[0] if V > 1 else jnp.zeros_like(feed))
        buf = buf.at[0].set(slot0)
        out = stage_vmap(blocks, valid, vpass, buf)
        out = jax.lax.with_sharding_constraint(
            out, jax.NamedSharding(mesh, buf_spec))
        cur = lax.dynamic_index_in_dim(outputs, retire_idx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(retire_on, out[S - 1], cur), retire_idx, 0)
        # rotate stages: s -> s+1 (slot 0 is fed or wrapped next tick)
        buf = jnp.roll(out, 1, axis=0)
        return (buf, outputs), None

    init_buf = jnp.zeros((S,) + x_micro.shape[1:], x_micro.dtype)
    init_buf = jax.device_put(init_buf, jax.NamedSharding(mesh, buf_spec)) \
        if not isinstance(init_buf, jax.core.Tracer) else init_buf
    init_out = jnp.zeros_like(x_micro)
    xs = (jnp.asarray(sched["feed"]), jnp.asarray(sched["feed_idx"]),
          jnp.asarray(sched["retire"]), jnp.asarray(sched["retire_idx"]),
          jnp.asarray(sched["vpass"]))
    (_, outputs), _ = lax.scan(tick, (init_buf, init_out), xs)
    return outputs


class PipelineEngine(DeepSpeedEngine):
    """Engine over a ``PipelineModule``. ``train_batch`` consumes
    ``gradient_accumulation_steps`` microbatches per optimizer step, exactly as
    the reference (micro_batches == gas, pipe/engine.py:55)."""

    def __init__(self, config=None, model=None, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a deepspeed_tpu PipelineModule"
        self.pipe_module = model
        super().__init__(config=config, model=model, **kwargs)
        if self.pipe_module.num_stages is None:
            self.pipe_module.num_stages = self.topology.pp_size
        assert self.topology.pp_size == self.pipe_module.num_stages, (
            f"mesh pp={self.topology.pp_size} != module stages "
            f"{self.pipe_module.num_stages}")
        self.micro_batches = self.gradient_accumulation_steps_value
        # grads of the mean-over-all-microbatches loss are already the GAS mean;
        # pre-multiply so the apply-step's /gas cancels
        self._grad_scale_multiplier = float(self.gradient_accumulation_steps_value)

    def _normalize_model_fn(self, model):
        pipe = model

        def model_fn(params, batch, rng, training=True):
            M = self.micro_batches
            micro = jax.tree.map(
                lambda x: x.reshape((M, x.shape[0] // M) + x.shape[1:]), batch)
            embed = jax.vmap(lambda b: pipe.embed.apply({"params": params["embed"]}, b))(micro) \
                if pipe.embed else micro

            def block_apply(p, x, extra):
                return pipe.block.apply({"params": p}, x, *pipe.block_args)

            outs = collective_pipeline(
                block_apply, params["blocks"], embed, self.mesh,
                num_stages=self.topology.pp_size,
                remat=self.config.activation_checkpointing.policy != "nothing",
                num_layers=pipe.num_layers,
                virtual_stages=pipe.virtual_stages)
            if pipe.tied_head_fn is not None:
                # tied embedding head: reads params["embed"], so autodiff
                # accumulates embed+unembed grads into one leaf (the
                # reference's tied-grad allreduce, pipe/engine.py:266)
                losses = jax.vmap(
                    lambda o, b: pipe.tied_head_fn(pipe.embed, params["embed"], o, b)
                )(outs, micro)
                return jnp.mean(losses)
            if pipe.head is not None:
                losses = jax.vmap(
                    lambda o, b: pipe.head.apply({"params": params["head"]}, o, b)
                )(outs, micro)
                return jnp.mean(losses)
            return outs

        return model_fn

    def _resolve_param_specs(self, params):
        if self._user_param_specs is not None:
            return self._user_param_specs
        return self.pipe_module.param_specs(params)

    def _init_state(self, model_parameters):
        # user-supplied trees (e.g. checkpoint-converted, naturally [L, ...])
        # get the same padded stack as init_params so the pp sharding divides
        padded = self.pipe_module.padded_layers()
        blocks = model_parameters.get("blocks") if isinstance(model_parameters, dict) else None
        if blocks is not None:
            have = jax.tree.leaves(blocks)[0].shape[0]
            if have == self.pipe_module.num_layers and have != padded:
                pad = padded - have
                model_parameters = dict(model_parameters)
                model_parameters["blocks"] = jax.tree.map(
                    lambda a: jnp.concatenate(
                        [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0),
                    blocks)
            elif have not in (self.pipe_module.num_layers, padded):
                raise ValueError(
                    f"model_parameters blocks stack has {have} layers; module "
                    f"expects {self.pipe_module.num_layers} (or padded {padded})")
        super()._init_state(model_parameters)

    def _ensure_initialized(self, batch):
        if self.state is not None:
            return
        mb = self.micro_batches
        sample = jax.tree.map(lambda x: x[: x.shape[0] // mb], batch)
        seed = self._rng_seed if isinstance(self._rng_seed, int) else 0
        params = self.pipe_module.init_params(jax.random.PRNGKey(seed), sample)
        self._init_state(params)

    def train_batch(self, data_iter=None):
        """reference pipe/engine.py:327: one call = gas microbatches + step."""
        if data_iter is None:
            assert self.training_dataloader is not None
            if self._data_iterator is None:
                from deepspeed_tpu.runtime.dataloader import RepeatingLoader
                self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._data_iterator
        gas = self.gradient_accumulation_steps_value
        from deepspeed_tpu import telemetry
        with telemetry.span("dataloader", gas=gas, pipe=True):
            micro_batches = [next(data_iter) for _ in range(gas)]
        batch = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *micro_batches)
        loss = self.forward(batch)
        self.backward(loss)
        # one fused call covers the whole GAS cycle; fix up the per-microstep
        # bookkeeping step() only does once
        self.micro_steps += gas - 1
        self.global_samples += (gas - 1) * self.micro_batch_size * self.topology.data_parallel_size
        self.step()
        # device-resident, matching DeepSpeedEngine.train_batch: the caller
        # pays the d2h sync when it actually reads the value
        return loss

    def eval_batch(self, data_iter_or_batch):
        if hasattr(data_iter_or_batch, "__next__"):
            gas = self.gradient_accumulation_steps_value
            micro = [next(data_iter_or_batch) for _ in range(gas)]
            batch = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *micro)
        else:
            batch = data_iter_or_batch
        return super().eval_batch(batch)
