"""Pipeline-parallel engine.

Mirrors the reference ``PipelineEngine`` (``runtime/pipe/engine.py:327``
``train_batch`` / :416 ``eval_batch``) — but where the reference interprets a
1F1B instruction stream with explicit p2p sends (``pipe/p2p.py:46,67``), the
TPU engine compiles the entire pipeline rotation into ONE XLA program:

- stage s holds block parameters [L/S, ...] (leading stacked-layer axis sharded
  over the ``pp`` mesh axis)
- each clock tick every stage applies its blocks to its current microbatch and
  the activations rotate to the next stage via ``lax.ppermute`` on ICI
- fill/drain bubbles are masked compute (SPMD requires uniform programs)
- JAX autodiff of the scan-of-ppermute program IS the backward schedule: the
  transpose of ppermute is the reverse rotation, so backward pipelining comes
  for free, and ``jax.checkpoint`` on the block gives the standard
  activation-recompute memory profile

Embed/head (first/last-stage-only roles in the reference) run under plain
GSPMD outside the rotation.
"""

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.utils.logging import log_dist


def collective_pipeline(block_apply, blocks_params, x_micro, mesh, *,
                        num_stages, remat=True, pp_axis="pp", extra=None,
                        num_layers=None):
    """Run M microbatches through the rotated block pipeline — pure GSPMD form.

    block_apply: (params_one_layer, x, extra) -> x
    blocks_params: stacked [L, ...] pytree (L = num_layers), pp-sharded on axis 0
    x_micro: [M, ...activation shape] (dp/sp shardings compose automatically)
    Returns: [M, ...] outputs after all L layers.

    Mechanics: activations live in a stage-stacked buffer [S, ...] whose leading
    axis is sharded over ``pp``; per-tick compute is ``vmap`` over that axis (so
    each device runs only its stage — the layer chunks differ only in the
    pp-sharded parameter slice) and the stage hand-off is ``jnp.roll`` on the
    sharded axis, which XLA lowers to a collective-permute over ICI. No manual
    region is needed, so tp/sp GSPMD inside the block composes untouched, and
    autodiff of the scan yields the reverse-rotation backward schedule.
    """
    body = jax.checkpoint(block_apply) if remat else block_apply
    S = num_stages
    M = x_micro.shape[0]

    # non-uniform partitioning: the stored stack is padded to S x ceil(L/S)
    # (PipelineModule.init_params) so the pp sharding divides evenly; padded
    # slots are masked no-ops here. With a homogeneous interior, balanced
    # partitioning (reference partition_method="parameters") == uniform slots.
    total = jax.tree.leaves(blocks_params)[0].shape[0]
    assert total % S == 0, f"padded layer stack {total} must divide stages {S}"
    K = total // S
    L = num_layers if num_layers is not None else total
    valid = (jnp.arange(S * K) < L).reshape(S, K)

    blocks = jax.tree.map(
        lambda a: a.reshape((S, K) + a.shape[1:]), blocks_params)
    blocks = jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(
            a, jax.NamedSharding(mesh, P(pp_axis))), blocks)

    def apply_stage(stage_blocks, stage_valid, x):
        def layer(h, pv):
            p, v = pv
            out = body(p, h, extra)
            # padded slot -> identity (out from zero params stays finite for
            # standard blocks, so the where-grad is clean)
            return jnp.where(v, out, h), None
        out, _ = lax.scan(layer, x, (stage_blocks, stage_valid))
        return out

    stage_vmap = jax.vmap(apply_stage, in_axes=(0, 0, 0), out_axes=0)
    buf_spec = P(pp_axis)

    def tick(carry, t):
        buf, outputs = carry  # buf: [S, ...] pp-sharded
        feed_idx = jnp.clip(t, 0, M - 1)
        feed = lax.dynamic_index_in_dim(x_micro, feed_idx, 0, keepdims=False)
        feed = jnp.where(t < M, feed, jnp.zeros_like(feed))
        buf = buf.at[0].set(feed)
        out = stage_vmap(blocks, valid, buf)
        out = jax.lax.with_sharding_constraint(
            out, jax.NamedSharding(mesh, buf_spec))
        # collect the last stage's result for microbatch t-(S-1)
        oidx = jnp.clip(t - (S - 1), 0, M - 1)
        out_ready = t - (S - 1) >= 0
        cur = lax.dynamic_index_in_dim(outputs, oidx, 0, keepdims=False)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(out_ready, out[S - 1], cur), oidx, 0)
        # rotate stages: s -> s+1 (slot 0 is overwritten by the next feed)
        buf = jnp.roll(out, 1, axis=0)
        return (buf, outputs), None

    init_buf = jnp.zeros((S,) + x_micro.shape[1:], x_micro.dtype)
    init_buf = jax.device_put(init_buf, jax.NamedSharding(mesh, buf_spec)) \
        if not isinstance(init_buf, jax.core.Tracer) else init_buf
    init_out = jnp.zeros_like(x_micro)
    (_, outputs), _ = lax.scan(tick, (init_buf, init_out), jnp.arange(M + S - 1))
    return outputs


class PipelineEngine(DeepSpeedEngine):
    """Engine over a ``PipelineModule``. ``train_batch`` consumes
    ``gradient_accumulation_steps`` microbatches per optimizer step, exactly as
    the reference (micro_batches == gas, pipe/engine.py:55)."""

    def __init__(self, config=None, model=None, **kwargs):
        assert isinstance(model, PipelineModule), \
            "PipelineEngine requires a deepspeed_tpu PipelineModule"
        self.pipe_module = model
        super().__init__(config=config, model=model, **kwargs)
        if self.pipe_module.num_stages is None:
            self.pipe_module.num_stages = self.topology.pp_size
        assert self.topology.pp_size == self.pipe_module.num_stages, (
            f"mesh pp={self.topology.pp_size} != module stages "
            f"{self.pipe_module.num_stages}")
        self.micro_batches = self.gradient_accumulation_steps_value
        # grads of the mean-over-all-microbatches loss are already the GAS mean;
        # pre-multiply so the apply-step's /gas cancels
        self._grad_scale_multiplier = float(self.gradient_accumulation_steps_value)

    def _normalize_model_fn(self, model):
        pipe = model

        def model_fn(params, batch, rng, training=True):
            M = self.micro_batches
            micro = jax.tree.map(
                lambda x: x.reshape((M, x.shape[0] // M) + x.shape[1:]), batch)
            embed = jax.vmap(lambda b: pipe.embed.apply({"params": params["embed"]}, b))(micro) \
                if pipe.embed else micro

            def block_apply(p, x, extra):
                return pipe.block.apply({"params": p}, x, *pipe.block_args)

            outs = collective_pipeline(
                block_apply, params["blocks"], embed, self.mesh,
                num_stages=self.topology.pp_size,
                remat=self.config.activation_checkpointing.policy != "nothing",
                num_layers=pipe.num_layers)
            if pipe.tied_head_fn is not None:
                # tied embedding head: reads params["embed"], so autodiff
                # accumulates embed+unembed grads into one leaf (the
                # reference's tied-grad allreduce, pipe/engine.py:266)
                losses = jax.vmap(
                    lambda o, b: pipe.tied_head_fn(pipe.embed, params["embed"], o, b)
                )(outs, micro)
                return jnp.mean(losses)
            if pipe.head is not None:
                losses = jax.vmap(
                    lambda o, b: pipe.head.apply({"params": params["head"]}, o, b)
                )(outs, micro)
                return jnp.mean(losses)
            return outs

        return model_fn

    def _resolve_param_specs(self, params):
        if self._user_param_specs is not None:
            return self._user_param_specs
        return self.pipe_module.param_specs(params)

    def _init_state(self, model_parameters):
        # user-supplied trees (e.g. checkpoint-converted, naturally [L, ...])
        # get the same padded stack as init_params so the pp sharding divides
        padded = self.pipe_module.padded_layers()
        blocks = model_parameters.get("blocks") if isinstance(model_parameters, dict) else None
        if blocks is not None:
            have = jax.tree.leaves(blocks)[0].shape[0]
            if have == self.pipe_module.num_layers and have != padded:
                pad = padded - have
                model_parameters = dict(model_parameters)
                model_parameters["blocks"] = jax.tree.map(
                    lambda a: jnp.concatenate(
                        [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0),
                    blocks)
            elif have not in (self.pipe_module.num_layers, padded):
                raise ValueError(
                    f"model_parameters blocks stack has {have} layers; module "
                    f"expects {self.pipe_module.num_layers} (or padded {padded})")
        super()._init_state(model_parameters)

    def _ensure_initialized(self, batch):
        if self.state is not None:
            return
        mb = self.micro_batches
        sample = jax.tree.map(lambda x: x[: x.shape[0] // mb], batch)
        seed = self._rng_seed if isinstance(self._rng_seed, int) else 0
        params = self.pipe_module.init_params(jax.random.PRNGKey(seed), sample)
        self._init_state(params)

    def train_batch(self, data_iter=None):
        """reference pipe/engine.py:327: one call = gas microbatches + step."""
        if data_iter is None:
            assert self.training_dataloader is not None
            if self._data_iterator is None:
                from deepspeed_tpu.runtime.dataloader import RepeatingLoader
                self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
            data_iter = self._data_iterator
        gas = self.gradient_accumulation_steps_value
        micro_batches = [next(data_iter) for _ in range(gas)]
        batch = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *micro_batches)
        loss = self.forward(batch)
        self.backward(loss)
        # one fused call covers the whole GAS cycle; fix up the per-microstep
        # bookkeeping step() only does once
        self.micro_steps += gas - 1
        self.global_samples += (gas - 1) * self.micro_batch_size * self.topology.data_parallel_size
        self.step()
        return float(jax.device_get(loss))

    def eval_batch(self, data_iter_or_batch):
        if hasattr(data_iter_or_batch, "__next__"):
            gas = self.gradient_accumulation_steps_value
            micro = [next(data_iter_or_batch) for _ in range(gas)]
            batch = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *micro)
        else:
            batch = data_iter_or_batch
        return super().eval_batch(batch)
