"""Pipeline model container.

Mirrors the reference ``PipelineModule``/``LayerSpec``/``TiedLayerSpec``
(``runtime/pipe/module.py:86,30,77``). The reference partitions an arbitrary
``LayerSpec`` list across stages; compiled SPMD pipelining wants a *homogeneous*
block stack (identical programs per stage), so the TPU-native container is
explicit about the three roles:

- ``embed``   — first-stage-only computation (batch → activations)
- ``block``   — the repeated layer, applied ``num_layers`` times; parameters are
  stacked [L, ...] and split [pp, L/pp, ...] across stages
- ``head``    — last-stage-only computation (activations(+batch) → loss/logits)

``LayerSpec``/``TiedLayerSpec`` and uniform/parameter-count partitioning are
retained for API parity: a LayerSpec list whose interior layers share a module
class is converted into this form by ``PipelineModule.from_layer_specs``.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """reference pipe/module.py:30 — lazily-built layer description."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """reference pipe/module.py:77 — layer whose params are tied across stages
    (e.g. embedding/unembedding). In the TPU container, ties are expressed by
    the head closing over the embed params, so the spec records only the key."""

    def __init__(self, key, typename, *module_args, forward_fn=None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_uniform(num_items, num_parts):
    """reference ds_utils.partition_uniform: balanced contiguous split."""
    parts = [0] * (num_parts + 1)
    chunk = num_items // num_parts
    residual = num_items % num_parts
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def partition_balanced(weights, num_parts):
    """reference ds_utils.partition_balanced: prefix-sum based split by weight
    (used for partition_method='parameters')."""
    import bisect
    prefix = [0]
    for w in weights:
        prefix.append(prefix[-1] + w)
    total = prefix[-1]
    parts = [0]
    for p in range(1, num_parts):
        target = total * p / num_parts
        idx = bisect.bisect_left(prefix, target)
        # snap to the nearer boundary
        if idx > 0 and abs(prefix[idx - 1] - target) <= abs(prefix[idx] - target):
            idx -= 1
        parts.append(max(idx, parts[-1]))
    parts.append(len(weights))
    return parts


class PipelineModule:
    """TPU-native pipeline container (see module docstring)."""

    def __init__(self, embed=None, block=None, head=None, num_layers=None,
                 num_stages=None, partition_method="uniform",
                 block_args: tuple = (), loss_fn=None,
                 activation_checkpoint_interval=0, tied_head_fn=None,
                 virtual_stages=1):
        """``tied_head_fn(embed_module, embed_params, acts, batch) -> loss``:
        the tied-embedding head (reference TiedLayerSpec, pipe/module.py:77).
        The head reads the *embed* parameters, so autodiff accumulates the
        embedding + unembedding gradients into the same leaf — the reference's
        tied-grad allreduce (pipe/engine.py:266) emerges from GSPMD because the
        embed params are replicated over pp.

        ``num_layers`` need not divide ``num_stages``: the block stack is
        padded to ``stages x ceil(L/S)`` with masked no-op slots (non-uniform
        partitioning — the reference's partition_method machinery; with a
        homogeneous interior, balanced == uniform-with-padding)."""
        assert block is not None and num_layers is not None
        self.embed = embed
        self.block = block
        self.head = head
        self.num_layers = num_layers
        self.num_stages = num_stages
        self.partition_method = partition_method
        # V>1: interleaved schedule — each stage holds V non-contiguous layer
        # chunks and activations circle the ring V times (reference
        # TrainSchedule, runtime/pipe/schedule.py:189); shrinks the pipeline
        # bubble from (S-1)/(M+S-1) toward (S-1)/(M*V)
        assert virtual_stages >= 1
        self.virtual_stages = int(virtual_stages)
        self.block_args = block_args
        self.loss_fn = loss_fn
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.tied_head_fn = tied_head_fn
        if tied_head_fn is not None and head is not None:
            raise ValueError("pass either head or tied_head_fn, not both")

    @staticmethod
    def from_layer_specs(layers, num_stages, loss_fn=None, **kw):
        """Parity constructor for reference-style LayerSpec lists: the first
        spec becomes embed, the last becomes head, the homogeneous interior
        becomes the block stack. A ``TiedLayerSpec`` pair (same key) at both
        ends becomes a tied embed/head (one parameter set, head via the spec's
        ``forward_fn(module, params, acts, batch)``)."""
        assert len(layers) >= 3, "need embed + blocks + head"
        first, last = layers[0], layers[-1]
        interior = layers[1:-1]
        t0 = interior[0].typename if isinstance(interior[0], LayerSpec) else type(interior[0])
        spec0 = interior[0]
        for l in interior:
            t = l.typename if isinstance(l, LayerSpec) else type(l)
            if t is not t0:
                raise ValueError(
                    "compiled SPMD pipelining requires a homogeneous interior "
                    f"layer stack; got {t0} and {t}")
            # same class is not enough: every stage is built from interior[0],
            # so differing constructor args would silently change the model
            if isinstance(l, LayerSpec) and isinstance(spec0, LayerSpec):
                if (l.module_args, l.module_kwargs) != (spec0.module_args,
                                                        spec0.module_kwargs):
                    raise ValueError(
                        "compiled SPMD pipelining requires identical constructor "
                        f"args for every interior layer; {spec0!r} has "
                        f"args={spec0.module_args} kwargs={spec0.module_kwargs} but "
                        f"{l!r} has args={l.module_args} kwargs={l.module_kwargs}")
        block = interior[0].build() if isinstance(interior[0], LayerSpec) else interior[0]
        if (isinstance(first, TiedLayerSpec) and isinstance(last, TiedLayerSpec)
                and first.key == last.key):
            if last.forward_fn is None:
                raise ValueError(
                    f"tied head spec {last!r} needs forward_fn(module, params, "
                    f"acts, batch) -> loss")
            return PipelineModule(embed=first.build(), block=block, head=None,
                                  num_layers=len(interior),
                                  num_stages=num_stages, loss_fn=loss_fn,
                                  tied_head_fn=last.forward_fn, **kw)
        embed = first.build() if isinstance(first, LayerSpec) else first
        head = last.build() if isinstance(last, LayerSpec) else last
        return PipelineModule(embed=embed, block=block, head=head,
                              num_layers=len(interior), num_stages=num_stages,
                              loss_fn=loss_fn, **kw)

    def padded_layers(self):
        """Stored stack length: num_layers padded up to a multiple of
        stages×virtual_stages (masked no-op slots; see __init__)."""
        if not self.num_stages:
            return self.num_layers
        unit = self.num_stages * self.virtual_stages
        return unit * (-(-self.num_layers // unit))

    # --- parameter init -------------------------------------------------
    def init_params(self, rng, sample_batch):
        """Initialize (embed, stacked blocks [padded_layers,...], head)."""
        k1, k2, k3 = jax.random.split(rng, 3)
        x = self.embed.init(k1, sample_batch)["params"] if self.embed else {}
        embed_params = x
        act = self.embed.apply({"params": embed_params}, sample_batch) if self.embed else sample_batch
        keys = jax.random.split(k2, self.num_layers)
        block_params = jax.vmap(
            lambda k: self.block.init(k, act, *self.block_args)["params"])(keys)
        pad = self.padded_layers() - self.num_layers
        if pad:
            block_params = jax.tree.map(
                lambda a: jnp.concatenate(
                    [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0),
                block_params)
        out = self.block.apply(
            {"params": jax.tree.map(lambda a: a[0], block_params)}, act, *self.block_args)
        head_params = self.head.init(k3, out, sample_batch)["params"] if self.head else {}
        return {"embed": embed_params, "blocks": block_params, "head": head_params}

    def param_specs(self, params):
        """pp-shard the stacked block axis; embed/head replicated (ZeRO/TP
        compose on the remaining dims via the engine partitioner)."""
        from jax.sharding import PartitionSpec as P

        specs = {
            "embed": jax.tree.map(lambda _: None, params["embed"]),
            "blocks": jax.tree.map(lambda leaf: P("pp"), params["blocks"]),
            "head": jax.tree.map(lambda _: None, params["head"]),
        }
        return specs
