"""Pipeline topology shims (mirrors reference ``runtime/pipe/topology.py``).

The reference's ``ProcessTopology``/``PipeModelDataParallelTopology``/
``PipelineParallelGrid`` (:12,:244,:251) map ranks to (pipe, data, model)
coordinates and build torch process groups per axis. On TPU those roles are
mesh axes of ``MeshTopology``; these shims keep the reference class names and
coordinate API for code written against them.
"""

from deepspeed_tpu.parallel.topology import MeshTopology


class PipeDataParallelTopology(MeshTopology):
    """axes=['pipe','data'] (reference topology.py:231)."""

    def __init__(self, num_pp, num_dp, devices=None):
        super().__init__(pp=num_pp, dp=num_dp, devices=devices)


class PipeModelDataParallelTopology(MeshTopology):
    """axes=['pipe','data','model'] (reference topology.py:244)."""

    def __init__(self, num_pp, num_mp, num_dp, devices=None):
        super().__init__(pp=num_pp, dp=num_dp, tp=num_mp, devices=devices)


class PipelineParallelGrid:
    """reference topology.py:251 — rank-coordinate views over the topology."""

    def __init__(self, topology: MeshTopology, process_rank=0):
        self.topo = topology
        self.global_rank = process_rank
        coords = topology.get_coord(process_rank)
        self.stage_id = coords["pp"]
        self.data_parallel_id = coords["dp"]
        self.model_parallel_id = coords["tp"]
        self.pipe_parallel_size = topology.pp_size
        self.data_parallel_size = topology.dp_size
        self.model_parallel_size = topology.tp_size

    def get_stage_id(self):
        return self.stage_id

    def get_data_parallel_id(self):
        return self.data_parallel_id

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id, **kwargs):
        coords = self.topo.get_coord(self.global_rank)
        coords["pp"] = stage_id
        coords.update(kwargs)
        return self.topo.get_rank(**coords)
