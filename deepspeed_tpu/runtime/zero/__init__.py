"""ZeRO — sharding-based partitioning of optimizer state, gradients, and
parameters (reference ``deepspeed/runtime/zero/``).

The public surface mirrors ``deepspeed.zero``: :class:`Init` for
partition-at-construction model initialization (reference
``partition_parameters.py:783``), with the partitioning rules themselves in
:mod:`deepspeed_tpu.runtime.zero.partition`.
"""

from deepspeed_tpu.runtime.zero.sharded_init import Init  # noqa: F401
from deepspeed_tpu.runtime.zero.tiling import (TiledLinear,  # noqa: F401
                                               TiledLinearReturnBias)
