"""ZeRO partitioning as GSPMD sharding specs.

The heart of the reference is partitioning params/grads/optimizer state across
the DP world (``zero/stage_1_and_2.py:96``, ``zero/stage3.py:75``,
``zero/partition_parameters.py:783``). On TPU the same capability is a *sharding
rule*: for each parameter leaf, pick an axis to shard over the ZeRO mesh axes
(dp, ep, sp), composed with any model-parallel (tp/ep) spec the model already
declares. XLA's GSPMD partitioner then emits the reduce-scatter (grads) and
all-gather (params) collectives that the reference implements by hand with
bucketed NCCL calls.

Stage semantics (reference ``zero/config.py``):
  0: master/opt replicated, grads replicated        (plain DP)
  1: master/opt sharded                             (optimizer-state partitioning)
  2: + gradient accumulation buffer sharded         (gradient partitioning)
  3: + working (bf16) params sharded                (parameter partitioning)

``stage3_param_persistence_threshold`` (reference ``zero/config.py:194``): leaves
smaller than the threshold stay replicated — identical capability (small params
are "persisted" rather than gathered per-use).
"""

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger


def _leaf_spec_with_zero(leaf, base_spec, zero_axes, mesh_sizes, threshold):
    """Compose ``base_spec`` (model-parallel) with a ZeRO shard axis choice.

    Mesh axes already consumed by the model spec (e.g. 'ep' on a stacked expert
    axis) are excluded — a NamedSharding may use each axis once."""
    shape = np.asarray(leaf.shape, dtype=np.int64) if hasattr(leaf, "shape") else None
    if shape is None or leaf.size < max(threshold, 1) or leaf.ndim == 0:
        return base_spec
    base = tuple(base_spec) if base_spec is not None else ()
    base = base + (None,) * (leaf.ndim - len(base))
    used = set()
    for entry in base:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            used.add(a)
    axes = tuple(a for a in zero_axes if a not in used)
    if not axes:
        return base_spec
    world = int(np.prod([mesh_sizes[a] for a in axes]))
    if world <= 1:
        return base_spec
    # choose the largest dimension not already sharded that divides the world
    best_dim, best_size = None, 0
    for d in range(leaf.ndim):
        if base[d] is not None:
            continue
        if shape[d] % world == 0 and shape[d] > best_size:
            best_dim, best_size = d, shape[d]
    if best_dim is None:
        return base_spec
    new = list(base)
    new[best_dim] = axes if len(axes) > 1 else axes[0]
    return P(*new)


class ZeroPartitioner:
    """Computes per-leaf shardings for every engine-state component."""

    def __init__(self, topology, zero_config, param_specs=None):
        self.topology = topology
        self.config = zero_config
        self.stage = zero_config.stage
        self.mesh = topology.mesh
        # only keep zero axes that actually have extent > 1. Master/opt/grads
        # shard over the full ZeRO world; working params may use the smaller
        # hierarchical group (hpZ secondary partition / MiCS shard group).
        self.zero_axes = tuple(a for a in topology.zero_axes if topology.get_dim(a) > 1)
        self.param_axes = tuple(a for a in topology.param_zero_axes
                                if topology.get_dim(a) > 1)
        self.zero_world = int(np.prod([topology.get_dim(a) for a in self.zero_axes])) if self.zero_axes else 1
        self.param_specs = param_specs  # pytree of P or None (model/tp specs)
        self.threshold = zero_config.stage3_param_persistence_threshold

    def _base_specs(self, params):
        if self.param_specs is None:
            return jax.tree.map(lambda _: None, params)
        return self.param_specs

    def _zero_tree(self, params, threshold, axes=None):
        base = self._base_specs(params)
        axes = self.zero_axes if axes is None else axes
        if not axes:
            return base
        sizes = {a: self.topology.get_dim(a) for a in axes}
        return jax.tree.map(
            lambda leaf, spec: _leaf_spec_with_zero(leaf, spec, axes,
                                                    sizes, threshold),
            params, base, is_leaf=lambda x: x is None)

    def _to_sharding(self, spec_tree):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s if s is not None else P()),
            spec_tree, is_leaf=lambda x: x is None or isinstance(x, P))

    # --- public per-component sharding trees ---
    def param_sharding(self, params):
        """Working-precision params: sharded only at stage 3 (plus model specs).
        Under hpZ/MiCS hierarchy the shard axes are the ICI-local group only
        (reference secondary tensors, ``partition_parameters.py`` hpZ)."""
        if self.stage >= 3:
            spec = self._zero_tree(params, self.threshold, axes=self.param_axes)
        else:
            spec = self._base_specs(params)
        return self._to_sharding(spec)

    def use_sharding(self, params):
        """Sharding at *use* sites inside the jitted step: model-parallel specs
        only, ZeRO axes gathered. Constraining params to this tree before
        ``model.apply`` is the GSPMD form of stage 3's per-use parameter
        all-gather (reference ``zero/partitioned_param_coordinator.py`` fetch):
        XLA inserts the all-gather at the use and — crucially — stops the
        *storage* sharding (hidden dim split over dp/sp) from propagating into
        activation shardings, which otherwise forces involuntary full
        rematerialization at sharding transitions."""
        return self._to_sharding(self._base_specs(params))

    def master_sharding(self, params):
        """fp32 master + optimizer moments: sharded from stage 1 up. Persistence
        threshold does NOT apply (the reference shards all optimizer state)."""
        if self.stage >= 1:
            spec = self._zero_tree(params, threshold=0)
        else:
            spec = self._base_specs(params)
        return self._to_sharding(spec)

    def grad_sharding(self, params):
        """Gradient accumulation buffer: sharded from stage 2 up."""
        if self.stage >= 2:
            spec = self._zero_tree(params, threshold=0)
        else:
            spec = self._base_specs(params)
        return self._to_sharding(spec)

    def opt_state_sharding(self, opt_state, params):
        """Optimizer state leaves that mirror a param shape get the master
        sharding; scalars/counters are replicated."""
        master = self.master_sharding(params)
        flat_master, _ = jax.tree.flatten(master)
        by_shape = {}
        for leaf, sh in zip(jax.tree.leaves(params), flat_master):
            by_shape.setdefault(tuple(leaf.shape), sh)
        rep = NamedSharding(self.mesh, P())

        def pick(leaf):
            if hasattr(leaf, "shape") and tuple(leaf.shape) in by_shape and leaf.ndim > 0:
                return by_shape[tuple(leaf.shape)]
            return rep

        return jax.tree.map(pick, opt_state)

    def describe(self, params):
        """Human-readable partition report (analog of the reference's partition
        logging in stage_1_and_2.py)."""
        shardings = self.master_sharding(params)
        n_sharded = sum(1 for s in jax.tree.leaves(shardings) if s.spec != P())
        total = len(jax.tree.leaves(params))
        logger.info(f"ZeRO stage {self.stage}: sharding {n_sharded}/{total} leaves "
                    f"over axes {self.zero_axes} (world {self.zero_world})")
