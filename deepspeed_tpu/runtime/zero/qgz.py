"""qgZ — ZeRO++ quantized gradient reduction, wired into the engine grad path.

Reference: ``zero_quantized_gradients`` routes the stage-3 gradient reduction
through ``all_to_all_quant_reduce`` (``runtime/zero/stage3.py:1249`` →
``runtime/comm/coalesced_collectives.py:81``): int4 all-to-all + reduce within
the node, int8 across nodes — ~4x less cross-node gradient traffic.

TPU design: under GSPMD the gradient all-reduce is emitted by XLA and cannot be
intercepted, so the qgZ engine path flips the ZeRO data axes to *manual*
(``jax.shard_map(axis_names={dp, dpr}, check_vma=False)``) while every other
axis (tp/sp/ep) stays compiler-managed:

- the micro-step computes **local** (unreduced) per-device gradients and
  accumulates them in a stacked ``[zero_world, ...]`` buffer sharded over the
  manual axes — exactly the reference's unreduced per-rank grad buffers;
- at the GAS boundary :func:`QgzPlan.reduce` performs the hierarchical
  quantized exchange per leaf along its ZeRO shard dimension: int4 blocks
  all-to-all'd over ``dp`` (ICI) and locally reduced, then int8 over ``dpr``
  (DCN), landing each device exactly its GSPMD gradient shard (axes-major
  chunk order). Leaves with no ZeRO-shardable dimension fall back to a plain
  ``psum``.

Trade-off vs the auto path (documented, inherent to manual-mode): stage-3
params are all-gathered at micro-step entry instead of per-use inside the
layer scan.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils import jax_compat  # noqa: F401  installs jax.shard_map on old jax
from deepspeed_tpu.runtime.comm.coalesced_collectives import exchange_reduce


class QgzPlan:
    """Everything the engine needs to run qgZ: manual axes, spec trees for the
    stacked local-grad buffer, and the boundary reduction."""

    def __init__(self, topology, partitioner, params_abstract, group_size=2048,
                 intra_bits=4, inter_bits=8):
        self.topology = topology
        self.mesh = topology.mesh
        self.group_size = group_size
        self.intra_bits = intra_bits
        self.inter_bits = inter_bits
        # hierarchy: dp rides ICI (intra), dpr rides DCN (inter)
        axes = tuple(a for a in ("dpr", "dp") if topology.get_dim(a) > 1)
        for a in ("ep", "sp"):
            if topology.get_dim(a) > 1:
                raise ValueError(
                    f"zero_quantized_gradients currently supports dp/dpr ZeRO "
                    f"axes only (got {a} size {topology.get_dim(a)} in the "
                    f"ZeRO world)")
        if not axes:
            raise ValueError("zero_quantized_gradients requires a data-parallel "
                             "world > 1")
        self.axes = axes                      # GSPMD chunk-major order
        self.sizes = {a: topology.get_dim(a) for a in axes}
        self.world = int(np.prod(list(self.sizes.values())))
        self.manual = set(axes)

        # per-leaf target gradient spec (the partitioner's stage>=2 layout)
        self.grad_specs = partitioner._zero_tree(params_abstract, threshold=0)
        self.base_specs = partitioner._base_specs(params_abstract)
        self.param_specs = (partitioner._zero_tree(params_abstract,
                                                   partitioner.threshold,
                                                   axes=partitioner.param_axes)
                            if partitioner.stage >= 3 else self.base_specs)

    # --- spec plumbing -------------------------------------------------
    def _project(self, spec):
        """Spec projected onto the manual axes (auto-axis entries dropped) —
        what shard_map in_specs must describe."""
        if spec is None:
            return P()
        out = []
        for e in spec:
            if e is None:
                out.append(None)
                continue
            axes = tuple(a for a in (e if isinstance(e, tuple) else (e,))
                         if a in self.manual)
            out.append(axes if len(axes) > 1 else (axes[0] if axes else None))
        return P(*out)

    def param_in_specs(self, params):
        return jax.tree.map(lambda _, s: self._project(s), params,
                            self.param_specs)

    def batch_in_spec(self):
        return P(self.axes)

    def stacked_spec(self, base_spec, project=False):
        base = tuple(base_spec) if base_spec is not None else ()
        stacked = P(self.axes, *base)
        return self._project(stacked) if project else stacked

    def stacked_specs(self, params, project=False):
        """Full specs (for buffer shardings) or manual-axis-projected specs
        (for shard_map in/out_specs — those may only mention manual axes)."""
        return jax.tree.map(
            lambda _, s: self.stacked_spec(s, project=project), params,
            self.base_specs)

    def stacked_shardings(self, params):
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            self.stacked_specs(params),
            is_leaf=lambda x: isinstance(x, P))

    def stacked_zeros(self, params, dtype):
        # allocate directly sharded (jit with out_shardings): device_put of a
        # host/default-device zeros would transiently stage world x leaf bytes
        # on one device — the OOM ZeRO exists to avoid
        shardings = self.stacked_shardings(params)
        shapes = jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct((self.world,) + tuple(leaf.shape),
                                              dtype), params)
        make = jax.jit(
            lambda: jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes),
            out_shardings=shardings)
        return make()

    def _gather_leaf(self, x, spec, skip_dims=0):
        """All-gather one leaf's manual-axis shards; ``skip_dims`` drops
        leading spec entries (a sliced-out scan dim shifts the rest left)."""
        if spec is None:
            return x
        for d, e in enumerate(spec[skip_dims:] if skip_dims else spec):
            if e is None:
                continue
            man = tuple(a for a in (e if isinstance(e, tuple) else (e,))
                        if a in self.manual)
            if man:
                x = lax.all_gather(x, man, axis=d, tiled=True)
        return x

    def gather_params(self, params_local, specs=None):
        """Inside the shard_map body: all-gather stage-3 param shards over the
        manual axes (the reference's param all-gather, done at step entry).
        ``specs`` restricts to a subtree (the overlap pass gathers only the
        resident leaves here; stacked blocks stream via gather_block)."""
        specs = self.param_specs if specs is None else specs
        return jax.tree.map(self._gather_leaf, params_local, specs)

    def gather_block(self, stacked_local, specs, i):
        """One scan block's params, gathered: slice block ``i`` off each
        stacked leaf's leading scan dim, then all-gather its ZeRO shards.
        This is the per-layer shard exchange the overlap schedule issues on
        the previous layer's boundary (overlap_schedule.scheduled_scan) —
        same math as slicing the monolithic gather, HBM holds O(depth)
        blocks instead of the stack."""
        def one(x, spec):
            # the partitioner may have put the ZeRO shard on the scan dim
            # itself — gather it first so index ``i`` addresses global blocks
            if spec is not None and len(spec) and spec[0] is not None:
                e = spec[0]
                man = tuple(a for a in (e if isinstance(e, tuple) else (e,))
                            if a in self.manual)
                if man:
                    x = lax.all_gather(x, man, axis=0, tiled=True)
            x = lax.dynamic_index_in_dim(x, i, axis=0, keepdims=False)
            return self._gather_leaf(x, spec, skip_dims=1)
        return jax.tree.map(one, stacked_local, specs)

    # --- leaf-wise zero-dim discovery ---------------------------------
    def _zero_dim(self, grad_spec, base_spec):
        """(dim, axes) the partitioner chose for this leaf's ZeRO shard, or
        (None, None) when the leaf stays replicated over the manual axes."""
        if grad_spec is None:
            return None, None
        base = tuple(base_spec) if base_spec is not None else ()
        for d, e in enumerate(grad_spec):
            if e is None:
                continue
            be = base[d] if d < len(base) else None
            if e == be:
                continue  # model-parallel entry, unchanged by the partitioner
            axes = tuple(a for a in (e if isinstance(e, tuple) else (e,))
                         if a in self.manual)
            if axes:
                return d, axes
        return None, None

    # --- boundary reduction --------------------------------------------
    def _reduce_leaf(self, local, d, axes, want_error=False):
        """Hierarchical quantized exchange of one leaf's chunks along dim d.

        ``local``: this device's full-shape accumulated gradient. Returns this
        device's chunk (the GSPMD shard for spec entry ``axes`` on dim d, in
        axes-major order). ``want_error=True`` additionally returns this
        device's quantization residual mapped back into ``local``'s
        coordinates (the error-feedback carry: stage-1 errors at their source
        chunks, the stage-2 error at this device's own dp chunk column)."""
        moved = jnp.moveaxis(local, d, 0)
        rest = moved.shape[1:]
        err = None
        if axes == ("dpr", "dp"):
            R, D = self.sizes["dpr"], self.sizes["dp"]
            chunks = moved.reshape(R, D, -1)                  # [R, D, m]
            m = chunks.shape[2]
            # stage 1 (ICI): dp-peer i receives slab chunks[:, i]
            slabs = chunks.transpose(1, 0, 2).reshape(D, -1)  # [D, R*m]
            s1 = exchange_reduce(slabs, "dp", self.intra_bits,
                                 self.group_size,
                                 return_error=want_error)     # [R*m]
            partial = s1[0] if want_error else s1
            # stage 2 (DCN): dpr-peer r receives row r of the partial
            s2 = exchange_reduce(partial.reshape(R, m), "dpr",
                                 self.inter_bits, self.group_size,
                                 return_error=want_error)     # [m]
            out = s2[0] if want_error else s2
            if want_error:
                # e1 [D, R*m] back to chunk coords; e2 [R, m] lands at this
                # device's own dp column (it is an error on the partial sum
                # only this device held — re-fed here, the next step's stage-1
                # sum carries it forward)
                e1 = s1[1].reshape(D, R, m).transpose(1, 0, 2)   # [R, D, m]
                my_dp = lax.axis_index("dp")
                hot = (jax.nn.one_hot(my_dp, D, dtype=e1.dtype)
                       [None, :, None])                          # [1, D, 1]
                err = (e1 + s2[1][:, None, :] * hot).reshape(moved.shape)
        else:
            (axis,) = axes
            n = self.sizes[axis]
            bits = self.intra_bits if axis == "dp" else self.inter_bits
            s1 = exchange_reduce(moved.reshape(n, -1), axis, bits,
                                 self.group_size, return_error=want_error)
            out = s1[0] if want_error else s1
            if want_error:
                err = s1[1].reshape(moved.shape)
        chunk_shape = (moved.shape[0] // self.world
                       if axes == ("dpr", "dp") else
                       moved.shape[0] // self.sizes[axes[0]],) + rest
        out = jnp.moveaxis(out.reshape(chunk_shape), 0, d)
        if want_error:
            return out, jnp.moveaxis(err, 0, d)
        return out

    @staticmethod
    def _bucketize(sizes, buckets):
        """Contiguous leaf-index groups with roughly equal byte load — the
        grad-bucket split the overlap schedule issues as independent
        exchanges. Deterministic (leaf order), never empty, always exactly
        ``min(buckets, len(sizes))`` groups."""
        k = max(1, min(int(buckets), len(sizes)))
        total = float(sum(sizes)) or 1.0
        groups, cur, acc = [], [], 0.0
        for j, s in enumerate(sizes):
            cur.append(j)
            acc += s
            remaining_leaves = len(sizes) - j - 1
            remaining_groups = k - len(groups) - 1
            if (len(groups) < k - 1
                    and (acc >= total * (len(groups) + 1) / k
                         or remaining_leaves == remaining_groups)
                    and remaining_leaves >= remaining_groups):
                groups.append(cur)
                cur = []
        if cur:
            groups.append(cur)
        return groups

    def reduce(self, acc_stacked, residual=None, return_residual=False,
               buckets=1):
        """Stacked local-grad buffer -> GSPMD-sharded summed gradients.

        Inside shard_map over the manual axes, each leaf either does the
        quantized hierarchical exchange along its ZeRO dim or (no shardable
        dim) a plain fp psum.

        ``buckets`` > 1 (the overlap schedule's async grad reduce): the leaf
        list splits into that many contiguous byte-balanced groups, each
        exchanged in its OWN shard_map region — the resulting program is
        ``buckets`` independent collective chains instead of one monolithic
        chain, so XLA's latency-hiding scheduler can pipeline one bucket's
        quantize/dequantize math under another bucket's wire time and start
        exchanging as soon as a bucket's grads exist. Leaf-wise math is
        untouched — bucketization is bit-identical to the monolithic reduce.

        Error feedback (``zero_quantized_gradients_error_feedback``):
        ``residual`` is the previous step's quantization error in the same
        stacked layout as ``acc_stacked``; it is folded into each leaf before
        quantization. ``return_residual=True`` returns ``(grads, residual')``
        where ``residual'`` is this step's fresh error carry (zeros for psum
        leaves — they are never quantized)."""
        if return_residual and residual is None:
            raise ValueError("return_residual=True needs the previous "
                             "residual (pass stacked zeros on the first step)")
        leaves, treedef = jax.tree.flatten(acc_stacked)
        gspecs = treedef.flatten_up_to(self.grad_specs)
        bspecs = treedef.flatten_up_to(self.base_specs)
        res_leaves = (treedef.flatten_up_to(residual)
                      if residual is not None else [None] * len(leaves))
        out_projs = [self._project(s) for s in gspecs]
        in_projs = [self.stacked_spec(s, project=True) for s in bspecs]

        def one(leaf, res, gspec, bspec):
            local = leaf[0].astype(jnp.float32)            # [*shape]
            if res is not None:
                local = local + res[0].astype(jnp.float32)
            d, axes = self._zero_dim(gspec, bspec)
            if d is None:
                out = lax.psum(local, tuple(self.axes))
                # psum leaves are never quantized: zero error carry
                return out, (jnp.zeros_like(local)[None]
                             if return_residual else None)
            if return_residual:
                out, err = self._reduce_leaf(local, d, axes, want_error=True)
                return out, err[None]
            return self._reduce_leaf(local, d, axes), None

        sizes = [l.size * jnp.dtype(l.dtype).itemsize for l in leaves]
        groups = self._bucketize(sizes, buckets)

        out_leaves = [None] * len(leaves)
        err_leaves = [None] * len(leaves)
        for idxs in groups:
            g_in = [in_projs[j] for j in idxs]
            g_out = [out_projs[j] for j in idxs]

            def body(acc_list, res_list, _idxs=idxs):
                pairs = [one(leaf, res, gspecs[j], bspecs[j])
                         for leaf, res, j in zip(acc_list, res_list, _idxs)]
                if not return_residual:
                    return [p[0] for p in pairs]
                return [p[0] for p in pairs], [p[1] for p in pairs]

            if residual is None:
                fn = jax.shard_map(lambda a, _i=idxs, _b=body: _b(a, [None] * len(_i)),
                                   mesh=self.mesh, in_specs=(g_in,),
                                   out_specs=g_out,
                                   axis_names=self.manual, check_vma=False)
                got = fn([leaves[j] for j in idxs])
                errs = [None] * len(idxs)
            else:
                out_specs = ((g_out, g_in) if return_residual else g_out)
                fn = jax.shard_map(body, mesh=self.mesh,
                                   in_specs=(g_in, g_in),
                                   out_specs=out_specs,
                                   axis_names=self.manual, check_vma=False)
                got = fn([leaves[j] for j in idxs],
                         [res_leaves[j] for j in idxs])
                got, errs = got if return_residual else (got, [None] * len(idxs))
            for j, g, e in zip(idxs, got, errs):
                out_leaves[j] = g
                err_leaves[j] = e

        grads = jax.tree.unflatten(treedef, out_leaves)
        if not return_residual:
            return grads
        return grads, jax.tree.unflatten(treedef, err_leaves)
