"""ZeRO config (mirrors reference ``deepspeed/runtime/zero/config.py:82-``).

Stage semantics on TPU (per-leaf GSPMD sharding over the data axes):

- stage 0: grads reduced (psum), fp32 master + optimizer state replicated
- stage 1: optimizer state + fp32 master sharded over the ZeRO axes
- stage 2: additionally the gradient-accumulation buffer is sharded (XLA turns
  the grad psum into reduce-scatter)
- stage 3: additionally the bf16 working parameters are stored sharded; XLA
  all-gathers them at use sites (per scan-block with scanned-layer models,
  which is the ``max_live_parameters`` analog)

Keys the reference exposes that are CUDA-mechanics-only (bucket sizes, stream
overlap) are accepted for config compatibility and recorded, but the XLA
scheduler owns overlap.
"""

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """reference ``zero/offload_config.py`` offload_param."""
    device = "none"  # none | cpu | nvme
    nvme_path = None
    buffer_count = 5
    buffer_size = 100_000_000
    max_in_cpu = 1_000_000_000
    pin_memory = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """reference ``zero/offload_config.py`` offload_optimizer; ``ratio`` is the
    Twin-Flow/offload++ partial-offload fraction."""
    device = "none"
    nvme_path = None
    buffer_count = 4
    pin_memory = False
    pipeline_read = False
    pipeline_write = False
    fast_init = False
    ratio = 1.0


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage = 0
    contiguous_gradients = True
    reduce_scatter = True
    reduce_bucket_size = 500_000_000
    use_multi_rank_bucket_allreduce = True
    allgather_partitions = True
    allgather_bucket_size = 500_000_000
    overlap_comm = None
    load_from_fp32_weights = True
    elastic_checkpoint = False
    offload_param = DeepSpeedZeroOffloadParamConfig()
    offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig()
    sub_group_size = 1_000_000_000
    cpu_offload = False  # deprecated alias handled in engine
    # stage-3 knobs (reference zero/config.py:194)
    stage3_max_live_parameters = 1_000_000_000
    stage3_max_reuse_distance = 1_000_000_000
    stage3_prefetch_bucket_size = 50_000_000
    stage3_param_persistence_threshold = 100_000
    model_persistence_threshold = 9_223_372_036_854_775_807
    stage3_gather_16bit_weights_on_model_save = False
    round_robin_gradients = False
    # ZeRO++ (reference zero/config.py:39-42)
    zero_hpz_partition_size = 1
    zero_quantized_weights = False
    zero_quantized_nontrainable_weights = False
    zero_quantized_gradients = False
    # carry the per-leaf quantization residual into the next step's gradient
    # (ZeRO++ error feedback; only meaningful with zero_quantized_gradients)
    zero_quantized_gradients_error_feedback = False
    mics_shard_size = -1
    mics_hierarchical_params_gather = False
    memory_efficient_linear = True
    pipeline_loading_checkpoint = False
    override_module_apply = True
    log_trace_cache_warnings = False

    _deprecated = {
        "stage3_gather_fp16_weights_on_model_save": "stage3_gather_16bit_weights_on_model_save",
    }

    def __init__(self, param_dict=None, **kwargs):
        super().__init__(param_dict, **kwargs)
        if isinstance(self.offload_param, dict):
            self.offload_param = DeepSpeedZeroOffloadParamConfig(self.offload_param)
        if isinstance(self.offload_optimizer, dict):
            self.offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(self.offload_optimizer)

    @property
    def offload_optimizer_device(self):
        return self.offload_optimizer.device if self.offload_optimizer else "none"

    @property
    def offload_param_device(self):
        return self.offload_param.device if self.offload_param else "none"
