"""Tiled linear layers (reference ``runtime/zero/tiling.py`` TiledLinear).

The reference splits a huge Linear into an ``in_splits x out_splits`` grid of
small Linears so ZeRO-3 can partition/gather each tile independently and the
full weight never needs to be resident at once. Under GSPMD most of that job
is the partitioner's (a sharded weight IS tiles), but the capability still
matters on TPU for layers bigger than one chip's HBM arena: storing the
weight as explicit tile parameters bounds the size of any single all-gather
and lets the engine's persistence threshold keep individual tiles sharded.

``TiledLinear`` keeps the tile grid as separate flax params named
``tile_{i}_{j}`` (each eligible for its own ZeRO sharding decision) and
contracts them with a python loop over output tiles — XLA fuses the
accumulation; peak live memory is one row of tiles plus the output.

The reference's ContiguousMemoryAllocator (defragmenting param buffers) has
no analog here by design: XLA owns allocation and lays buffers out at
compile time, so fragmentation of framework-managed arenas cannot occur.
"""

from typing import Any, Callable, Optional

import jax.numpy as jnp
import flax.linen as nn


def _splits(total, n):
    if total % n != 0:
        raise ValueError(f"cannot split {total} into {n} even tiles")
    return total // n


class TiledLinear(nn.Module):
    """Drop-in ``nn.Dense`` with an ``in_splits x out_splits`` tiled weight.

    Equivalent math to ``nn.Dense(features)``; the weight is stored as
    ``in_splits * out_splits`` independent ``[in/i, out/j]`` params. The
    default init scales variance by the FULL fan-in (not the tile fan-in),
    so fresh-init statistics match ``nn.Dense`` exactly.
    """
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: Any = None
    kernel_init: Optional[Callable] = None  # None = Dense-equivalent default
    bias_init: Callable = nn.initializers.zeros

    def _contract(self, x):
        """Shared tile contraction: returns (y_without_bias, bias|None)."""
        in_features = x.shape[-1]
        di = _splits(in_features, self.in_splits)
        dj = _splits(self.features, self.out_splits)
        dtype = self.dtype or x.dtype
        # lecun_normal over the whole layer: per-tile variance must be
        # 1/in_features, not 1/di, or summing in_splits tile products gives
        # sqrt(in_splits)x the fresh-init output std of nn.Dense
        kinit = self.kernel_init or nn.initializers.variance_scaling(
            1.0 / self.in_splits, "fan_in", "truncated_normal")
        xs = [x[..., i * di:(i + 1) * di] for i in range(self.in_splits)]
        outs = []
        for j in range(self.out_splits):
            acc = None
            for i in range(self.in_splits):
                w = self.param(f"tile_{i}_{j}", kinit, (di, dj), jnp.float32)
                part = xs[i] @ w.astype(dtype)
                acc = part if acc is None else acc + part
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        bias = None
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,),
                              jnp.float32).astype(dtype)
        return y, bias

    @nn.compact
    def __call__(self, x):
        y, bias = self._contract(x)
        return y if bias is None else y + bias

    @staticmethod
    def from_dense_kernel(kernel, in_splits, out_splits):
        """Split a dense [in, out] kernel into the tile param dict (migration
        helper, the reference's ``copy_params_from`` analog)."""
        di = _splits(kernel.shape[0], in_splits)
        dj = _splits(kernel.shape[1], out_splits)
        return {f"tile_{i}_{j}": kernel[i * di:(i + 1) * di,
                                        j * dj:(j + 1) * dj]
                for i in range(in_splits) for j in range(out_splits)}


class TiledLinearReturnBias(TiledLinear):
    """Reference ``TiledLinearReturnBias``: returns (output_without_bias,
    bias) so callers can defer the bias add (fused residual paths)."""

    @nn.compact
    def __call__(self, x):
        return self._contract(x)
