"""ZeRO-Infinity parameter tier — working parameters live on host DRAM or NVMe.

Reference capability being replaced (not translated):
- ``runtime/swap_tensor/partitioned_param_swapper.py:36``
  (``AsyncPartitionedParameterSwapper``): partitioned fp16 params swap between
  NVMe, pinned host buffers, and device memory around each submodule's
  forward/backward.
- ``runtime/zero/parameter_offload.py:83`` (``DeepSpeedZeRoOffload``) +
  ``runtime/zero/partitioned_param_coordinator.py:520``: module-granular
  fetch/release hooks with NVMe prefetch ahead of the forward walk.

TPU-native redesign. The reference streams parameters around an *eager module
walk*; under XLA there is no walk — the whole step is one compiled program. The
stream therefore rides the program itself:

- The model's layer stack is already a ``lax.scan`` over homogeneous blocks
  (the TPU-idiomatic layout every model family here uses). In param-offload
  mode the engine runs the model through its *streaming protocol*: the scan
  body fetches block ``i``'s parameters from the host tier via a
  ``jax.pure_callback`` — so at any moment device HBM holds O(1 block) of
  streamed weights, never the stack.
- The fetch is a ``jax.custom_vjp``: its backward is an ``io_callback`` that
  writes the block's parameter *gradient* cotangent straight back into host
  accumulators. Combined with rematerialization of the scan body, the backward
  pass re-streams each block (the reference re-gathers partitions for backward
  the same way) and gradients leave the device the moment they exist —
  the analog of the reference's grad-partition device→host copies
  (``stage3.py`` ``partition_gradients`` + cpu-offload path).
- The optimizer step for streamed blocks runs on host in the native AVX-512
  CPU Adam (``csrc/adam/cpu_adam.cpp``) over fp32 masters held in DRAM, with
  moments optionally swapped to NVMe — the existing ZeRO-Offload host tier
  (``zero/offload.py``). New working-precision bytes are published back to the
  store; the next step's fetches see them. Streamed parameters NEVER make a
  host→device round trip through the optimizer.

Small non-stacked leaves (embeddings, final norm, lm head) stay device-resident
with a normal device optimizer — the analog of the reference's
``stage3_param_persistence_threshold`` (small params are pinned on-device there
for the same reason: streaming them costs more than holding them).

NVMe tier: one file per scan block through ``AsyncIOHandle``
(``csrc/aio/ds_aio.cpp`` O_DIRECT thread pool), with direction-aware read-ahead
(forward sweep prefetches ``i+1``, the backward re-stream prefetches ``i-1``)
into a small ring of host buffers — the double-buffering of the reference's
swapper, driven by observed access order instead of hooks.
"""

import os
import threading

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None


def _np_dtype(jdtype):
    if jdtype == jnp.bfloat16:
        if _BF16 is None:  # pragma: no cover
            raise RuntimeError("bfloat16 param offload requires ml_dtypes")
        return _BF16
    return np.dtype(jdtype)


class BlockParamStore:
    """Host/NVMe tier for the scan-stacked working parameters of one model.

    Owns, per scan block ``i``:
    - the working-precision flat leaves (DRAM arrays, or an NVMe file plus a
      host buffer ring),
    - fp32 gradient accumulators (filled by the backward io_callback; summed
      across the GAS window exactly like the device accumulator),
    - and, via ``HostOffloadOptimizer``, the fp32 masters + optimizer moments.
    """

    def __init__(self, stacked_f32, param_cfg, opt_cfg, opt_params, working_dtype,
                 opt_name="adamw"):
        """``stacked_f32``: pytree whose leaves are fp32 arrays with leading
        dim L (the scan axis). ``param_cfg``: DeepSpeedZeroOffloadParamConfig.
        ``opt_cfg``: DeepSpeedZeroOffloadOptimizerConfig (moment tier; its
        device may be "none" → moments stay in DRAM)."""
        from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer

        self.device = param_cfg.device
        self.working_dtype = working_dtype
        self._np_work = _np_dtype(working_dtype)

        leaves_p = jax.tree_util.tree_flatten_with_path(stacked_f32)
        self._treedef = leaves_p[1]
        self._paths = [jax.tree_util.keystr(p) for p, _ in leaves_p[0]]
        leaves = [np.asarray(l, dtype=np.float32) for _, l in leaves_p[0]]
        lset = {l.shape[0] for l in leaves}
        if len(lset) != 1:
            raise ValueError(f"stacked leaves disagree on the scan length: {lset}")
        self.num_blocks = lset.pop()
        self.block_shapes = [l.shape[1:] for l in leaves]
        self._sizes = [int(np.prod(s)) if s else 1 for s in self.block_shapes]
        self._offsets = np.concatenate([[0], np.cumsum(self._sizes)]).astype(np.int64)
        self.block_elems = int(self._offsets[-1])
        self.itemsize = self._np_work.itemsize

        # fp32 masters + moments: the existing ZeRO-Offload host tier, keyed
        # per (block, leaf) so NVMe moment swapping sees leaf-sized units
        masters = {self._key(i, j): leaves[j][i]
                   for i in range(self.num_blocks) for j in range(len(leaves))}
        self._opt = HostOffloadOptimizer(masters, opt_cfg, dict(opt_params or {}),
                                         working_dtype, opt_name=opt_name)

        # gradient accumulators (fp32, one flat buffer per block)
        self._grads = [np.zeros(self.block_elems, np.float32)
                       for _ in range(self.num_blocks)]
        self._grad_writes = 0
        self._lock = threading.Lock()

        # working tier
        self._last_fetch = -1
        if self.device == "nvme":
            from deepspeed_tpu.ops.aio import AsyncIOHandle
            self._aio = AsyncIOHandle()
            self._dir = os.path.join(param_cfg.nvme_path or "/tmp/ds_tpu_nvme",
                                     "params")
            os.makedirs(self._dir, exist_ok=True)
            nbuf = max(2, int(param_cfg.buffer_count))
            self._ring = [np.empty(self.block_elems, self._np_work)
                          for _ in range(nbuf)]
            self._ring_block = [-1] * nbuf   # which block each buffer holds
            self._ring_busy = [False] * nbuf  # read in flight
            self._ring_next = 0
            for i in range(self.num_blocks):
                flat = np.empty(self.block_elems, self._np_work)
                for j, l in enumerate(leaves):
                    flat[self._offsets[j]:self._offsets[j + 1]] = \
                        l[i].reshape(-1).astype(self._np_work)
                self._write_file(i, flat)
        else:
            self._work = []
            for i in range(self.num_blocks):
                flat = np.empty(self.block_elems, self._np_work)
                for j, l in enumerate(leaves):
                    flat[self._offsets[j]:self._offsets[j + 1]] = \
                        l[i].reshape(-1).astype(self._np_work)
                self._work.append(flat)
        host_mb = self.num_blocks * self.block_elems * 4 / 1e6
        log_dist(f"ZeRO-Infinity param tier: {self.num_blocks} blocks x "
                 f"{self.block_elems/1e6:.2f}M elems on {self.device} "
                 f"(masters+moments {host_mb * 3:.0f}MB host)", ranks=[0])

    def _key(self, i, j):
        return f"b{i:05d}::{self._paths[j]}"

    def _path_of(self, i):
        return os.path.join(self._dir, f"block_{i:05d}.bin")

    def _write_file(self, i, flat):
        self._aio.sync_pwrite(flat.view(np.uint8), self._path_of(i))
        # a rewrite invalidates any ring copy of this block
        for s, b in enumerate(self._ring_block):
            if b == i:
                self._ring_block[s] = -1

    # --- fetch path (called from inside the compiled step) ---------------
    def _ring_slot(self, i):
        for s, b in enumerate(self._ring_block):
            if b == i:
                return s
        return -1

    def _issue_read(self, i, avoid=-1):
        """Start an async read of block ``i`` into the next ring slot, never
        evicting the slot that holds block ``avoid`` (the block currently
        being returned — an eviction there would race the caller's copy)."""
        if self._ring_slot(i) >= 0:
            return
        s = self._ring_next
        if self._ring_block[s] == avoid:
            s = (s + 1) % len(self._ring)
        self._ring_next = (s + 1) % len(self._ring)
        if self._ring_busy[s]:
            self._aio.wait()
            for k in range(len(self._ring)):
                self._ring_busy[k] = False
        self._aio.async_pread(self._ring[s].view(np.uint8), self._path_of(i))
        self._ring_block[s] = i
        self._ring_busy[s] = True

    def read_block(self, i):
        """Flat leaves (working dtype) of block ``i``; drives read-ahead."""
        i = int(i)
        if self.device == "nvme":
            if self._ring_slot(i) < 0:
                self._issue_read(i)
            self._aio.wait()
            for k in range(len(self._ring)):
                self._ring_busy[k] = False
            flat = self._ring[self._ring_slot(i)]
        else:
            flat = self._work[i]
        # COPIES, not views: jax may zero-copy callback results on CPU
        # backends, and both the ring (async read-ahead) and the DRAM tier
        # (in-place optimizer write-back) mutate these buffers while returned
        # arrays can still feed pending thunks. Copy BEFORE issuing the
        # read-ahead — the prefetch must never land in this block's slot.
        out = tuple(flat[self._offsets[j]:self._offsets[j + 1]]
                    .reshape(self.block_shapes[j]).copy()
                    for j in range(len(self._paths)))
        if self.device == "nvme":
            # direction-aware read-ahead: fwd sweep wants i+1, the backward
            # re-stream wants i-1 (the coordinator-prefetch analog)
            step = i - self._last_fetch
            nxt = i + (1 if step >= 0 else -1)
            if 0 <= nxt < self.num_blocks:
                self._issue_read(nxt, avoid=i)
        self._last_fetch = i
        return out

    # --- gradient path (called from the custom_vjp backward) -------------
    def accum_grad(self, i, *cts):
        i = int(i)
        with self._lock:
            g = self._grads[i]
            for j, ct in enumerate(cts):
                g[self._offsets[j]:self._offsets[j + 1]] += \
                    np.asarray(ct, dtype=np.float32).reshape(-1)
            self._grad_writes += 1
        return np.int32(0)

    def grad_sq_and_finite(self):
        """(sum of squares, all-finite) over the host grad accumulators —
        merged with the device-side stats for the global clip/overflow. A
        non-finite block makes the sum inf (matching ``global_norm`` on a
        poisoned device tree) instead of silently dropping contributions."""
        sq, finite = 0.0, True
        for g in self._grads:
            if np.isfinite(g).all():
                sq += float(np.dot(g.astype(np.float64), g.astype(np.float64)))
            else:
                finite = False
                sq = float("inf")
        return sq, finite

    def zero_grads(self):
        for g in self._grads:
            g[:] = 0
        self._grad_writes = 0

    # --- optimizer boundary ----------------------------------------------
    def step(self, lr, inv_scale):
        """Host optimizer over every streamed block, then publish the new
        working-precision bytes so the next step's fetches observe them."""
        grads = {}
        for i in range(self.num_blocks):
            g = self._grads[i]
            for j in range(len(self._paths)):
                grads[self._key(i, j)] = g[self._offsets[j]:self._offsets[j + 1]]
        new_working = self._opt.step(grads, lr, inv_scale)
        for i in range(self.num_blocks):
            if self.device == "nvme":
                flat = np.empty(self.block_elems, self._np_work)
                for j in range(len(self._paths)):
                    flat[self._offsets[j]:self._offsets[j + 1]] = \
                        np.asarray(new_working[self._key(i, j)],
                                   dtype=self._np_work).reshape(-1)
                self._write_file(i, flat)
            else:
                flat = self._work[i]
                for j in range(len(self._paths)):
                    flat[self._offsets[j]:self._offsets[j + 1]] = \
                        np.asarray(new_working[self._key(i, j)],
                                   dtype=self._np_work).reshape(-1)
        self.zero_grads()

    # --- materialization / checkpointing ----------------------------------
    def stacked_params(self, dtype=np.float32):
        """Reassemble the full stacked tree from the fp32 masters (host-side;
        used by checkpointing and ``get_model_parameters``)."""
        leaves = []
        for j, shape in enumerate(self.block_shapes):
            arr = np.empty((self.num_blocks,) + tuple(shape), dtype=dtype)
            for i in range(self.num_blocks):
                arr[i] = self._opt.masters[self._key(i, j)] \
                    .reshape(shape).astype(dtype)
            leaves.append(arr)
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def load_stacked_params(self, stacked):
        """Replace masters from a stacked tree and re-publish the working tier
        (checkpoint load / universal-checkpoint resume)."""
        leaves = jax.tree_util.tree_leaves(stacked)
        for j, l in enumerate(leaves):
            l = np.asarray(l, dtype=np.float32)
            for i in range(self.num_blocks):
                self._opt.masters[self._key(i, j)][:] = l[i].reshape(-1)
        self._publish_from_masters()

    def _publish_from_masters(self):
        for i in range(self.num_blocks):
            flat = np.empty(self.block_elems, self._np_work)
            for j in range(len(self._paths)):
                flat[self._offsets[j]:self._offsets[j + 1]] = \
                    self._opt.masters[self._key(i, j)].astype(self._np_work)
            if self.device == "nvme":
                self._write_file(i, flat)
            else:
                self._work[i][:] = flat

    def get_moments(self, i, j):
        """(m, v) fp32 flat moment views for block ``i``, leaf ``j`` —
        universal-checkpoint export (checkpoint/universal.py)."""
        key = self._key(i, j)
        if self._opt.swapper is not None:
            return self._opt.swapper.state_arrays()[key]
        return self._opt.adam.state_for(key, self._sizes[j])

    def set_moments(self, i, j, m, v):
        key = self._key(i, j)
        m = np.ascontiguousarray(m, np.float32).reshape(-1)
        v = np.ascontiguousarray(v, np.float32).reshape(-1)
        if self._opt.swapper is not None:
            self._opt.swapper.load_state_arrays({key: (m, v)})
        else:
            self._opt.adam.set_state(key, m, v)

    def set_master(self, i, j, value):
        self._opt.masters[self._key(i, j)][:] = \
            np.asarray(value, np.float32).reshape(-1)

    def get_opt_step(self):
        return self._opt.adam.step_count

    def set_opt_step(self, step):
        self._opt.adam.step_count = int(step)

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, sd):
        self._opt.load_state_dict(sd)
        self._publish_from_masters()


def make_streaming_fetch(store):
    """Build the differentiable block fetch for ``streaming_apply``.

    Forward: ``pure_callback`` pulls block ``i``'s working-precision leaves out
    of the host tier (O(1 block) HBM). Backward: ``io_callback`` accumulates
    the parameter cotangent into the tier's fp32 grad buffers. The extra
    ``token`` argument is a differentiable scalar threaded from the loss
    inputs — without a float input JAX would treat the fetch as a constant and
    dead-code-eliminate the backward write.
    """
    out_shapes = tuple(
        jax.ShapeDtypeStruct(s, store.working_dtype) for s in store.block_shapes)
    treedef = store._treedef

    @jax.custom_vjp
    def fetch(i, token):
        flat = jax.pure_callback(store.read_block, out_shapes, i)
        return jax.tree_util.tree_unflatten(treedef, list(flat))

    def fetch_fwd(i, token):
        return fetch(i, token), i

    def fetch_bwd(i, ct):
        flat_ct = jax.tree_util.tree_leaves(ct)
        jax.experimental.io_callback(
            store.accum_grad, jax.ShapeDtypeStruct((), jnp.int32), i, *flat_ct,
            ordered=False)
        return None, jnp.zeros((), jnp.float32)

    fetch.defvjp(fetch_fwd, fetch_bwd)
    return fetch
