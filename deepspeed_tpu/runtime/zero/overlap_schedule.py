"""Overlap scheduling pass — double-buffered param prefetch, bucketized grad
exchange, and the graph-level plan the autotuner co-decides.

ROADMAP item 2 (T3 / DeepCompile in PAPERS.md): PR 8 built the measurement —
``telemetry/overlap.py`` attributes every collective's *exposed* seconds, and
its analytic mode's serialized schedule (compute, then every collective after
it) is the 100%-exposed worst case. This module is the pass that acts on that
number. Three layers, one plan:

**Analytic scheduler** (stdlib-only — the chip-free model of what the
scheduled program does). :func:`scheduled_intervals` builds the two-resource
timeline a prefetch-depth-D / K-bucket step implies: one compute stream (L
forward layer slabs, then backward), one serialized collective stream.
Parameter all-gathers split per layer; gather ``i`` may issue when layer
``i - D``'s compute *starts* (D buffers in flight) and layer ``i``'s compute
waits on it — the pipeline-fill gather stays exposed, the steady state hides.
Grad reduce-scatters split into K buckets; bucket ``b`` may issue the moment
its slice of backward completes, overlapping the remaining backward. Smaller
chunks pay the per-call link latency — more buckets is not free, which is
exactly the trade-off the planner weighs. The existing exposure algebra
(``overlap.attribute``) scores the timeline; nothing here hand-computes
exposure.

**Planner**. :func:`candidate_plans` turns ``telemetry.overlap.advise()``
hints into seed candidates ("prefetch all_gather over dp" → deeper prefetch
first, reduce_scatter hints → more buckets first) and
:func:`plan_exposure` scores a (depth, buckets) plan on an inventory —
``Autotuner.tune_chip_free`` sweeps it as a fourth/fifth tuning dimension
alongside (stage × micro-batch × remat).

**Runtime structure** (jax, imported lazily). :func:`scheduled_scan` is the
double-buffered layer loop the engine's qgZ micro-step runs under
``overlap.schedule``: the scan carry holds the next ``depth`` blocks' gathered
parameters, each iteration issues the gather for block ``i + depth`` *before*
the compute that consumes block ``i`` — the all-gather is data-independent of
the current block's math, so XLA's async-collective scheduling can overlap
them; no hand-ordered host code. The gather itself is
``QgzPlan.gather_block``; grads ride the shadow-input trick (see
``engine._build_micro_step``) so the qgZ stacked accumulator keeps its
unreduced local-grad semantics.

perf_gate loads this file standalone (same pattern as ``telemetry/overlap.py``)
to re-derive the checked-in baseline's schedule jax-free; ``_OVERLAP`` is the
injection point for the equally-standalone overlap module.
"""

import math

# Injection point: perf_gate.py loads this file outside the package and plugs
# its standalone telemetry/overlap.py module in here. In-package callers
# resolve it lazily (overlap.py is stdlib-only, so this never drags in jax).
_OVERLAP = None

# Same injection shape for the measured per-op cost store
# (telemetry/profile_store.py): perf_gate/overlap_report plug their standalone
# copy in; in-package callers resolve lazily; a missing store (or a standalone
# load where the package import fails) degrades to the roofline, never errors.
_PROFILE = None

# matches kernel_tuner._COMM_LATENCY_S — the per-call launch/sync floor that
# makes many small collectives cost more than one big one
DEFAULT_LATENCY_S = 1e-6

# op-name classes the scheduler knows how to move. Everything else (grad-norm
# all_reduce, ...) stays serialized after backward — exposed. The MoE expert
# all-to-all gets its own pair of classes: dispatch can lead the expert GEMM
# it feeds, combine trails it — a different dependence shape from either the
# param prefetch or the grad buckets (see :func:`moe_scheduled_intervals`).
_PREFETCH_OPS = ("all_gather", "gather")
_BUCKET_OPS = ("reduce_scatter", "psum_scatter", "all_to_all", "exchange")
_MOE_DISPATCH_OPS = ("a2a_dispatch",)
_MOE_COMBINE_OPS = ("a2a_combine",)


def _ov():
    global _OVERLAP
    if _OVERLAP is None:
        from deepspeed_tpu.telemetry import overlap as _OVERLAP  # noqa: PLW0603
    return _OVERLAP


def _profile():
    global _PROFILE
    if _PROFILE is None:
        try:
            from deepspeed_tpu.telemetry import profile_store as _PROFILE  # noqa: PLW0603
        except ImportError:
            return None
    return _PROFILE


def _count_resolution(op, reason):
    """Per-resolve reason-code counter (measured | roofline_fallback) — a
    no-op when telemetry is disabled or the package isn't importable (the
    standalone perf_gate path)."""
    try:
        from deepspeed_tpu import telemetry
    except ImportError:
        return
    if telemetry.enabled():
        telemetry.count(f"overlap/cost_resolution/{reason}", op=str(op))


def _op_class(op):
    name = str(op or "").lower()
    # moe classes first: "a2a_*" must not fall through to the generic
    # "all_to_all"/"exchange" bucket class
    if any(k in name for k in _MOE_DISPATCH_OPS):
        return "moe_dispatch"
    if any(k in name for k in _MOE_COMBINE_OPS):
        return "moe_combine"
    if any(k in name for k in _PREFETCH_OPS):
        return "prefetch"
    if any(k in name for k in _BUCKET_OPS):
        return "bucket"
    return "tail"


class OverlapPlan:
    """One schedule decision: how deep the param prefetch pipeline runs and
    how many grad buckets the boundary exchange splits into. ``n_layers`` and
    ``fwd_fraction`` shape the analytic timeline only."""

    def __init__(self, prefetch_depth=1, grad_buckets=2, n_layers=8,
                 fwd_fraction=1.0 / 3.0, latency_s=DEFAULT_LATENCY_S,
                 a2a_chunks=1):
        if prefetch_depth < 0:
            raise ValueError(f"prefetch_depth must be >= 0, got {prefetch_depth}")
        if grad_buckets < 1:
            raise ValueError(f"grad_buckets must be >= 1, got {grad_buckets}")
        if n_layers < 1:
            raise ValueError(f"n_layers must be >= 1, got {n_layers}")
        if not 0.0 < fwd_fraction < 1.0:
            raise ValueError(f"fwd_fraction must be in (0, 1), got {fwd_fraction}")
        if a2a_chunks < 1:
            raise ValueError(f"a2a_chunks must be >= 1, got {a2a_chunks}")
        self.prefetch_depth = int(prefetch_depth)
        self.grad_buckets = int(grad_buckets)
        self.n_layers = int(n_layers)
        self.fwd_fraction = float(fwd_fraction)
        self.latency_s = float(latency_s)
        self.a2a_chunks = int(a2a_chunks)

    def to_dict(self):
        return {"prefetch_depth": self.prefetch_depth,
                "grad_buckets": self.grad_buckets,
                "n_layers": self.n_layers,
                "fwd_fraction": round(self.fwd_fraction, 6),
                "latency_s": self.latency_s,
                "a2a_chunks": self.a2a_chunks}

    @classmethod
    def from_dict(cls, d):
        return cls(prefetch_depth=d.get("prefetch_depth", 1),
                   grad_buckets=d.get("grad_buckets", 2),
                   n_layers=d.get("n_layers", 8),
                   fwd_fraction=d.get("fwd_fraction", 1.0 / 3.0),
                   latency_s=d.get("latency_s", DEFAULT_LATENCY_S),
                   a2a_chunks=d.get("a2a_chunks", 1))

    def __repr__(self):
        return (f"OverlapPlan(depth={self.prefetch_depth}, "
                f"buckets={self.grad_buckets}, layers={self.n_layers}, "
                f"a2a_chunks={self.a2a_chunks})")


def _split_spec(spec, m, latency_s):
    """One comm-op inventory entry split into ``m`` equal chunks. The
    bandwidth share divides evenly; every chunk pays the per-call latency
    floor again — splitting is never free."""
    m = max(int(m), 1)
    count = max(int(spec.get("count", 1)), 1)
    total_s = float(spec["seconds"]) * count
    bw_s = max(total_s - latency_s * count, 0.0)
    chunk_s = bw_s / m + latency_s
    nbytes = int(spec.get("bytes", 0) or 0)
    wire = spec.get("wire_bytes")
    out = []
    for k in range(m):
        out.append({"op": spec["op"], "axis": spec.get("axis"),
                    "bytes": nbytes // m,
                    "wire_bytes": (int(wire) // m if wire is not None else None),
                    "count": 1, "seconds": chunk_s})
    return out


def scheduled_intervals(compute_s, comm_ops, plan, device="analytic:0"):
    """The per-device timeline a scheduled step implies — the analytic-mode
    counterpart of ``overlap.analytic_intervals``'s serialized worst case.

    Two resources: the compute stream runs ``n_layers`` forward slabs then the
    backward block; the collective stream serializes chunks (collectives never
    hide each other — same rule the attribution uses). Data dependencies:
    layer ``i``'s forward waits on param-gather chunk ``i``; gather ``i`` may
    issue once layer ``i - depth``'s compute starts (``depth`` buffers in
    flight; depth 0 = issue at the consuming layer's boundary, fully
    serialized fill). Grad bucket ``b`` may issue once backward has retired
    ``(b+1)/K`` of its work; tail ops (grad-norm all_reduce, anything
    unclassified) wait for backward *and* every bucket.

    ``comm_ops`` entries need ``seconds`` (use :func:`fill_comm_seconds`).
    Comm totals are conserved up to the per-chunk latency floor, so serialized
    and scheduled reports stay byte-comparable."""
    ov = _ov()
    L, D, K = plan.n_layers, plan.prefetch_depth, plan.grad_buckets
    lat = plan.latency_s

    gathers, buckets, tail = [], [], []
    for spec in comm_ops:
        # unknown classes (incl. moe dispatch/combine in a non-moe timeline)
        # stay serialized at the tail — exposed, never silently dropped
        cls = _op_class(spec.get("op"))
        {"prefetch": gathers, "bucket": buckets}.get(cls, tail).append(spec)

    # split each class across its pipeline stages
    gather_chunks = [[] for _ in range(L)]
    for spec in gathers:
        for i, c in enumerate(_split_spec(spec, L, lat)):
            gather_chunks[i].append(c)
    bucket_chunks = [[] for _ in range(K)]
    for spec in buckets:
        for b, c in enumerate(_split_spec(spec, K, lat)):
            bucket_chunks[b].append(c)

    compute_s = float(compute_s)
    fwd_s = compute_s * plan.fwd_fraction
    bwd_s = compute_s - fwd_s
    fwd_slab = fwd_s / L

    ivs = []
    comm_free = 0.0

    def issue(chunks, ready, tag):
        """Serialize ``chunks`` onto the collective stream, not before
        ``ready``; returns when the last lands."""
        nonlocal comm_free
        done = ready
        for c in chunks:
            start = max(ready, comm_free)
            end = start + float(c["seconds"])
            ivs.append(ov.make_interval(
                f"comm:{c['op']}/{tag}", start, end, kind="comm",
                device=device, op=c["op"], axis=c.get("axis"),
                nbytes=c.get("bytes", 0), wire_bytes=c.get("wire_bytes")))
            comm_free = done = end
        return done

    # forward: gather i issues at layer (i - D)'s compute start; layer i's
    # compute waits on gather i and the previous layer
    start_c = [0.0] * L
    end_c = [0.0] * L
    for i in range(L):
        if D == 0:
            ready = end_c[i - 1] if i > 0 else 0.0
        else:
            ready = start_c[i - D] if i >= D else 0.0
        g_done = issue(gather_chunks[i], ready, f"prefetch{i:02d}")
        start_c[i] = max(end_c[i - 1] if i > 0 else 0.0, g_done)
        end_c[i] = start_c[i] + fwd_slab
        if fwd_slab > 0:
            ivs.append(ov.make_interval(f"compute/fwd{i:02d}", start_c[i],
                                        end_c[i], kind="compute",
                                        device=device))

    # backward: one slab per bucket window so bucket readiness lands on a
    # compute boundary; bucket b issues as soon as its window retires
    t0b = end_c[L - 1] if L else 0.0
    last_bucket_done = t0b
    for b in range(K):
        s = t0b + bwd_s * b / K
        e = t0b + bwd_s * (b + 1) / K
        if bwd_s > 0:
            ivs.append(ov.make_interval(f"compute/bwd{b:02d}", s, e,
                                        kind="compute", device=device))
        done = issue(bucket_chunks[b], e, f"bucket{b:02d}")
        last_bucket_done = max(last_bucket_done, done)

    # tail: grad-norm all_reduce and anything unclassified needs every grad
    # bucket — serialized after backward and the last exchange
    ready = max(t0b + bwd_s, last_bucket_done)
    for spec in tail:
        secs = float(spec["seconds"])
        for _ in range(max(int(spec.get("count", 1)), 1)):
            issue([dict(spec, seconds=secs, count=1)], ready, "tail")
            ready = comm_free
    return {device: ivs}


def fill_comm_seconds(comm_ops, device_kind="tpu_v5e", axis_sizes=None):
    """Per-call seconds for inventory entries that lack them — measured
    first, roofline second.

    Each priced entry consults the persisted per-op profile store
    (``telemetry/profile_store.py``) before the analytic roofline
    ``overlap.analytic_report`` uses, and is tagged with a
    ``cost_source`` reason code (``"measured"`` on a store hit,
    ``"roofline_fallback"`` otherwise); the same code lands on the
    ``overlap/cost_resolution/*`` telemetry counter when enabled. Needs
    jax only when the roofline actually fires — checked-in baselines
    carry seconds and stay stdlib-only; a measured hit is stdlib-only
    too."""
    specs = []
    for spec in comm_ops:
        spec = dict(spec)
        if "seconds" not in spec:
            count = max(int(spec.get("count", 1)), 1)
            per_call_bytes = spec.get("bytes", 0) / count
            ps = _profile()
            measured = None
            if ps is not None:
                measured, _ = ps.resolve(spec["op"], per_call_bytes,
                                         device_kind=device_kind)
            if measured is not None:
                spec["seconds"] = measured
                spec["cost_source"] = "measured"
            else:
                from deepspeed_tpu.autotuning import kernel_tuner
                n = (axis_sizes or {}).get(spec.get("axis"))
                spec["seconds"] = kernel_tuner.comm_roofline_seconds(
                    spec["op"], per_call_bytes, n=n, device_kind=device_kind)
                spec["cost_source"] = "roofline_fallback"
            _count_resolution(spec["op"], spec["cost_source"])
        specs.append(spec)
    return specs


def plan_exposure(compute_s, comm_ops, plan, device="analytic:0"):
    """Exposed-comm seconds of one plan on one inventory (the planner's
    scoring primitive — attribution algebra, no report assembly)."""
    per_device = scheduled_intervals(compute_s, comm_ops, plan, device=device)
    att = _ov().attribute(per_device)
    return att["totals"]["exposed_comm_s"]


def moe_scheduled_intervals(compute_s, comm_ops, plan, device="analytic:0"):
    """The MoE-step timeline ``plan.a2a_chunks`` implies — the expert-parallel
    counterpart of :func:`scheduled_intervals`.

    ``compute_s`` is the expert GEMM block; the dispatch all-to-all feeds it
    and the combine all-to-all drains it, so with one chunk the step is fully
    serialized: dispatch, then experts, then combine — the worst case the
    ratchet baseline records. Splitting into ``A = a2a_chunks`` chunks
    pipelines them: every dispatch chunk is ready at step start (routing
    precedes expert compute) and issues immediately on the serialized
    collective stream; expert chunk ``c`` waits on dispatch chunk ``c`` and
    its predecessor; combine chunk ``c`` issues the moment expert chunk ``c``
    retires. Steady-state dispatch hides under the previous expert chunk and
    combine under the next — only the fill (first dispatch) and drain (last
    combine) stay exposed. Per-chunk latency is re-paid on every split
    (:func:`_split_spec`), so more chunks is not free — the planner's
    trade-off. Unclassified ops serialize at the tail as ever."""
    ov = _ov()
    A = plan.a2a_chunks
    lat = plan.latency_s

    dispatch, combine, tail = [], [], []
    for spec in comm_ops:
        cls = _op_class(spec.get("op"))
        {"moe_dispatch": dispatch,
         "moe_combine": combine}.get(cls, tail).append(spec)

    disp_chunks = [[] for _ in range(A)]
    for spec in dispatch:
        for c, ch in enumerate(_split_spec(spec, A, lat)):
            disp_chunks[c].append(ch)
    comb_chunks = [[] for _ in range(A)]
    for spec in combine:
        for c, ch in enumerate(_split_spec(spec, A, lat)):
            comb_chunks[c].append(ch)

    compute_s = float(compute_s)
    slab = compute_s / A

    ivs = []
    comm_free = 0.0

    def issue(chunks, ready, tag):
        nonlocal comm_free
        done = ready
        for c in chunks:
            start = max(ready, comm_free)
            end = start + float(c["seconds"])
            ivs.append(ov.make_interval(
                f"comm:{c['op']}/{tag}", start, end, kind="comm",
                device=device, op=c["op"], axis=c.get("axis"),
                nbytes=c.get("bytes", 0), wire_bytes=c.get("wire_bytes")))
            comm_free = done = end
        return done

    # all dispatch chunks are ready at t=0 — queue them ahead of any combine
    # so a trailing combine never blocks the next chunk's dispatch
    d_done = [issue(disp_chunks[c], 0.0, f"dispatch{c:02d}")
              for c in range(A)]

    prev_end = 0.0
    last_done = 0.0
    for c in range(A):
        start = max(prev_end, d_done[c])
        end = start + slab
        if slab > 0:
            ivs.append(ov.make_interval(f"compute/expert{c:02d}", start, end,
                                        kind="compute", device=device))
        prev_end = end
        done = issue(comb_chunks[c], end, f"combine{c:02d}")
        last_done = max(last_done, done, end)

    ready = last_done
    for spec in tail:
        secs = float(spec["seconds"])
        for _ in range(max(int(spec.get("count", 1)), 1)):
            issue([dict(spec, seconds=secs, count=1)], ready, "tail")
            ready = comm_free
    return {device: ivs}


def moe_plan_exposure(compute_s, comm_ops, plan, device="analytic:0"):
    """Exposed-comm seconds of one plan on an MoE inventory — the a2a_chunks
    scoring primitive."""
    per_device = moe_scheduled_intervals(compute_s, comm_ops, plan,
                                         device=device)
    att = _ov().attribute(per_device)
    return att["totals"]["exposed_comm_s"]


def scheduled_report(cost, comm_ops, plan, device_kind="tpu_v5e",
                     axis_sizes=None, top_k=10, compute_s=None):
    """Chip-free overlap report for the *scheduled* program, with the
    serialized worst case it ratchets from riding in ``report["schedule"]``.

    Same inputs as ``overlap.analytic_report`` plus the plan; ``compute_s``
    short-circuits the cost-model roofline when the caller already has it
    (the standalone perf_gate path — no jax)."""
    ov = _ov()
    if compute_s is None:
        from deepspeed_tpu.autotuning import kernel_tuner
        compute_s = kernel_tuner.roofline_compute_seconds(
            float(cost.get("flops", 0.0) or 0.0),
            float(cost.get("bytes accessed", 0.0) or 0.0),
            device_kind=device_kind)
    specs = fill_comm_seconds(comm_ops, device_kind=device_kind,
                              axis_sizes=axis_sizes)
    serialized = ov.attribute(ov.analytic_intervals(compute_s, specs))
    ser_exposed = serialized["totals"]["exposed_comm_s"]

    per_device = scheduled_intervals(compute_s, specs, plan)
    report = ov.overlap_report(per_device, mode="analytic", top_k=top_k,
                               device_kind=device_kind)
    exposed = report["exposed_comm_s"]
    reduction = ((ser_exposed - exposed) / ser_exposed
                 if ser_exposed > 0 else 0.0)
    report["schedule"] = dict(
        plan.to_dict(),
        compute_s=round(float(compute_s), 9),
        comm_ops=[{k: v for k, v in s.items()} for s in specs],
        serialized_exposed_comm_s=round(ser_exposed, 9),
        exposed_reduction_fraction=round(reduction, 6),
    )
    return report


def moe_scheduled_report(cost, comm_ops, plan, device_kind="tpu_v5e",
                         axis_sizes=None, top_k=10, compute_s=None):
    """Chip-free overlap report for the *scheduled* MoE step — the
    :func:`scheduled_report` twin built on :func:`moe_scheduled_intervals`,
    with the fully-serialized worst case riding in ``report["schedule"]`` for
    ``perf_gate check_moe_baseline`` to ratchet."""
    ov = _ov()
    if compute_s is None:
        from deepspeed_tpu.autotuning import kernel_tuner
        compute_s = kernel_tuner.roofline_compute_seconds(
            float(cost.get("flops", 0.0) or 0.0),
            float(cost.get("bytes accessed", 0.0) or 0.0),
            device_kind=device_kind)
    specs = fill_comm_seconds(comm_ops, device_kind=device_kind,
                              axis_sizes=axis_sizes)
    serialized = ov.attribute(ov.analytic_intervals(compute_s, specs))
    ser_exposed = serialized["totals"]["exposed_comm_s"]

    per_device = moe_scheduled_intervals(compute_s, specs, plan)
    report = ov.overlap_report(per_device, mode="analytic", top_k=top_k,
                               device_kind=device_kind)
    exposed = report["exposed_comm_s"]
    reduction = ((ser_exposed - exposed) / ser_exposed
                 if ser_exposed > 0 else 0.0)
    report["schedule"] = dict(
        plan.to_dict(),
        compute_s=round(float(compute_s), 9),
        comm_ops=[{k: v for k, v in s.items()} for s in specs],
        serialized_exposed_comm_s=round(ser_exposed, 9),
        exposed_reduction_fraction=round(reduction, 6),
    )
    return report


def validate_schedule(sched):
    """Structural check of a report's ``schedule`` block (stdlib-only —
    perf_gate re-derives the baseline from exactly these fields). Returns a
    list of error strings."""
    errs = []
    if not isinstance(sched, dict):
        return ["schedule block is not a dict"]
    for k in ("prefetch_depth", "grad_buckets", "n_layers"):
        v = sched.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"schedule.{k} missing or invalid (got {v!r})")
    # optional (pre-moe baselines omit it; from_dict defaults to 1)
    v = sched.get("a2a_chunks", 1)
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        errs.append(f"schedule.a2a_chunks invalid (got {v!r})")
    for k in ("compute_s", "serialized_exposed_comm_s", "fwd_fraction"):
        v = sched.get(k)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not math.isfinite(v) or v < 0:
            errs.append(f"schedule.{k} missing or non-finite (got {v!r})")
    ops = sched.get("comm_ops")
    if not isinstance(ops, list) or not ops:
        errs.append("schedule.comm_ops missing or empty")
        return errs
    for spec in ops:
        if not isinstance(spec, dict) or "op" not in spec:
            errs.append(f"malformed comm_ops entry {spec!r}")
            continue
        s = spec.get("seconds")
        if not isinstance(s, (int, float)) or not math.isfinite(s) or s < 0:
            errs.append(f"comm_ops[{spec['op']}].seconds invalid ({s!r})")
    return errs


# ---------------------------------------------------------------------------
# planner: advisor hints -> candidate plans -> scored sweep dimension
# ---------------------------------------------------------------------------

DEFAULT_DEPTHS = (0, 1, 2)
DEFAULT_BUCKETS = (1, 2, 4)
DEFAULT_A2A_CHUNKS = (1, 2, 4)


def candidate_plans(hints=None, n_layers=8, depths=DEFAULT_DEPTHS,
                    buckets=DEFAULT_BUCKETS, fwd_fraction=1.0 / 3.0):
    """(depth, buckets) candidates for the sweep, advisor-seeded.

    ``hints``: ``telemetry.overlap.advise()`` rows. A hint naming a
    gather-class op with saving potential promotes the deepest prefetch
    candidates to the front; a reduce-class hint promotes the highest bucket
    counts — the sweep tries what the measured exposure says matters before
    falling back to the full ladder. Depth is capped at ``n_layers - 1``
    (you cannot hold more lookahead than there are layers left)."""
    depths = sorted({min(int(d), max(n_layers - 1, 0)) for d in depths})
    buckets = sorted({max(1, min(int(b), n_layers)) for b in buckets})
    want_depth = want_buckets = False
    for h in hints or []:
        if float(h.get("potential_saving_s", 0) or 0) <= 0:
            continue
        cls = _op_class(h.get("op"))
        want_depth |= cls == "prefetch"
        want_buckets |= cls == "bucket"

    d_order = sorted(depths, reverse=want_depth)
    b_order = sorted(buckets, reverse=want_buckets)
    out, seen = [], set()
    for d in d_order:
        for b in b_order:
            if (d, b) not in seen:
                seen.add((d, b))
                out.append(OverlapPlan(prefetch_depth=d, grad_buckets=b,
                                       n_layers=n_layers,
                                       fwd_fraction=fwd_fraction))
    return out


def best_plan(compute_s, comm_ops, hints=None, n_layers=8,
              depths=DEFAULT_DEPTHS, buckets=DEFAULT_BUCKETS):
    """Sweep the candidates on one inventory; returns
    ``(plan, exposed_s, ranking)`` with the ranking listing every candidate's
    exposure (ties broken toward the shallower/cheaper plan — fewer live
    buffers, fewer launches)."""
    ranking = []
    for plan in candidate_plans(hints, n_layers=n_layers, depths=depths,
                                buckets=buckets):
        exposed = plan_exposure(compute_s, comm_ops, plan)
        ranking.append({"prefetch_depth": plan.prefetch_depth,
                        "grad_buckets": plan.grad_buckets,
                        "exposed_comm_s": round(exposed, 9)})
    if not ranking:
        raise ValueError("no overlap candidates to rank")
    ranking.sort(key=lambda r: (r["exposed_comm_s"], r["prefetch_depth"],
                                r["grad_buckets"]))
    top = ranking[0]
    plan = OverlapPlan(prefetch_depth=top["prefetch_depth"],
                       grad_buckets=top["grad_buckets"], n_layers=n_layers)
    return plan, top["exposed_comm_s"], ranking


def best_moe_a2a_chunks(compute_s, comm_ops, base_plan=None,
                        chunks=DEFAULT_A2A_CHUNKS):
    """Sweep ``a2a_chunks`` on an MoE inventory (dispatch/combine a2a ops vs
    the expert GEMM block); returns ``(plan, exposed_s, ranking)`` like
    :func:`best_plan`. ``base_plan`` carries the non-moe dimensions (depth,
    buckets) the main sweep already decided — chunk count is co-decided on
    top, not instead."""
    base = base_plan if base_plan is not None else OverlapPlan()
    ranking = []
    for a in sorted({max(1, int(a)) for a in chunks}):
        plan = OverlapPlan(prefetch_depth=base.prefetch_depth,
                           grad_buckets=base.grad_buckets,
                           n_layers=base.n_layers,
                           fwd_fraction=base.fwd_fraction,
                           latency_s=base.latency_s, a2a_chunks=a)
        exposed = moe_plan_exposure(compute_s, comm_ops, plan)
        ranking.append({"a2a_chunks": a,
                        "exposed_comm_s": round(exposed, 9)})
    if not ranking:
        raise ValueError("no a2a_chunks candidates to rank")
    # ties break toward fewer chunks — fewer launches, less latency re-paid
    ranking.sort(key=lambda r: (r["exposed_comm_s"], r["a2a_chunks"]))
    top = ranking[0]
    plan = OverlapPlan(prefetch_depth=base.prefetch_depth,
                       grad_buckets=base.grad_buckets,
                       n_layers=base.n_layers,
                       fwd_fraction=base.fwd_fraction,
                       latency_s=base.latency_s,
                       a2a_chunks=top["a2a_chunks"])
    return plan, top["exposed_comm_s"], ranking


# ---------------------------------------------------------------------------
# runtime: the double-buffered layer loop (jax, lazy)
# ---------------------------------------------------------------------------

def scheduled_scan(block_fn, carry, n_blocks, fetch, prefetch_depth=1,
                   remat=True):
    """Layer loop with the gather-ahead rotation the scheduling pass needs.

    ``fetch(i)`` returns block ``i``'s (gathered) parameter tree;
    ``block_fn(carry, block_params, i) -> carry`` applies one block. With
    ``prefetch_depth`` D >= 1 the scan carry holds the next D fetched blocks:
    each iteration issues ``fetch(i + D)`` *before* ``block_fn`` consumes the
    head of the buffer, so inside the loop body the gather has no data
    dependence on the current block's compute — the async-collective-friendly
    program order (start on the previous layer's boundary, consume one layer
    later). Depth 0 degrades to the plain fetch-at-use scan. ``remat=True``
    wraps the body save-nothing so backward re-issues the gathers instead of
    pinning every fetched block (stage-3 semantics)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_blocks = int(n_blocks)
    depth = max(int(prefetch_depth), 0)
    if depth == 0:
        def body(c, i):
            return block_fn(c, fetch(i), i), None
        if remat:
            body = jax.checkpoint(body, prevent_cse=False)
        out, _ = lax.scan(body, carry, jnp.arange(n_blocks))
        return out

    depth = min(depth, max(n_blocks - 1, 1))
    # pipeline fill: the first D blocks' gathers issue before the loop
    buf = tuple(fetch(jnp.int32(min(k, n_blocks - 1))) for k in range(depth))

    def body(state, i):
        c, buf = state
        # issue the lookahead gather FIRST — independent of this block's math
        # (tail iterations re-fetch the last block; the value is unused)
        nxt = fetch(jnp.minimum(i + depth, n_blocks - 1))
        c = block_fn(c, buf[0], i)
        return (c, buf[1:] + (nxt,)), None

    if remat:
        body = jax.checkpoint(body, prevent_cse=False)
    (out, _), _ = lax.scan(body, (carry, buf), jnp.arange(n_blocks))
    return out


def moe_chunked_scan(expert_fn, dispatch, n_chunks, depth=1, remat=True):
    """Chunked-expert streaming loop — the MoE twin of :func:`scheduled_scan`.

    ``dispatch(c)`` performs chunk ``c``'s dispatch all-to-all and returns the
    exchanged rows; ``expert_fn(rows, c)`` runs the expert GEMM on them (and
    typically the combine a2a) and returns the chunk's output. With ``depth``
    D >= 1 the loop issues ``dispatch(c + D)`` *before* ``expert_fn`` consumes
    chunk ``c`` — the next chunk's a2a has no data dependence on the current
    chunk's GEMM, so XLA's async-collective scheduling can run them
    concurrently: the ``a2a_chunks`` knob of :func:`moe_scheduled_intervals`
    made real program order. Depth 0 degrades to the serialized
    dispatch-at-use loop. Returns the stacked ``[n_chunks, ...]`` outputs in
    chunk order."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_chunks = int(n_chunks)
    depth = max(int(depth), 0)
    if depth == 0:
        def body(_, c):
            return None, expert_fn(dispatch(c), c)
        if remat:
            body = jax.checkpoint(body, prevent_cse=False)
        _, ys = lax.scan(body, None, jnp.arange(n_chunks))
        return ys

    depth = min(depth, max(n_chunks - 1, 1))
    # pipeline fill: the first D chunks' dispatches issue before the loop
    buf = tuple(dispatch(jnp.int32(min(k, n_chunks - 1))) for k in range(depth))

    def body(buf, c):
        # issue the lookahead dispatch FIRST — independent of this chunk's GEMM
        # (tail iterations re-dispatch the last chunk; the value is unused)
        nxt = dispatch(jnp.minimum(c + depth, n_chunks - 1))
        y = expert_fn(buf[0], c)
        return buf[1:] + (nxt,), y

    if remat:
        body = jax.checkpoint(body, prevent_cse=False)
    _, ys = lax.scan(body, buf, jnp.arange(n_chunks))
    return ys
