"""MiCS / hpZ — hierarchical ZeRO partitioning (reference ``runtime/zero/mics.py``,
``zero_hpz_partition_size`` in ``zero/config.py:39``).

The reference builds nested process groups (shard group within a node, replica
groups across nodes) and hand-writes hierarchical all-gathers
(``mics_utils.py``). On TPU the same capability is a *mesh factorization*
(``parallel/topology.py``): the data-parallel world splits into ``dpr``
(replica groups, DCN) × ``dp`` (shard group, ICI), and the partitioner
(``zero/partition.py``) picks which state shards over which factor:

- **MiCS** (``mics_shard_size``): master/optimizer/grads shard over ``dp``
  only, replicated across ``dpr``. XLA emits reduce-scatter inside the shard
  group plus a cross-group all-reduce — exactly MiCS's hierarchical pattern,
  but scheduled by the compiler.
- **hpZ** (``zero_hpz_partition_size``): optimizer state shards over the full
  world, while the stage-3 *working* (bf16) params — the reference's
  "secondary tensor" (``partition_parameters.py`` ``ds_secondary_tensor``) —
  shard only over ``dp``, so every backward all-gather rides ICI.

Config usage (identical keys to the reference)::

    {"zero_optimization": {"stage": 3, "zero_hpz_partition_size": 8}}
    {"zero_optimization": {"stage": 3, "mics_shard_size": 8}}

There is no ``MiCS_Init``/``MiCS_Optimizer`` class to thread through user
code: ``deepspeed_tpu.initialize`` reads the config keys and builds the
hierarchical mesh (``parallel/topology.py build_topology``).
"""

from deepspeed_tpu.parallel.topology import MeshTopology


def mics_topology(shard_size, devices=None, **axes):
    """Convenience constructor for a MiCS mesh (shard groups of
    ``shard_size``, replicated across the rest of the DP world)."""
    return MeshTopology(devices=devices, zero_shard_size=shard_size,
                        zero_hierarchy="mics", **axes)


def hpz_topology(partition_size, devices=None, **axes):
    """Convenience constructor for a ZeRO++ hpZ mesh (secondary parameter
    partition of ``partition_size``)."""
    return MeshTopology(devices=devices, zero_shard_size=partition_size,
                        zero_hierarchy="hpz", **axes)
