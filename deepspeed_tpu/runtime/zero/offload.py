"""ZeRO-Offload / ZeRO-Infinity — host- and NVMe-tier optimizer state.

Capability map to the reference:
- ``offload_optimizer.device=cpu`` (``zero/stage_1_and_2.py`` cpu-offload path,
  ``async_accumulate_grad_in_cpu_via_gpu:1177``): fp32 master weights + Adam
  moments live in host DRAM; gradients stream device→host at the boundary; the
  update runs in the native C++ CPU Adam (``csrc/adam/cpu_adam.cpp``); the bf16
  working copy streams back, produced in the same pass (fused param_copy).
- ``offload_optimizer.device=nvme`` (ZeRO-Infinity,
  ``swap_tensor/partitioned_optimizer_swapper.py``): moments additionally swap
  to NVMe through the async aio handle with next-leaf read-ahead.
- ``offload_optimizer.ratio`` (ZeRO-Offload++ Twin-Flow,
  ``blogs/deepspeed-offloadpp``): only that fraction of parameter elements
  (largest leaves first) is offloaded; the rest takes the normal on-device
  sharded optax path. Unlike the reference — which interleaves CUDA and CPU
  optimizers over flat shards — the split here is per-leaf, which keeps both
  sides a plain pytree and lets XLA overlap the device update with host I/O.

On TPU the device→host and host→device streams ride the PCIe DMA engines
while the TPU keeps executing dispatched XLA programs, so the overlap story
of the reference (CUDA streams) falls out of JAX's async dispatch.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import log_dist

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None


def _keystr(path):
    return jax.tree_util.keystr(path)


class HostOffloadOptimizer:
    """Owns the host tier: fp32 masters + moments for the offloaded leaves."""

    def __init__(self, params_f32_leaves, offload_config, opt_params, working_dtype,
                 opt_name="adamw"):
        """``params_f32_leaves``: dict keystr -> numpy fp32 initial values.
        ``opt_name``: adam/adamw (native SIMD step), adagrad, or lion
        (reference csrc/adagrad/cpu_adagrad.cpp, csrc/lion/cpu_lion.cpp)."""
        self.device = offload_config.device
        self.working_dtype = working_dtype
        self.opt_name = opt_name = opt_name.lower()
        wd = opt_params.get("weight_decay", 0.0)
        if opt_name in ("adam", "adamw"):
            # adam_w_mode defaults True for BOTH spellings, matching the
            # device-side optax mapping (ops/adam.py ADAM_W_MODE_DEFAULT):
            # offloaded and resident leaves must decay identically
            self.adam = DeepSpeedCPUAdam(
                lr=opt_params.get("lr", 1e-3),
                betas=tuple(opt_params.get("betas", (0.9, 0.999))),
                eps=opt_params.get("eps", 1e-8), weight_decay=wd,
                adamw_mode=opt_params.get("adam_w_mode", True))
        elif opt_name == "adagrad":
            from deepspeed_tpu.ops.cpu_adagrad import DeepSpeedCPUAdagrad
            self.adam = DeepSpeedCPUAdagrad(
                lr=opt_params.get("lr", 1e-2),
                eps=opt_params.get("eps", 1e-10), weight_decay=wd)
        elif opt_name == "lion":
            from deepspeed_tpu.ops.cpu_lion import DeepSpeedCPULion
            self.adam = DeepSpeedCPULion(
                lr=opt_params.get("lr", 1e-4),
                betas=tuple(opt_params.get("betas", (0.9, 0.99))),
                weight_decay=wd)
        else:
            raise ValueError(
                f"offload_optimizer supports adam/adamw/adagrad/lion host "
                f"steps, got {opt_name!r}")
        if self.device == "nvme" and opt_name not in ("adam", "adamw"):
            raise ValueError("NVMe optimizer-state swapping is Adam-only "
                             "(two-moment swap layout); use device 'cpu' for "
                             f"{opt_name}")
        # copy=True: device_get can hand back read-only views, and the host
        # tier updates masters in place
        self.masters = {k: np.array(v, dtype=np.float32, copy=True).reshape(-1)
                        for k, v in params_f32_leaves.items()}
        self.shapes = {k: np.asarray(v).shape for k, v in params_f32_leaves.items()}
        self._out_u16 = {k: np.empty(v.size, dtype=np.uint16)
                         for k, v in self.masters.items()}
        self.swapper = None
        if self.device == "nvme":
            from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import (
                PartitionedOptimizerSwapper)
            swap_dir = os.path.join(offload_config.nvme_path or "/tmp/ds_tpu_nvme",
                                    "optimizer")
            self.swapper = PartitionedOptimizerSwapper(
                swap_dir, buffer_count=offload_config.buffer_count,
                pipeline=offload_config.pipeline_read or offload_config.pipeline_write)
            for k, m in self.masters.items():
                self.swapper.register(k, m.size, async_op=True)
            self.swapper.flush()

    def step(self, grads, lr, scale):
        """Update all offloaded leaves. ``grads``: dict keystr -> numpy fp32
        (already fetched from device); ``scale`` multiplies grads (combines
        1/(gas*loss_scale) and clip coefficient). Returns dict keystr ->
        numpy working-precision arrays (flat) for device upload."""
        self.adam.begin_step()
        out = {}
        keys = list(grads)
        for i, k in enumerate(keys):
            g = np.ascontiguousarray(grads[k], dtype=np.float32).reshape(-1)
            if scale != 1.0:
                g = g * np.float32(scale)
            p = self.masters[k]
            want_bf16 = self.working_dtype == jnp.bfloat16
            u16 = self._out_u16[k] if want_bf16 else None
            if self.swapper is not None:
                nxt = keys[i + 1] if i + 1 < len(keys) else None
                m, v = self.swapper.fetch(k, prefetch_next=nxt)
                self.adam.update(k, p, g, out_bf16=u16, lr=lr, m=m, v=v)
                self.swapper.commit(k)
            else:
                self.adam.update(k, p, g, out_bf16=u16, lr=lr)
            if want_bf16 and _BF16 is not None:
                out[k] = u16.view(_BF16).reshape(self.shapes[k])
            elif self.working_dtype == jnp.float32:
                out[k] = p.reshape(self.shapes[k])
            else:  # fp16 or no ml_dtypes: numpy cast
                out[k] = p.astype(np.float16 if self.working_dtype == jnp.float16
                                  else np.float32).reshape(self.shapes[k])
        if self.swapper is not None:
            self.swapper.finish_step()
        return out

    # --- checkpointing ---
    def state_dict(self):
        """Host-tier state as one dict (masters + optimizer moments + step).
        Moment blob names follow the optimizer's MOMENT_NAMES (Adam: m/v,
        Adagrad: v, Lion: m)."""
        blobs = {f"master::{k}": v for k, v in self.masters.items()}
        if self.swapper is not None:
            for k, (m, v) in self.swapper.state_arrays().items():
                blobs[f"m::{k}"] = m
                blobs[f"v::{k}"] = v
        else:
            names = getattr(self.adam, "MOMENT_NAMES", ("m", "v"))
            for k in self.masters:
                for name, arr in zip(names,
                                     self.adam.state_for(k, self.masters[k].size)):
                    blobs[f"{name}::{k}"] = arr
        blobs["step_count"] = np.asarray(self.adam.step_count)
        return blobs

    def load_state_dict(self, blobs):
        self.adam.step_count = int(blobs["step_count"])
        names = getattr(self.adam, "MOMENT_NAMES", ("m", "v"))
        swap_states = {}
        for name in blobs:
            if name.startswith("master::"):
                self.masters[name[8:]] = np.ascontiguousarray(
                    blobs[name], dtype=np.float32)
        has_moments = any("::" in n and not n.startswith("master::")
                          for n in blobs)
        for k in self.masters:
            moms = [blobs[f"{nm}::{k}"] for nm in names if f"{nm}::{k}" in blobs]
            if len(moms) != len(names):
                if has_moments:
                    raise ValueError(
                        f"offload checkpoint moment blobs do not match the "
                        f"{self.opt_name} optimizer (expected {names} for "
                        f"leaf {k!r}; was it saved under a different "
                        f"optimizer?)")
                continue  # checkpoint carries no moment state at all
            if self.swapper is not None:
                swap_states[k] = tuple(moms)
            else:
                self.adam.set_state(k, *moms)
        if self.swapper is not None:
            self.swapper.load_state_arrays(swap_states)

    def save(self, path):
        np.savez(path, **self.state_dict())

    def load(self, path):
        data = np.load(path)
        self.load_state_dict({name: data[name] for name in data.files})


def select_offload_leaves(params_f32, ratio):
    """Pick leaves to offload: largest first until ``ratio`` of total elements
    (ZeRO-Offload++ partial offload). Returns (host_paths set, total, offloaded)."""
    leaves = jax.tree_util.tree_flatten_with_path(params_f32)[0]
    sized = sorted(((int(np.prod(l.shape)) if hasattr(l, "shape") else 1, _keystr(p))
                    for p, l in leaves), reverse=True)
    total = sum(s for s, _ in sized)
    budget = ratio * total
    host, acc = set(), 0
    for s, k in sized:
        if acc >= budget:
            break
        host.add(k)
        acc += s
    log_dist(f"ZeRO-Offload: {len(host)}/{len(sized)} leaves "
             f"({acc/max(total,1):.0%} of {total/1e6:.1f}M elements) on host tier",
             ranks=[0])
    return host, total, acc
