"""Sharded (partition-at-construction) parameter initialization — the
``zero.Init`` analog.

The reference's ``zero.Init`` (``runtime/zero/partition_parameters.py:783``)
monkey-patches ``nn.Module.__init__`` so every parameter is partitioned the
moment it is constructed, letting models larger than one device be built at
all. The TPU-native equivalent needs no patching: flax initialization is
already lazy, so we

  1. ``jax.eval_shape`` the model's init to get the abstract parameter tree
     (zero bytes allocated),
  2. derive the ZeRO + model-parallel shardings from the abstract tree via
     :class:`~deepspeed_tpu.runtime.zero.partition.ZeroPartitioner`,
  3. run the real init under ``jax.jit`` with those ``out_shardings`` —
     XLA materializes every parameter directly into its shard; no device
     (and no host) ever holds the full tree.

Used automatically by ``DeepSpeedEngine`` when ``model_parameters`` is omitted,
and available standalone as :func:`materialize_sharded` (e.g. to build the
param tree before constructing an engine). ``Init`` is the context-manager
spelling for reference API parity.
"""

import jax

from deepspeed_tpu.utils.logging import log_dist


def abstract_params(model, sample_batch, rng=None):
    """Shape-evaluate a flax model's parameter tree without allocating it."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    shapes = jax.eval_shape(lambda r: model.init(r, sample_batch), rng)
    return shapes["params"]


def materialize_sharded(model, sample_batch, partitioner, rng=None,
                        abstract=None):
    """Initialize ``model``'s parameters born-sharded per ``partitioner``.

    Returns the fp32 parameter tree laid out with the partitioner's *master*
    sharding (the stage>=1 fully-sharded layout), so no device holds more
    than its shard at any point during initialization. Pass ``abstract`` (a
    precomputed :func:`abstract_params` tree) to skip re-tracing the init.
    """
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    if abstract is None:
        abstract = abstract_params(model, sample_batch, rng)
    master_sh = partitioner.master_sharding(abstract)

    init_fn = jax.jit(lambda r, b: model.init(r, b)["params"],
                      out_shardings=master_sh)
    params = init_fn(rng, sample_batch)
    n = sum(x.size for x in jax.tree.leaves(params))
    log_dist(f"zero.Init: materialized {n/1e6:.2f}M params sharded "
             f"(stage {partitioner.stage}, world {partitioner.zero_world})",
             ranks=[0])
    return params


class Init:
    """Context-manager spelling for reference API parity
    (``deepspeed.zero.Init``). Construction in JAX/flax allocates nothing, so
    the context only captures the config/mesh used by
    :meth:`materialize` afterwards::

        with zero.Init(config=ds_config, mesh=topology) as zinit:
            model = LlamaForCausalLM(cfg)          # lazy — no allocation
        params = zinit.materialize(model, sample_batch)
    """

    def __init__(self, config=None, mesh=None, rng=None):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        from deepspeed_tpu.parallel.topology import MeshTopology
        self.config = config if isinstance(config, DeepSpeedConfig) \
            else DeepSpeedConfig(config or {})
        if mesh is not None and not isinstance(mesh, MeshTopology):
            raise ValueError("pass a deepspeed_tpu.parallel.topology.MeshTopology")
        self.topology = mesh if mesh is not None else MeshTopology()
        self.rng = rng

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def materialize(self, model, sample_batch):
        from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
        abstract = abstract_params(model, sample_batch, self.rng)
        specs = None
        if hasattr(model, "param_specs"):
            try:
                specs = model.param_specs(abstract)
            except Exception:
                specs = None
        partitioner = ZeroPartitioner(self.topology, self.config.zero_config,
                                      param_specs=specs)
        return materialize_sharded(model, sample_batch, partitioner, self.rng,
                                   abstract=abstract)
