"""Dataloader (mirrors reference ``deepspeed/runtime/dataloader.py``).

``DeepSpeedDataLoader`` wraps any indexable dataset (dict-of-arrays, list of
samples, or an iterable of ready batches) and yields numpy batches of the
*global* batch size; the engine shards them over the (dp, ep) × sp mesh axes at
device_put time, which is the TPU analog of the reference's DistributedSampler
(each rank reading its slice). ``RepeatingLoader`` is a faithful port of the
reference's infinite wrapper.
"""

import numpy as np

import jax


class DeepSpeedDataLoader:

    def __init__(self, dataset, batch_size, collate_fn=None, topology=None,
                 shuffle=True, seed=0, drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.topology = topology
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self._epoch = 0
        if hasattr(dataset, "__len__") and not isinstance(dataset, dict):
            self.num_samples = len(dataset)
        elif isinstance(dataset, dict):
            self.num_samples = len(next(iter(dataset.values())))
        else:
            self.num_samples = None  # pure iterable

    def __len__(self):
        if self.num_samples is None:
            raise TypeError("iterable dataset has no length")
        n = self.num_samples // self.batch_size
        if not self.drop_last and self.num_samples % self.batch_size:
            n += 1
        return n

    def _index_batches(self):
        idx = np.arange(self.num_samples)
        if self.shuffle:
            self._rng.shuffle(idx)
        end = (self.num_samples // self.batch_size) * self.batch_size if self.drop_last \
            else self.num_samples
        for start in range(0, end, self.batch_size):
            yield idx[start:start + self.batch_size]

    def __iter__(self):
        self._epoch += 1
        if self.num_samples is None:
            yield from self.dataset
            return
        for batch_idx in self._index_batches():
            if isinstance(self.dataset, dict):
                batch = {k: np.asarray(v)[batch_idx] for k, v in self.dataset.items()}
            else:
                samples = [self.dataset[int(i)] for i in batch_idx]
                if self.collate_fn is not None:
                    batch = self.collate_fn(samples)
                else:
                    batch = jax.tree.map(lambda *xs: np.stack(xs), *samples)
            yield batch


class RepeatingLoader:
    """reference ``runtime/dataloader.py`` RepeatingLoader."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class PrefetchLoader:
    """Background batch assembly + ahead-of-time device placement.

    The synchronous loader assembles the next batch and pays the host→HBM
    transfer INSIDE the step gap; this wrapper runs assembly in a worker
    thread and ``jax.device_put``s up to ``depth`` batches onto the mesh
    while the current step computes — the input pipeline overlaps with
    device work (the reference gets this from torch DataLoader workers +
    pin_memory/CUDA-stream copies; on TPU the async dispatch of device_put
    is the copy stream).

    Args:
        loader: any iterable of pytree batches (numpy leaves).
        sharding: optional ``jax.sharding.Sharding`` (or pytree of) applied
            at device_put — pass ``engine.topology.batch_sharding()`` so
            batches land pre-sharded; ``None`` leaves host arrays for the
            engine's own placement.
        depth: number of batches resident ahead of the consumer.
    """

    _END = object()

    def __init__(self, loader, sharding=None, depth=2):
        self.loader = loader
        self.sharding = sharding
        self.depth = max(1, int(depth))
        self._active_cancel = None  # cancels the previous pass's worker

    def __len__(self):
        return len(self.loader)

    def __getattr__(self, name):
        # delegate the wrapped loader's surface (batch_size, dataset, ...)
        return getattr(self.loader, name)

    def _put(self, batch):
        if self.sharding is None:
            return batch
        import jax.tree_util as jtu
        if jtu.all_leaves([self.sharding]):
            return jax.tree.map(lambda x: jax.device_put(x, self.sharding),
                                batch)
        return jax.tree.map(jax.device_put, batch, self.sharding)

    def __iter__(self):
        import queue
        import threading
        # fresh queue/worker per pass: sharing them across iterations would
        # leak a previous pass's leftover batches (and its _END) into this
        # one. A semaphore of `depth` bounds RESIDENT device batches to
        # exactly depth — the worker only device_puts after securing a slot.
        q = queue.Queue()
        slots = threading.Semaphore(self.depth)
        cancel = threading.Event()
        if self._active_cancel is not None:
            self._active_cancel.set()  # release an abandoned pass's worker
        self._active_cancel = cancel

        def worker():
            try:
                for batch in self.loader:
                    while not slots.acquire(timeout=0.1):
                        if cancel.is_set():
                            return
                    if cancel.is_set():
                        return
                    # device_put dispatches async: transfer overlaps compute
                    q.put(self._put(batch))
            except Exception as e:  # surfaced at the consumer's next next()
                q.put(e)
                return
            q.put(self._END)

        threading.Thread(target=worker, daemon=True).start()
        while True:
            item = q.get()
            if item is self._END:
                return
            if isinstance(item, Exception):
                raise item
            try:
                yield item
            finally:
                slots.release()
