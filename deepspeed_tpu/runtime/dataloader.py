"""Dataloader (mirrors reference ``deepspeed/runtime/dataloader.py``).

``DeepSpeedDataLoader`` wraps any indexable dataset (dict-of-arrays, list of
samples, or an iterable of ready batches) and yields numpy batches of the
*global* batch size; the engine shards them over the (dp, ep) × sp mesh axes at
device_put time, which is the TPU analog of the reference's DistributedSampler
(each rank reading its slice). ``RepeatingLoader`` is a faithful port of the
reference's infinite wrapper.
"""

import numpy as np

import jax


class DeepSpeedDataLoader:

    def __init__(self, dataset, batch_size, collate_fn=None, topology=None,
                 shuffle=True, seed=0, drop_last=True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.topology = topology
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)
        self._epoch = 0
        if hasattr(dataset, "__len__") and not isinstance(dataset, dict):
            self.num_samples = len(dataset)
        elif isinstance(dataset, dict):
            self.num_samples = len(next(iter(dataset.values())))
        else:
            self.num_samples = None  # pure iterable

    def __len__(self):
        if self.num_samples is None:
            raise TypeError("iterable dataset has no length")
        n = self.num_samples // self.batch_size
        if not self.drop_last and self.num_samples % self.batch_size:
            n += 1
        return n

    def _index_batches(self):
        idx = np.arange(self.num_samples)
        if self.shuffle:
            self._rng.shuffle(idx)
        end = (self.num_samples // self.batch_size) * self.batch_size if self.drop_last \
            else self.num_samples
        for start in range(0, end, self.batch_size):
            yield idx[start:start + self.batch_size]

    def __iter__(self):
        self._epoch += 1
        if self.num_samples is None:
            yield from self.dataset
            return
        for batch_idx in self._index_batches():
            if isinstance(self.dataset, dict):
                batch = {k: np.asarray(v)[batch_idx] for k, v in self.dataset.items()}
            else:
                samples = [self.dataset[int(i)] for i in batch_idx]
                if self.collate_fn is not None:
                    batch = self.collate_fn(samples)
                else:
                    batch = jax.tree.map(lambda *xs: np.stack(xs), *samples)
            yield batch


class RepeatingLoader:
    """reference ``runtime/dataloader.py`` RepeatingLoader."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
