"""Loss scaling (mirrors reference ``deepspeed/runtime/fp16/loss_scaler.py:42,67``).

``LossScaler`` is static; ``DynamicLossScaler`` doubles after
``scale_window`` consecutive overflow-free steps and halves (with hysteresis)
on overflow. Here the scaler state is a small pytree updated *inside* the jitted
apply step with ``lax.cond``-free arithmetic, so overflow skipping costs no
host sync.
"""

from typing import NamedTuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    loss_scale: jnp.ndarray      # f32 scalar
    good_steps: jnp.ndarray      # i32 consecutive overflow-free steps
    hysteresis: jnp.ndarray      # i32 remaining tolerated overflows before halving


def init_loss_scale_state(fp16_config, static_scale=None):
    if static_scale is None:
        static_scale = fp16_config.loss_scale
    if static_scale and static_scale > 0:
        init = float(static_scale)
    else:
        init = float(2.0 ** fp16_config.initial_scale_power)
    return LossScaleState(loss_scale=jnp.float32(init),
                          good_steps=jnp.int32(0),
                          hysteresis=jnp.int32(fp16_config.hysteresis))


def update_loss_scale(state, found_inf, fp16_config, dynamic):
    """One ``DynamicLossScaler.update_scale`` step (reference loss_scaler.py:67)
    as branch-free arithmetic. Returns the new state."""
    if not dynamic:
        return state
    window = fp16_config.loss_scale_window
    min_scale = fp16_config.min_loss_scale
    found_inf = found_inf.astype(jnp.bool_)

    # on overflow: consume hysteresis; halve scale only when hysteresis exhausted
    hys_left = jnp.where(found_inf, jnp.maximum(state.hysteresis - 1, 0), state.hysteresis)
    do_halve = found_inf & (state.hysteresis <= 1)
    scale = jnp.where(do_halve, jnp.maximum(state.loss_scale / 2.0, min_scale), state.loss_scale)

    good = jnp.where(found_inf, 0, state.good_steps + 1)
    do_grow = (~found_inf) & (good % window == 0) & (good > 0)
    scale = jnp.where(do_grow, scale * 2.0, scale)
    # reset hysteresis on successful growth interval (consecutive_hysteresis=False default)
    hys = jnp.where(do_grow | ((~found_inf)
                               & (not fp16_config.consecutive_hysteresis)),
                    jnp.int32(fp16_config.hysteresis), hys_left)
    return LossScaleState(loss_scale=scale, good_steps=good, hysteresis=hys)
