"""Progressive layer drop (reference ``runtime/progressive_layer_drop.py``).

PLD accelerates BERT-style pretraining by stochastically skipping transformer
layers with a keep probability theta(t) that decays over training:

    theta(t) = (1 - theta_min) * gamma_decay(t) + theta_min,
    where gamma_decay(t) = exp(-gamma * t)  -> theta decays from 1 to theta_min

The reference injects ``progressive_layer_drop`` kwargs into forward
(``engine.py:1826-1828``); here models consume ``theta`` via
``should_keep_layer`` inside their scan body (a Bernoulli draw per layer —
static shapes preserved by weighting the residual branch, not by skipping
compilation)."""

import math

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:

    def __init__(self, theta=0.5, gamma=0.001):
        """``theta``: final (minimum) keep probability; ``gamma``: decay rate
        (reference defaults)."""
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        decay = math.exp(-self.gamma * global_step)
        self.current_theta = (1.0 - self.theta) * decay + self.theta
        return self.current_theta


def should_keep_layer(rng, layer_idx, theta):
    """Per-layer Bernoulli keep draw; deeper layers drop more often
    (keep prob theta^(i/L) scaling is left to the caller — the reference uses
    a uniform theta per step)."""
    return jax.random.bernoulli(jax.random.fold_in(rng, layer_idx), theta)


def pld_residual(keep, layer_out, residual, theta):
    """Stochastic-depth combine: keep ? residual + layer_out/theta : residual
    (inverted scaling keeps expectation; static shapes either way)."""
    scale = jnp.where(theta > 0, 1.0 / jnp.maximum(theta, 1e-6), 1.0)
    return jnp.where(keep, residual + layer_out * scale, residual)
