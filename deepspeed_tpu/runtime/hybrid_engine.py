"""Hybrid engine — train + generate in one engine (RLHF).

Reference ``runtime/hybrid_engine.py:32`` (``DeepSpeedHybridEngine``):
RLHF rollout needs fast generation from the *training* weights, so the
reference flips ZeRO-3 partitioned params into inference kernel containers
before ``generate`` (:174) and back afterwards (``_zero3_forward`` :363),
with LoRA fuse/unfuse around each flip.

On TPU the flip is unnecessary by construction: training params are GSPMD
global arrays — the KV-cached decode program simply *reads the same buffers*
under their training shardings, and XLA inserts whatever gathers the decode
needs (the analog of the reference's gather-once-per-generate, but scheduled
by the compiler and cached per shape). What remains of the reference surface:

- ``generate()``: jitted prefill + while-loop decode over the live weights
  (inference/generation.py), with qwZ int8 weights dequantized in-trace.
- ``eval()`` / ``train()``: mode flags (reference nn.Module semantics).
- per-call latency bookkeeping (reference ``_generate_latency`` timers).
"""

import time

import jax

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedHybridEngine(DeepSpeedEngine):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._training_mode = True
        self._generate_latency = 0.0
        self._generate_tokens = 0
        self._num_generations = 0
        he = self.config.hybrid_engine
        self._max_out_tokens = he.get("max_out_tokens", 512)
        log_dist("DeepSpeedHybridEngine: generation reads training shards "
                 "in place (no container flip needed under GSPMD)", ranks=[0])

    # --- mode flags (reference module.eval()/train() flow) ---
    def eval(self):
        self._training_mode = False
        return self

    def train(self, mode=True):
        self._training_mode = mode
        return self

    def is_in_training_mode(self):
        return self._training_mode

    # --- LoRA (reference hybrid_engine fuse/unfuse_lora_weight) ---
    def configure_lora(self, lora):
        """Attach an adapter pytree (``runtime/lora.py``); generation reads
        the merged view, training params stay untouched."""
        from deepspeed_tpu.runtime.lora import merged_view
        assert not getattr(self, "_lora_fused", False), \
            "unfuse_lora_weight() before configuring a new adapter — the " \
            "previous delta is baked into the params"
        self._lora = lora
        self._lora_fused = False
        self._lora_merge_fn = jax.jit(merged_view)  # built once: jit caches

    def fuse_lora_weight(self):
        """Explicit merge into the training params (reference semantics —
        e.g. before exporting rollout weights). While fused, generation skips
        the in-trace merge — the delta must never apply twice."""
        from deepspeed_tpu.runtime.lora import fuse_lora
        assert getattr(self, "_lora", None) is not None
        assert not self._lora_fused, "LoRA already fused"
        self.state = self.state._replace(
            params=fuse_lora(self.state.params, self._lora))
        self._lora_fused = True

    def unfuse_lora_weight(self):
        from deepspeed_tpu.runtime.lora import unfuse_lora
        assert getattr(self, "_lora", None) is not None
        assert self._lora_fused, "LoRA is not fused"
        self.state = self.state._replace(
            params=unfuse_lora(self.state.params, self._lora))
        self._lora_fused = False

    def _inference_params(self):
        """The weights generation reads: the live working copy, dequantized
        when qwZ stores it as int8 (the reference's gather+dequant flip),
        with LoRA adapters merged in-trace when configured (and not already
        fused into the params)."""
        p = self.state.params
        if self.quantized_weights:
            if not hasattr(self, "_dequant_fn"):
                self._dequant_fn = jax.jit(self._dequantize_working)
            p = self._dequant_fn(p)
        if getattr(self, "_lora", None) is not None and not self._lora_fused:
            p = self._lora_merge_fn(p, self._lora)
        return p

    def generate(self, input_ids, max_new_tokens=None, temperature=0.0,
                 top_k=0, top_p=1.0, eos_token_id=None, rng=None):
        """RLHF rollout generation (reference ``hybrid_engine.generate`` :174).
        Requires the wrapped model to support the KV-cache contract
        (``use_cache=True``; see models/llama.py)."""
        assert hasattr(self.module, "apply"), \
            "hybrid engine generation needs a flax module with a KV-cache path"
        from deepspeed_tpu.inference.generation import generate as _generate
        max_new_tokens = max_new_tokens or self._max_out_tokens
        t0 = time.perf_counter()
        out = _generate(self.module, self._inference_params(), input_ids,
                        max_new_tokens=max_new_tokens, temperature=temperature,
                        top_k=top_k, top_p=top_p, eos_token_id=eos_token_id,
                        rng=rng)
        out = jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self._generate_latency += dt
        self._num_generations += 1
        self._generate_tokens += int(out.shape[0]) * int(out.shape[1])
        return out

    def generation_stats(self):
        """(total seconds, generations, tokens, tokens/sec) — the reference's
        latency bookkeeping used by DS-Chat throughput reports."""
        tps = self._generate_tokens / self._generate_latency \
            if self._generate_latency else 0.0
        return {"latency_s": self._generate_latency,
                "generations": self._num_generations,
                "tokens": self._generate_tokens,
                "tokens_per_sec": tps}
