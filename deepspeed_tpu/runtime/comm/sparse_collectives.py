"""Sparse (factored) gradient collectives — device-side, static-shape.

Reference: the engine's sparse embedding-gradient allreduce
(``deepspeed/runtime/engine.py:2470-2539``): embedding grads touch at most
``batch x seq`` of the ``vocab`` rows, so ranks exchange (indices, values)
pairs instead of the dense [V, D] table.

TPU design: the nonzero rows of an embedding gradient are exactly the batch's
token ids, whose COUNT is static — so the whole factored exchange stays
inside jit with fixed shapes:

1. :func:`dedupe_rows` — sort ids, segment-sum duplicate rows (a local
   gradient already sums duplicates; dedupe prevents double-counting when
   gathering rows *from* the dense local grad);
2. gather the deduped rows from the local dense grad;
3. ``all_gather`` (ids, rows) over the data axis — traffic
   ``world x N x (D+1)`` vs ``V x D`` for a dense psum;
4. scatter-add everything back into a dense table (out-of-range pad ids are
   dropped).

Use inside ``jax.shard_map`` bodies (the engine's manual-mode grad paths);
for host-side numpy SparseTensors see ``runtime/sparse_tensor.py``.
"""

import jax
import jax.numpy as jnp
from jax import lax


def dedupe_rows(ids, rows, pad_id):
    """Sum rows of duplicate ids into one slot each, padding the rest.

    ids [N] int, rows [N, D]. Returns (uids [N], vals [N, D]) where the
    first k slots (k = unique count) hold the unique ids and their summed
    rows; remaining slots hold ``pad_id`` and zero rows. Pure static shapes.
    """
    order = jnp.argsort(ids)
    sid = ids[order]
    srow = rows[order]
    is_new = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(is_new) - 1                       # [N] segment number
    uids = jnp.full(ids.shape, pad_id, ids.dtype).at[seg].set(sid)
    vals = jax.ops.segment_sum(srow, seg, num_segments=ids.shape[0])
    return uids, vals


def sparse_all_reduce(dense_grad, ids, axis_name):
    """Factored allreduce of an embedding gradient inside shard_map.

    ``dense_grad`` [V, D]: this device's LOCAL (unreduced) gradient whose
    nonzero rows are a subset of ``ids`` [N] (the device's token ids, possibly
    with duplicates). Returns the dense [V, D] sum over ``axis_name`` — equal
    to ``lax.psum(dense_grad, axis_name)`` whenever the nonzero-row invariant
    holds, at ``N x (D+1)`` per-device traffic instead of ``V x D``.
    """
    V = dense_grad.shape[0]
    uids, _ = dedupe_rows(ids, jnp.zeros((ids.shape[0], 1),
                                         dense_grad.dtype), V)
    rows = jnp.take(dense_grad, uids, axis=0, mode="fill", fill_value=0)
    all_ids = lax.all_gather(uids, axis_name, tiled=True)      # [W*N]
    all_rows = lax.all_gather(rows, axis_name, tiled=True)     # [W*N, D]
    return jnp.zeros_like(dense_grad).at[all_ids].add(
        all_rows, mode="drop")


def sparse_exchange(ids, rows, axis_name, pad_id):
    """All-gather the factored form itself: (ids [N], rows [N, D]) ->
    (all_ids [W*N], all_rows [W*N, D]), deduped locally first. The caller
    scatters into whatever layout it wants (e.g. only its optimizer shard)."""
    uids, vals = dedupe_rows(ids, rows, pad_id)
    return (lax.all_gather(uids, axis_name, tiled=True),
            lax.all_gather(vals, axis_name, tiled=True))
