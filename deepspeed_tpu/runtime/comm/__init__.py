from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce  # noqa: F401
