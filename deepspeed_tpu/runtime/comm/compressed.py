"""Error-feedback sign-compressed allreduce.

The capability analog of the reference's compressed-communication backends
(``deepspeed/runtime/comm/nccl.py:51`` ``NcclBackend.compressed_allreduce``,
``runtime/comm/mpi.py``, ``runtime/comm/hccl.py``): a two-phase allreduce that
transmits one sign bit per element plus one fp32 scale per tensor, with
worker- and server-side error feedback so compression noise averages out over
steps (the 1-bit Adam family relies on this).

TPU-native shape: the reference packs sign bits with cupy and issues NCCL
alltoall/allgather by hand; here the same algorithm is a pure function over
``jax.lax`` collectives, meant to run inside ``shard_map`` over a mesh axis —
typically the DCN-crossing axis, where 32x wire compression actually matters
(ICI-local reductions are better served by plain ``psum``).

Wire format: signs bit-packed to uint8 (``jnp.packbits``) + a single fp32
scale, so the all_to_all/all_gather really move 1 bit per element.
"""

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils import jax_compat  # noqa: F401  installs lax.axis_size on old jax


def sign_compress(x, error, mask=None):
    """Error-feedback sign compression core, shared by the wire-level
    collective below and the 1-bit optimizer family (``ops/onebit.py``).

    Returns ``(decompressed, new_error, scale, bits)`` where ``decompressed =
    scale * sign(x + error)`` and ``new_error`` is the residual actually left
    unapplied. The scale preserves the l2 norm (reference nccl.py:
    ``norm/sqrt(numel)``); zeros compress to +1 like
    torch.sign-with-bit-packing does.

    ``mask`` zeroes coordinates that must not receive compressed magnitude
    (e.g. coordinates whose frozen Adam variance is exactly 0 — dead ReLU
    units — where ``1/(sqrt(0)+eps)`` would blow the update up); the residual
    stays consistent with what was actually applied.
    """
    corrected = x + error
    scale = jnp.linalg.norm(corrected.reshape(-1)) / jnp.sqrt(jnp.float32(corrected.size))
    bits = (corrected >= 0)
    decompressed = scale * jnp.where(bits, 1.0, -1.0).astype(x.dtype)
    if mask is not None:
        decompressed = jnp.where(mask, decompressed, 0.0)
    return decompressed, corrected - decompressed, scale, bits


def _compress(flat, error):
    """Wire form: sign-compress → (packed_bits, scale, new_error)."""
    decompressed, new_error, scale, bits = sign_compress(flat, error)
    return jnp.packbits(bits), scale, new_error


def _decompress(packed, scale, n, dtype):
    bits = jnp.unpackbits(packed)[:n]
    return scale * jnp.where(bits, 1.0, -1.0).astype(dtype)


def compressed_allreduce(tensor, worker_error, server_error, axis_name="dp"):
    """Average ``tensor`` over ``axis_name`` using 1-bit compression.

    Must be called inside ``shard_map``/``pmap`` with ``axis_name`` bound.
    ``worker_error`` has ``tensor.size`` elements (padded size — see
    ``error_shapes``); ``server_error`` has ``tensor.size // world`` elements.
    Both are device-local state the caller threads between steps (the reference
    stores them on the optimizer, e.g. ``fp16/onebit/adam.py``).

    Returns ``(averaged, new_worker_error, new_server_error)``.
    """
    world = lax.axis_size(axis_name)
    flat = tensor.reshape(-1).astype(jnp.float32)
    n = flat.size
    # pad so each of the `world` chunks is a whole number of packed bytes
    chunk = -(-n // world)
    chunk = -(-chunk // 8) * 8
    padded = chunk * world
    flat = jnp.pad(flat, (0, padded - n))
    assert worker_error.size == padded and server_error.size == chunk, (
        f"error buffers must be sized by error_shapes(): need ({padded},)/({chunk},), "
        f"got ({worker_error.size},)/({server_error.size},)")

    # phase 1 — worker compression + all_to_all of packed chunks
    packed, scale, new_worker_error = _compress(flat, worker_error.reshape(-1))
    # (world, chunk/8) uint8 — each rank receives its chunk from every rank
    recv = lax.all_to_all(packed.reshape(world, chunk // 8), axis_name,
                          split_axis=0, concat_axis=0, tiled=False)
    scales = lax.all_gather(scale, axis_name)  # (world,)

    # server-side average of this rank's chunk over all workers
    bits = jnp.unpackbits(recv, axis=1)  # (world, chunk)
    signs = jnp.where(bits, 1.0, -1.0).astype(jnp.float32)
    server_chunk = (signs * scales[:, None]).mean(axis=0)

    # phase 2 — server compression + all_gather of packed server chunks
    packed_s, scale_s, new_server_error = _compress(server_chunk, server_error.reshape(-1))
    gathered = lax.all_gather(packed_s, axis_name, axis=0, tiled=True)
    scales_s = lax.all_gather(scale_s, axis_name)  # (world,)
    bits_g = jnp.unpackbits(gathered).reshape(world, chunk)
    out = (jnp.where(bits_g, 1.0, -1.0) * scales_s[:, None]).reshape(-1)[:n]
    return out.reshape(tensor.shape).astype(tensor.dtype), new_worker_error, new_server_error


def error_shapes(n, world):
    """Shapes of (worker_error, server_error) buffers for an n-element tensor:
    per-rank chunk rounded up to whole packed bytes."""
    chunk = (-(-n // world) + 7) // 8 * 8
    return (chunk * world,), (chunk,)


def init_error_buffers(n, world, dtype=jnp.float32):
    w, s = error_shapes(n, world)
    return jnp.zeros(w, dtype), jnp.zeros(s, dtype)
