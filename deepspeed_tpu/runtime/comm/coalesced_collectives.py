"""Coalesced + quantized collectives — ZeRO++ comm kernels.

Reference ``runtime/comm/coalesced_collectives.py``:
- ``reduce_scatter_coalesced`` (:31): one fused reduce-scatter over many
  tensors.
- ``all_to_all_quant_reduce`` (:81, qgZ): gradients are int4-quantized,
  exchanged all-to-all *within* the node, reduced locally, int8-quantized and
  exchanged across nodes, reduced again — 4x less cross-node traffic.

TPU mapping: these run inside ``shard_map`` over mesh axes. The hierarchy is
``dp`` (intra-slice ICI, the reference's intra-node NVLink) and ``dpr``
(cross-slice DCN, the reference's inter-node IB) — see
``parallel/topology.py``. qwZ (``zero_quantized_weights``) is
``quantized_all_gather``: the wire format is int8 + per-group scales.

The quantize / dequantize halves are the ``ops/pallas/quant_collective``
kernel pair (``block_quantize`` / ``block_dequantize_reduce``, jnp fallback
off-TPU): the dequant+sum of the exchange is fused into one VMEM pass, and
nothing wider than the wire payload is ever materialized per peer. Every
exchange records trace-time comm telemetry with both the logical fp32 bytes
(comparable with the unquantized path) and the true ``wire_bytes``
(packed ints + fp32 group scales) per mesh axis.
"""

import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils import jax_compat  # noqa: F401  installs lax.axis_size on old jax

from deepspeed_tpu.ops.pallas.quant_collective import (
    block_dequantize,
    block_dequantize_reduce,
    block_quantize,
    wire_nbytes,
)


def _record_wire(op, axis, logical_numel, wire):
    """Trace-time comm record: logical fp32 bytes + true wire bytes."""
    from deepspeed_tpu import telemetry
    if telemetry.enabled():
        telemetry.record_comm(op, int(logical_numel) * 4, 0.0, axis=axis,
                              traced=True, wire_bytes=int(wire))


def reduce_scatter_coalesced(tensors, axis_name="dp"):
    """Fused reduce-scatter of a list of tensors over ``axis_name``
    (reference :31). Each tensor is flattened; every rank gets back its
    1/world shard of each (padded to divide evenly)."""
    world = lax.axis_size(axis_name)
    out = []
    for t in tensors:
        flat = t.reshape(-1)
        pad = (-flat.shape[0]) % world
        if pad:
            flat = jnp.pad(flat, (0, pad))
        out.append(lax.psum_scatter(flat.reshape(world, -1), axis_name,
                                    scatter_dimension=0, tiled=False))
    return out


def quantized_all_gather(x, axis_name="dp", num_bits=8, group_size=2048,
                         dtype=jnp.float32):
    """qwZ: all-gather with an int8 wire format (reference qwZ quantized
    all-gather: ``partition_parameters.py:728`` CUDAQuantizer +
    ``csrc/quantization/swizzled_quantize.cu``). Gathers ``x`` (this rank's
    shard) from every rank along ``axis_name``; only int8 values + fp32
    group scales cross the wire, and each gathered shard row dequantizes
    straight into its output slot — no fp32 ``[world, *shape]`` staging
    pass."""
    world = lax.axis_size(axis_name)
    flat = x.reshape(-1)
    q, scale = block_quantize(flat, num_bits=num_bits, group_size=group_size,
                              local=True)
    _record_wire("all_gather_quant", axis_name, flat.shape[0],
                 wire_nbytes(flat.shape[0], num_bits, group_size))
    qg = lax.all_gather(q, axis_name)        # [world, wire]
    sg = lax.all_gather(scale, axis_name)    # [world, groups]
    full = block_dequantize(qg, sg, num_bits=num_bits, group_size=group_size,
                            out_len=flat.shape[0], dtype=dtype, local=True)
    return full.reshape((world * x.shape[0],) + x.shape[1:])


def exchange_reduce(blocks, axis, bits, group_size=2048, return_error=False):
    """Quantized all-to-all + fused dequant-reduce: the qgZ exchange
    primitive.

    ``blocks``: [peers, m] — row j is this rank's payload destined for peer j.
    Each row is groupwise-quantized to ``bits``, exchanged over ``axis``
    (row j -> peer j), and dequant-summed in one kernel pass: returns this
    rank's [m] partial sum over the ``axis`` group.

    ``return_error=True`` additionally returns the local quantization
    residual ``blocks - dequantize(quantize(blocks))`` ([peers, m], computed
    from this rank's own outgoing wire payload, no extra comm) — the
    error-feedback carry for the next step."""
    P, m = blocks.shape
    q, s = block_quantize(blocks, num_bits=bits, group_size=group_size,
                          local=True)
    _record_wire("all_to_all_quant", axis, blocks.size,
                 P * wire_nbytes(m, bits, group_size))
    qx = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
    sx = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=False)
    out = block_dequantize_reduce(qx, sx, num_bits=bits,
                                  group_size=group_size, out_len=m,
                                  local=True)
    if return_error:
        err = blocks - block_dequantize(q, s, num_bits=bits,
                                        group_size=group_size, out_len=m,
                                        local=True)
        return out, err
    return out


def expert_all_to_all(x, axis, bits=None, group_size=2048,
                      op="a2a_dispatch"):
    """MoE expert dispatch/combine all-to-all of per-peer payload blocks.

    ``x``: [peers, ...] — block j is this rank's payload for peer j along
    ``axis``; returns [peers, ...] where block j is what peer j sent here.

    ``bits`` None keeps the payload's own dtype on the wire (the ICI
    default: wire bytes == payload bytes). ``bits`` set routes each peer
    block through the qwZ/qgZ kernel pair — only packed ints + fp32 group
    scales cross the link (the DCN leg). Either way telemetry records the
    exchange under ``op`` ("a2a_dispatch" / "a2a_combine" — the overlap
    scheduler's MoE stream classes) with logical fp32 bytes and true wire
    bytes.

    The quantized leg is forward-only (round-to-nearest has no useful VJP);
    training paths keep ``bits=None`` unless they carry their own error
    feedback like ``exchange_reduce`` callers do."""
    P = x.shape[0]
    if bits is None:
        _record_wire(op, axis, x.size, x.size * x.dtype.itemsize)
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=False)
    blocks = x.reshape(P, -1).astype(jnp.float32)
    m = blocks.shape[1]
    q, s = block_quantize(blocks, num_bits=bits, group_size=group_size,
                          local=True)
    _record_wire(op, axis, x.size, P * wire_nbytes(m, bits, group_size))
    qx = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
    sx = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=False)
    out = block_dequantize(qx, sx, num_bits=bits, group_size=group_size,
                           out_len=m, local=True)
    return out.reshape(x.shape).astype(x.dtype)


def moe_hierarchical_a2a(x, intra_axis="ep", inter_axis="dpr", inter_bits=8,
                         group_size=2048, op="a2a_dispatch"):
    """hpZ-split expert all-to-all over a two-level expert world.

    ``x``: [inter, intra, ...] — block (a, b) is this rank's payload for the
    peer at inter index ``a`` (DCN) and intra index ``b`` (ICI). Returns
    [inter, intra, ...] where block (a, b) holds what THAT peer sent here.

    Stage 1 exchanges full precision over ``intra_axis`` (ICI — bytes are
    nearly free); stage 2 exchanges ``inter_bits`` over ``inter_axis`` (DCN
    — the leg ``perf_gate check_moe_wire`` caps at ≤ 0.5x fp32). Same
    hierarchy split as qgZ/hpZ in :func:`all_to_all_quant_reduce`, but
    payload-preserving (no reduce) — expert tokens must arrive intact."""
    # stage 1 (ICI, fp): lead with the intra destination. Result is
    # [intra_src, inter_dest, ...]: each intra peer now holds the slab its
    # group routed to this intra index, still grouped by inter destination.
    y = expert_all_to_all(jnp.swapaxes(x, 0, 1), intra_axis, bits=None,
                          group_size=group_size, op=op)
    # stage 2 (DCN, quantized): lead with the inter destination. Result is
    # [inter_src, intra_src, ...] — payload from every (a, b) peer.
    return expert_all_to_all(jnp.swapaxes(y, 0, 1), inter_axis,
                             bits=inter_bits, group_size=group_size, op=op)


def all_to_all_quant_reduce(x, intra_axis="dp", inter_axis=None,
                            intra_bits=4, inter_bits=8, group_size=2048,
                            dtype=jnp.float32):
    """qgZ: hierarchical quantized gradient reduction (reference :81).

    ``x`` is this rank's full-size gradient; the result is this rank's
    1/world flat shard of the *sum* over all ranks (world = intra × inter).
    Stage 1 int4-quantizes per destination block and all-to-alls within
    ``intra_axis`` (ICI), then dequant-reduces; stage 2 (when ``inter_axis``
    is given) repeats with int8 across ``inter_axis`` (DCN). Cross-DCN bytes
    are inter_bits/32 of an fp32 reduce-scatter."""

    intra = lax.axis_size(intra_axis)
    inter = lax.axis_size(inter_axis) if inter_axis else 1
    world = intra * inter
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % world
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = flat.shape[0] // world

    # stage 1 (ICI): each intra-peer block carries all its inter-shards
    partial = exchange_reduce(flat.reshape(intra, inter * shard),
                              intra_axis, intra_bits, group_size)
    if inter == 1:
        return partial.astype(dtype)
    # stage 2 (DCN): exchange the partial sums' inter-blocks
    return exchange_reduce(partial.reshape(inter, shard),
                           inter_axis, inter_bits, group_size).astype(dtype)
