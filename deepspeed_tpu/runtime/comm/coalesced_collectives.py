"""Coalesced + quantized collectives — ZeRO++ comm kernels.

Reference ``runtime/comm/coalesced_collectives.py``:
- ``reduce_scatter_coalesced`` (:31): one fused reduce-scatter over many
  tensors.
- ``all_to_all_quant_reduce`` (:81, qgZ): gradients are int4-quantized,
  exchanged all-to-all *within* the node, reduced locally, int8-quantized and
  exchanged across nodes, reduced again — 4x less cross-node traffic.

TPU mapping: these run inside ``shard_map`` over mesh axes. The hierarchy is
``dp`` (intra-slice ICI, the reference's intra-node NVLink) and ``dpr``
(cross-slice DCN, the reference's inter-node IB) — see
``parallel/topology.py``. qwZ (``zero_quantized_weights``) is
``quantized_all_gather``: the wire format is int8 + per-group scales.
"""

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.utils import jax_compat  # noqa: F401  installs lax.axis_size on old jax

from deepspeed_tpu.ops.quantizer import dequantize, quantize


def reduce_scatter_coalesced(tensors, axis_name="dp"):
    """Fused reduce-scatter of a list of tensors over ``axis_name``
    (reference :31). Each tensor is flattened; every rank gets back its
    1/world shard of each (padded to divide evenly)."""
    world = lax.axis_size(axis_name)
    out = []
    for t in tensors:
        flat = t.reshape(-1)
        pad = (-flat.shape[0]) % world
        if pad:
            flat = jnp.pad(flat, (0, pad))
        out.append(lax.psum_scatter(flat.reshape(world, -1), axis_name,
                                    scatter_dimension=0, tiled=False))
    return out


def quantized_all_gather(x, axis_name="dp", num_bits=8, group_size=2048,
                         dtype=jnp.float32):
    """qwZ: all-gather with an int8 wire format (reference qwZ quantized
    all-gather: ``partition_parameters.py:728`` CUDAQuantizer +
    ``csrc/quantization/swizzled_quantize.cu``). Gathers ``x`` (this rank's
    shard) from every rank along ``axis_name``; only int8 values + fp32
    group scales cross the wire."""
    q, scale = quantize(x, num_bits=num_bits, group_size=group_size)
    qg = lax.all_gather(q, axis_name)        # [world, groups, packed]
    sg = lax.all_gather(scale, axis_name)    # [world, groups]
    deq = jax.vmap(lambda qi, si: dequantize(qi, si, x.shape,
                                             num_bits=num_bits,
                                             group_size=group_size,
                                             dtype=dtype))
    parts = deq(qg, sg)                      # [world, *x.shape]
    return parts.reshape((parts.shape[0] * x.shape[0],) + x.shape[1:])


def exchange_reduce(blocks, axis, bits, group_size=2048):
    """Quantized all-to-all + local reduce: the qgZ exchange primitive.

    ``blocks``: [peers, m] — row j is this rank's payload destined for peer j.
    Each row is groupwise-quantized to ``bits``, exchanged over ``axis``
    (row j -> peer j), dequantized, and summed: returns this rank's [m]
    partial sum over the ``axis`` group."""
    qfn = jax.vmap(lambda row: quantize(row, num_bits=bits,
                                        group_size=group_size))
    q, s = qfn(blocks)
    qx = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
    sx = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=False)
    m = blocks.shape[1]
    deq = jax.vmap(lambda qi, si: dequantize(qi, si, (m,), num_bits=bits,
                                             group_size=group_size))
    return deq(qx, sx).sum(axis=0)  # [m]


def all_to_all_quant_reduce(x, intra_axis="dp", inter_axis=None,
                            intra_bits=4, inter_bits=8, group_size=2048,
                            dtype=jnp.float32):
    """qgZ: hierarchical quantized gradient reduction (reference :81).

    ``x`` is this rank's full-size gradient; the result is this rank's
    1/world flat shard of the *sum* over all ranks (world = intra × inter).
    Stage 1 int4-quantizes per destination block and all-to-alls within
    ``intra_axis`` (ICI), then dequant-reduces; stage 2 (when ``inter_axis``
    is given) repeats with int8 across ``inter_axis`` (DCN). Cross-DCN bytes
    are inter_bits/32 of an fp32 reduce-scatter."""

    intra = lax.axis_size(intra_axis)
    inter = lax.axis_size(inter_axis) if inter_axis else 1
    world = intra * inter
    flat = x.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % world
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = flat.shape[0] // world

    # stage 1 (ICI): each intra-peer block carries all its inter-shards
    partial = exchange_reduce(flat.reshape(intra, inter * shard),
                              intra_axis, intra_bits, group_size)
    if inter == 1:
        return partial.astype(dtype)
    # stage 2 (DCN): exchange the partial sums' inter-blocks
    return exchange_reduce(partial.reshape(inter, shard),
                           inter_axis, inter_bits, group_size).astype(dtype)
