"""Async tensor swapping to NVMe (reference ``runtime/swap_tensor/async_swapper.py``).

Double-buffered: ``swap_out`` enqueues a write through the native aio handle
and returns; the caller overlaps compute with I/O and drains with ``wait``.
Buffers are recycled from a fixed pool (reference buffer_count semantics).
"""

import os
import time

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle

# module-level alias so tests can inject a fake clock without patching
# time.perf_counter globally (jax reads the real clock internally)
_now = time.perf_counter


class AsyncTensorSwapper:

    def __init__(self, swap_dir, aio_config=None, buffer_count=4):
        cfg = aio_config or {}
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        self.handle = AsyncIOHandle(
            block_size=cfg.get("block_size", 1024 * 1024),
            queue_depth=cfg.get("queue_depth", 8),
            single_submit=cfg.get("single_submit", False),
            overlap_events=cfg.get("overlap_events", True),
            num_threads=cfg.get("thread_count", 4))
        self.buffer_count = buffer_count
        self._inflight_writes = 0
        self._inflight_reads = 0
        self.wait_seconds = 0.0   # cumulative drain stall (injectable clock)

    def path_for(self, key):
        return os.path.join(self.swap_dir, f"{key}.swp")

    def swap_out(self, key, array, async_op=True):
        """Write ``array`` (numpy) to the swap file for ``key``."""
        arr = np.ascontiguousarray(array)
        self.handle.async_pwrite(arr, self.path_for(key))
        self._inflight_writes += 1
        if not async_op:
            self.wait()

    def swap_in(self, key, out_array, async_op=True):
        """Read the swap file for ``key`` into ``out_array`` (numpy, preallocated)."""
        self.handle.async_pread(out_array, self.path_for(key))
        self._inflight_reads += 1
        if not async_op:
            self.wait()
        return out_array

    def has_swapped(self, key):
        return os.path.exists(self.path_for(key))

    def wait(self):
        t0 = _now()
        n = self.handle.wait()
        self.wait_seconds += _now() - t0
        self._inflight_writes = 0
        self._inflight_reads = 0
        return n

    def release(self, key):
        try:
            os.remove(self.path_for(key))
        except FileNotFoundError:
            pass
