"""NVMe KV-block store: the disk rung under the host-DRAM spill tier.

ZeRO-Infinity's NVMe offload applied to inference KV (the 1M-token regime):
when the ``BlockedAllocator`` host tier fills, its oldest payload is demoted
through this store — each payload (the tuple of per-block page arrays the
``HostKVSwapper`` landed) is written file-per-array through the in-tree
``swap_tensor`` aio path (:class:`AsyncTensorSwapper` over
``ops.aio.AsyncIOHandle``, which degrades to a thread-pool fallback when the
native library isn't built). Reads rebuild the exact numpy tuple; dtype and
shape ride in a host-side record, never on disk.

Keys are single-shot like allocator spill handles: ``read`` does not drop
(the allocator drops after a successful read so a failed read can't leak the
record), ``drop`` removes the backing files.
"""

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper


class NVMeKVStore:

    def __init__(self, swap_dir, aio_config=None, buffer_count=4):
        self._swapper = AsyncTensorSwapper(swap_dir, aio_config=aio_config,
                                           buffer_count=buffer_count)
        self._meta = {}   # key -> [(shape, dtype), ...] per array of the tuple
        self._next = 0
        self.writes = 0
        self.reads = 0
        self.drops = 0
        self.bytes_written = 0
        self.bytes_read = 0

    @property
    def swap_dir(self):
        return self._swapper.swap_dir

    @property
    def resident(self) -> int:
        return len(self._meta)

    def _part(self, key, i):
        return f"{key}-{i}"

    def write(self, arrays):
        """Persist a tuple/list of numpy arrays; returns the store key."""
        key = f"kvblk{self._next}"
        self._next += 1
        arrays = tuple(np.asarray(a) for a in arrays)
        for i, a in enumerate(arrays):
            self._swapper.swap_out(self._part(key, i), a, async_op=True)
            self.bytes_written += int(a.nbytes)
        # drain before returning: the handle's buffers recycle per call and a
        # later demotion must never race a still-queued write of this key
        self._swapper.wait()
        self._meta[key] = [(a.shape, a.dtype) for a in arrays]
        self.writes += 1
        return key

    def read(self, key):
        """Read the tuple back (preallocated, aio pread per array)."""
        if key not in self._meta:
            raise ValueError(f"read of unknown nvme key {key}")
        out = []
        for i, (shape, dtype) in enumerate(self._meta[key]):
            buf = np.empty(shape, dtype=dtype)
            self._swapper.swap_in(self._part(key, i), buf, async_op=True)
            out.append(buf)
            self.bytes_read += int(buf.nbytes)
        self._swapper.wait()
        self.reads += 1
        return tuple(out)

    def drop(self, key):
        """Remove the backing files and forget the record."""
        if key not in self._meta:
            raise ValueError(f"drop of unknown nvme key {key}")
        for i in range(len(self._meta.pop(key))):
            self._swapper.release(self._part(key, i))
        self.drops += 1

    def stats(self):
        return {"writes": self.writes, "reads": self.reads,
                "drops": self.drops, "resident": len(self._meta),
                "bytes_written": self.bytes_written,
                "bytes_read": self.bytes_read,
                "wait_seconds": self._swapper.wait_seconds}
