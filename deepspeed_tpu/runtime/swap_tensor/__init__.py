from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper
from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import PartitionedOptimizerSwapper

__all__ = ["AsyncTensorSwapper", "PartitionedOptimizerSwapper"]
