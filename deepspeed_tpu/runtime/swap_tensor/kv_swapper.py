"""Host-DRAM KV page swapper: async double-buffered device->host spills.

Sibling of ``AsyncTensorSwapper`` (NVMe aio) for the serving KV tier: parked
prefix-cache blocks spill their pages to host DRAM instead of being evicted,
so the prefix cache becomes effectively unbounded (ZeRO-Inference/Infinity
offload lineage — cold state belongs one tier down, moved off the hot path).

The pipeline shape mirrors the aio swapper's two-deep buffering, adapted to
jax's async dispatch: the caller dispatches the device->host *gather* (a
copying ``jnp.take``) and hands the still-in-flight device arrays to
``submit``. Nothing blocks until the pending queue exceeds ``buffer_count``
entries, at which point the oldest entry is *landed* — fetched to host numpy
through the injected accounted-fetch callable — and its device buffers drop.
Decode steps dispatched between submit and landing overlap the copies.

``restore`` of a still-pending payload lands it first; a landed payload is
plain numpy. Payloads are single-use (the allocator's spill-handle contract).
"""

from collections import deque


class _Payload:
    """One spilled block's pages: device arrays until landed, numpy after."""

    __slots__ = ("arrays", "landed")

    def __init__(self, arrays):
        self.arrays = arrays   # tuple of device arrays, then numpy
        self.landed = False


class HostKVSwapper:

    def __init__(self, fetch, buffer_count=2, land_wrapper=None):
        """``fetch(arrays, what)`` -> host numpy tuple: the accounted
        device->host fetch (the engine's ``host_fetch`` when wired, so the
        host-sync ratchet sees every landing). ``land_wrapper(thunk)``, when
        set, runs each landing's fetch thunk — the caller decides whether to
        time it (telemetry enabled) or run it bare, so the disabled path
        stays clock-free."""
        self._fetch = fetch
        self._buffer_count = max(1, int(buffer_count))
        self._pending = deque()      # _Payload entries, oldest first
        self._land_wrapper = land_wrapper
        self.landings = 0

    @property
    def pending(self) -> int:
        return len(self._pending)

    def submit(self, arrays):
        """Enqueue in-flight device gathers as a new payload; lands the
        oldest entries beyond the double-buffer depth. Returns the payload
        (the allocator's opaque spill record)."""
        p = _Payload(tuple(arrays))
        self._pending.append(p)
        while len(self._pending) > self._buffer_count:
            self._land(self._pending.popleft())
        return p

    def land(self, payload):
        """Force a specific payload onto host (restore of a pending spill)."""
        if not payload.landed:
            self._pending.remove(payload)
            self._land(payload)
        return payload.arrays

    def drain(self):
        """Land everything pending (shutdown / barrier)."""
        while self._pending:
            self._land(self._pending.popleft())

    def _land(self, payload):
        thunk = lambda: self._fetch(payload.arrays, "kv_cache/spill")  # noqa: E731
        payload.arrays = thunk() if self._land_wrapper is None \
            else self._land_wrapper(thunk)
        payload.landed = True
        self.landings += 1
