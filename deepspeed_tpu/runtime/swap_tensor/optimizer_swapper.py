"""Optimizer-state NVMe swapping (reference
``runtime/swap_tensor/partitioned_optimizer_swapper.py:219`` /
``pipelined_optimizer_swapper.py``).

Per-leaf Adam moments live in swap files; around each leaf's host update the
swapper reads them in and writes them back, with read-ahead of the next leaf
(the reference's PipelinedOptimizerSwapper overlap) through the async aio
handle. Master fp32 weights stay in host DRAM (the reference's DRAM tier);
moments — 2/3 of optimizer bytes — go to NVMe.
"""

import time

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper

# injectable clock alias (see async_swapper.py)
_now = time.perf_counter


class PartitionedOptimizerSwapper:

    def __init__(self, swap_dir, aio_config=None, buffer_count=4, pipeline=True):
        self.swapper = AsyncTensorSwapper(swap_dir, aio_config, buffer_count)
        self.pipeline = pipeline
        self._sizes = {}          # key -> element count
        self._buffers = {}        # key currently resident -> (m, v)
        self._prefetched = None   # key with a read in flight
        self.fetch_stall_seconds = 0.0  # drains the pipeline didn't hide

    def register(self, key, n, async_op=False):
        """Declare a leaf's moment buffers (initialized to zeros on NVMe).
        Pass ``async_op=True`` and call ``flush()`` once after registering many
        leaves to overlap the initial writes."""
        self._sizes[key] = n
        zeros = np.zeros(2 * n, dtype=np.float32)
        self.swapper.swap_out(key, zeros, async_op=async_op)

    def flush(self):
        self.swapper.wait()

    def keys(self):
        return list(self._sizes)

    def _issue_read(self, key):
        buf = np.empty(2 * self._sizes[key], dtype=np.float32)
        self.swapper.swap_in(key, buf, async_op=True)
        self._buffers[key] = buf
        self._prefetched = key

    def fetch(self, key, prefetch_next=None):
        """Return (m, v) views for ``key``; optionally start reading the next
        leaf's moments while the caller computes."""
        if key not in self._buffers:
            self._issue_read(key)
        t0 = _now()
        self.swapper.wait()  # drain the read (and any pending writebacks)
        self.fetch_stall_seconds += _now() - t0
        self._prefetched = None
        buf = self._buffers[key]
        n = self._sizes[key]
        m, v = buf[:n], buf[n:]
        if self.pipeline and prefetch_next is not None and prefetch_next != key:
            self._issue_read(prefetch_next)
        return m, v

    def commit(self, key):
        """Write back ``key``'s moments (async; next fetch/finish drains)."""
        buf = self._buffers.pop(key)
        self.swapper.swap_out(key, buf, async_op=True)

    def finish_step(self):
        self.swapper.wait()
        # drop any speculative prefetch not consumed this step
        self._buffers = {k: v for k, v in self._buffers.items() if k == self._prefetched}

    def state_arrays(self):
        """Synchronously read all moments (checkpointing)."""
        out = {}
        for key, n in self._sizes.items():
            buf = np.empty(2 * n, dtype=np.float32)
            self.swapper.swap_in(key, buf, async_op=False)
            out[key] = (buf[:n].copy(), buf[n:].copy())
        return out

    def load_state_arrays(self, states):
        for key, (m, v) in states.items():
            buf = np.concatenate([np.asarray(m, np.float32).reshape(-1),
                                  np.asarray(v, np.float32).reshape(-1)])
            self._sizes[key] = buf.size // 2
            self.swapper.swap_out(key, buf, async_op=False)
