"""LR schedules (mirrors reference ``deepspeed/runtime/lr_schedules.py:18-22,267``).

The reference implements LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR and
WarmupCosineLR as torch scheduler objects. Here each schedule is a pure
``lr(step) -> float`` function built from the same config params — usable both
inside jit (jnp ops only) and on the host — wrapped in a scheduler shim with
the reference's ``step()/get_lr()/state_dict()`` surface.
"""

import jax.numpy as jnp

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"

VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR]


def _warmup(step, warmup_num_steps, warmup_min_lr, warmup_max_lr, warmup_type="log"):
    warmup_num_steps = max(2, warmup_num_steps)
    if warmup_type == "log":
        # reference _get_gamma: min + (max-min) * log(step+1)/log(warmup_steps)
        # (log(1)=0 at step 0 => exactly warmup_min_lr)
        frac = jnp.log(step + 1.0) / jnp.log(float(warmup_num_steps))
    else:  # linear
        frac = step / float(warmup_num_steps)
    frac = jnp.clip(frac, 0.0, 1.0)
    return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac


def warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.001, warmup_num_steps=1000,
              warmup_type="log", **_):
    """reference WarmupLR: warmup then hold at max."""

    def lr(step):
        step = jnp.asarray(step, jnp.float32)
        return jnp.where(step < warmup_num_steps,
                         _warmup(step, warmup_num_steps, warmup_min_lr, warmup_max_lr, warmup_type),
                         warmup_max_lr)

    return lr


def warmup_decay_lr(total_num_steps, warmup_min_lr=0.0, warmup_max_lr=0.001,
                    warmup_num_steps=1000, warmup_type="log", **_):
    """reference WarmupDecayLR: warmup then linear decay to 0 at total_num_steps."""

    def lr(step):
        step = jnp.asarray(step, jnp.float32)
        decay_frac = jnp.clip(
            (total_num_steps - step) / jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0),
            0.0, 1.0)
        return jnp.where(step < warmup_num_steps,
                         _warmup(step, warmup_num_steps, warmup_min_lr, warmup_max_lr, warmup_type),
                         warmup_max_lr * decay_frac)

    return lr


def warmup_cosine_lr(total_num_steps, warmup_min_ratio=0.0, warmup_num_steps=1000,
                     cos_min_ratio=0.0001, warmup_type="log", warmup_max_lr=1.0, **_):
    """reference WarmupCosineLR: ratio warmup then cosine decay; returns a
    multiplier of the optimizer lr (we fold warmup_max_lr in for an absolute lr)."""

    def lr(step):
        step = jnp.asarray(step, jnp.float32)
        warm = _warmup(step, warmup_num_steps, warmup_min_ratio * warmup_max_lr,
                       warmup_max_lr, warmup_type)
        progress = jnp.clip((step - warmup_num_steps) /
                            jnp.maximum(float(total_num_steps - warmup_num_steps), 1.0), 0.0, 1.0)
        cosine = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_num_steps, warm, warmup_max_lr * cosine)

    return lr


def lr_range_test(lr_range_test_min_lr=1e-3, lr_range_test_step_size=2000,
                  lr_range_test_step_rate=1.0, lr_range_test_staircase=False, **_):
    """reference LRRangeTest (:18): linearly/staircase increasing lr probe."""

    def lr(step):
        step = jnp.asarray(step, jnp.float32)
        interval = (jnp.floor(step / lr_range_test_step_size)
                    if lr_range_test_staircase else step / lr_range_test_step_size)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return lr


def one_cycle(cycle_min_lr=0.0, cycle_max_lr=0.001, decay_lr_rate=0.0,
              cycle_first_step_size=2000, cycle_second_step_size=None,
              cycle_first_stair_count=0, cycle_second_stair_count=None,
              decay_step_size=0, **_):
    """reference OneCycle (:19): triangular cycle then decay."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def lr(step):
        step = jnp.asarray(step, jnp.float32)
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (step / cycle_first_step_size)
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * ((step - cycle_first_step_size) / second)
        in_cycle = jnp.where(step < cycle_first_step_size, up, down)
        post = step - total_cycle
        decayed = cycle_min_lr if decay_step_size == 0 else (
            cycle_min_lr / (1.0 + jnp.floor(post / decay_step_size) * decay_lr_rate))
        return jnp.where(step < total_cycle, in_cycle, decayed)

    return lr


_FACTORIES = {
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
}


def get_lr_schedule(name, params, base_lr=None):
    """Build an ``lr(step)`` function from a scheduler config section."""
    if name is None:
        base = base_lr if base_lr is not None else 1e-3
        return lambda step: jnp.asarray(base, jnp.float32)
    if name not in _FACTORIES:
        raise ValueError(f"unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}")
    params = dict(params or {})
    if base_lr is not None:
        params.setdefault("warmup_max_lr", base_lr)
    return _FACTORIES[name](**params)


class LRSchedulerShim:
    """Object with the reference scheduler surface (step/get_lr/state_dict)."""

    def __init__(self, schedule_fn, engine=None):
        self.schedule_fn = schedule_fn
        self._engine = engine
        self.last_batch_iteration = -1

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is not None:
            self.last_batch_iteration = last_batch_iteration
        else:
            self.last_batch_iteration += 1

    def get_lr(self):
        step = self.last_batch_iteration
        if self._engine is not None:
            step = self._engine.global_steps
        return [float(self.schedule_fn(max(step, 0)))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


def add_tuning_arguments(parser):
    """CLI args for the LR schedules (reference ``lr_schedules.py:60``):
    one flag per schedule parameter, read back by ``get_lr_from_args``-style
    glue or passed into the config's scheduler section."""
    group = parser.add_argument_group(
        "Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", action="store_true")
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--cycle_min_mom", type=float, default=0.8)
    group.add_argument("--cycle_max_mom", type=float, default=0.9)
    group.add_argument("--decay_mom_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0.0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default="log")
    return parser
