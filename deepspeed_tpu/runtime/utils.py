"""Runtime math/memory utilities (mirrors reference ``deepspeed/runtime/utils.py``).

- ``get_global_norm_of_tensors`` (:836) / ``clip_grad_norm_`` (:316) → pytree
  global-norm + clip, GSPMD-safe (partial sums over sharded leaves are combined
  by XLA automatically).
- ``CheckOverflow`` (:182) → ``has_overflow`` on a pytree.
- ``see_memory_usage`` (:762) → PJRT memory stats.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def global_norm(tree, use_rms=False):
    """L2 norm over every leaf of a pytree (reference utils.py:836)."""
    leaves = [jnp.sum(jnp.square(l.astype(jnp.float32))) for l in jax.tree.leaves(tree)]
    total = jnp.asarray(0.0, jnp.float32) if not leaves else sum(leaves)
    if use_rms:
        n = sum(l.size for l in jax.tree.leaves(tree))
        return jnp.sqrt(total / max(n, 1))
    return jnp.sqrt(total)


def clip_grads_by_global_norm(grads, max_norm, norm=None, eps=1e-6):
    """Scale grads so their global norm ≤ max_norm (reference clip_grad_norm_:316).
    Returns (clipped_grads, pre_clip_norm)."""
    if norm is None:
        norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + eps))
    return jax.tree.map(lambda g: g * scale.astype(g.dtype), grads), norm


def has_overflow(tree):
    """True if any leaf contains inf/nan (reference CheckOverflow, utils.py:182)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(False)
    flags = [~jnp.all(jnp.isfinite(l.astype(jnp.float32))) for l in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = out | f
    return out


def tree_where(pred, a, b):
    """Elementwise select whole pytrees on a scalar predicate (used for fp16
    overflow step-skipping without host sync)."""
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def tree_cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


def tree_zeros_like(tree, dtype=None):
    return jax.tree.map(lambda x: jnp.zeros(x.shape, dtype or x.dtype), tree)


def count_parameters(tree):
    return sum(l.size for l in jax.tree.leaves(tree))


def see_memory_usage(message, force=False):
    """reference utils.py:762 — PJRT per-device memory stats. Reads go
    through the telemetry memory stream so every HBM sample lands in one
    place (docs/OBSERVABILITY.md)."""
    from deepspeed_tpu import telemetry
    stats = telemetry.sample_memory("see_memory_usage", message=message) or {}
    gb = 1024**3
    logger.info(f"{message} | MA {stats.get('bytes_in_use', 0)/gb:.2f} GB | "
                f"Max_MA {stats.get('peak_bytes_in_use', 0)/gb:.2f} GB | "
                f"limit {stats.get('bytes_limit', 0)/gb:.2f} GB")


def constrain_tree(tree, sharding_tree):
    """Apply with_sharding_constraint leaf-wise (no-op outside jit tracing)."""
    return jax.tree.map(jax.lax.with_sharding_constraint, tree, sharding_tree)
