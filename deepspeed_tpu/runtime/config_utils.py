"""Config plumbing (mirrors reference ``deepspeed/runtime/config_utils.py``).

The reference uses pydantic-v1 models with ``deprecated``/``new_param`` field
metadata; to avoid a pydantic version dependency this is a small hand-rolled
equivalent: ``DeepSpeedConfigModel`` subclasses declare defaults as class
attributes and are constructed from a dict, with unknown-key warnings and
deprecated-key remapping.
"""

import copy

from deepspeed_tpu.utils.logging import logger


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


class DeepSpeedConfigModel:
    """Dict-backed config with class-attribute defaults.

    Subclasses set defaults as class attributes and may define
    ``_deprecated = {"old_key": "new_key"}``. Construction copies defaults to the
    instance then overlays the dict.
    """

    _deprecated = {}

    def __init__(self, param_dict=None, **kwargs):
        param_dict = dict(param_dict or {})
        param_dict.update(kwargs)
        # instance copies of all class-level defaults
        for klass in reversed(type(self).__mro__):
            for k, v in vars(klass).items():
                if not k.startswith("_") and not callable(v) and not isinstance(v, (property, classmethod, staticmethod)):
                    setattr(self, k, copy.deepcopy(v))
        known = set(k for k in vars(self) if not k.startswith("_"))
        for k, v in param_dict.items():
            key = k
            if key in self._deprecated:
                new = self._deprecated[key]
                logger.warning(f"Config param {key} is deprecated, use {new}")
                key = new
            if key in known:
                if v == "auto":
                    # HF-style "auto": keep the default (reference "auto"
                    # values are filled in by the HF integration layer)
                    continue
                cur = getattr(self, key)
                if isinstance(cur, DeepSpeedConfigModel) and isinstance(v, dict):
                    setattr(self, key, type(cur)(v))
                else:
                    setattr(self, key, v)
            else:
                self._handle_unknown(key, v)

    def _handle_unknown(self, key, value):
        logger.warning(f"{type(self).__name__}: ignoring unknown config key '{key}'")

    def to_dict(self):
        out = {}
        for k, v in vars(self).items():
            if k.startswith("_"):
                continue
            out[k] = v.to_dict() if isinstance(v, DeepSpeedConfigModel) else v
        return out

    def __repr__(self):
        return f"{type(self).__name__}({self.to_dict()})"
