"""Compile support surface (reference ``runtime/compiler.py``).

The reference gates ``torch.compile`` integration behind
``is_compile_supported()`` and a CompileConfig. Here EVERY training and
inference step is already an XLA-compiled program (``jax.jit``), so compile
support is unconditionally present and ``compile`` is the identity — the
config's ``"compile"`` key is accepted for parity (runtime/config.py).
"""


def is_compile_supported() -> bool:
    return True


def compile(module, *args, **kwargs):  # noqa: A001 - reference name
    """No-op for parity: jitted execution is always on."""
    return module
