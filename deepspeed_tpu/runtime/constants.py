"""Config key names and defaults (mirrors reference ``deepspeed/runtime/constants.py``)."""

TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"

OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"
MAX_GRAD_NORM = "max_grad_norm"

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"

FP16 = "fp16"
BF16 = "bf16"
ZERO_OPTIMIZATION = "zero_optimization"

SPARSE_GRADIENTS = "sparse_gradients"
PREFETCH_BATCHES = "prefetch_batches"
FUSED_STEP = "fused_step"

DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"

ACTIVATION_CHECKPOINTING = "activation_checkpointing"
PIPELINE = "pipeline"
TENSOR_PARALLEL = "tensor_parallel"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"

COMMS_LOGGER = "comms_logger"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_CSV = "csv_monitor"
MONITOR_WANDB = "wandb"
FLOPS_PROFILER = "flops_profiler"
TELEMETRY = "telemetry"
OVERLAP = "overlap"
RESILIENCE = "resilience"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
CHECKPOINT = "checkpoint"
COMPILE = "compile"

ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"
