"""SparseTensor — sparse embedding-gradient representation.

Reference ``runtime/sparse_tensor.py`` + the engine's ``sparse_allreduce_*``
(``engine.py:2470-2539``): embedding gradients touch few rows per step, so
they travel as (indices, values) pairs and are reduced by concatenating and
re-deduplicating instead of dense allreduce.

On TPU dense gradients ride ICI cheaply, so this is mostly an interop/API
surface; the rendezvous math (dedupe + sum by index) is still useful for
host-side gradient post-processing and for DCN-frugal multi-slice setups.
"""

import numpy as np


class SparseTensor:
    """(indices, values) rows of a [num_rows, dim] dense tensor."""

    def __init__(self, indices, values, dense_size):
        self.indices = np.asarray(indices, dtype=np.int64)
        self.values = np.asarray(values)
        self.dense_size = tuple(dense_size)
        assert self.values.shape[0] == self.indices.shape[0]

    @classmethod
    def from_dense(cls, dense, threshold=0.0):
        dense = np.asarray(dense)
        row_nonzero = np.abs(dense).max(axis=tuple(range(1, dense.ndim))) > threshold
        idx = np.nonzero(row_nonzero)[0]
        return cls(idx, dense[idx], dense.shape)

    def to_dense(self):
        out = np.zeros(self.dense_size, dtype=self.values.dtype)
        np.add.at(out, self.indices, self.values)
        return out

    def deduplicate(self):
        """Sum values of repeated indices (reference sparse reduce merge)."""
        uniq, inv = np.unique(self.indices, return_inverse=True)
        summed = np.zeros((uniq.shape[0],) + self.values.shape[1:],
                          dtype=self.values.dtype)
        np.add.at(summed, inv, self.values)
        return SparseTensor(uniq, summed, self.dense_size)

    def sparse_size(self):
        return self.indices.size + self.values.size

    def __repr__(self):
        return (f"SparseTensor(nnz_rows={self.indices.shape[0]}, "
                f"dense={self.dense_size})")


def sparse_all_reduce(sparse_tensors):
    """Reduce a list of SparseTensors (one per rank) into the dense sum —
    the in-process analog of the engine's sparse allreduce rendezvous."""
    assert sparse_tensors
    base = sparse_tensors[0]
    all_idx = np.concatenate([s.indices for s in sparse_tensors])
    all_val = np.concatenate([s.values for s in sparse_tensors])
    return SparseTensor(all_idx, all_val, base.dense_size).deduplicate()
