"""Activation checkpointing — rematerialization on TPU.

Reference: ``runtime/activation_checkpointing/checkpointing.py`` — Megatron-
compatible ``checkpoint()`` (:990) / ``CheckpointFunction`` (:485) with
partitioned activations across MP ranks (:374), CPU checkpointing,
contiguous buffers and a CUDA RNG tracker (:123).

TPU mapping: the capability is ``jax.checkpoint`` (remat) — XLA recomputes
the forward inside backward instead of saving activations, trading FLOPs for
HBM exactly as the reference does, but scheduled by the compiler:

- ``partition_activations``: unnecessary as a mechanism — under GSPMD a saved
  residual inherits the sharding of the computation that produced it, so
  activations are already partitioned over the sp/tp axes. The flag is
  accepted and recorded.
- ``cpu_checkpointing``: maps to XLA host offload — the ``offload-dots``
  policy stores matmul results on ``pinned_host`` memory instead of HBM.
- ``contiguous_memory_optimization`` / ``synchronize`` / ``profile``: CUDA
  allocator/stream concerns; accepted for config parity, owned by XLA.
- RNG: JAX PRNG keys are functional, so the reference's
  ``CudaRNGStatesTracker`` (stash/restore CUDA RNG state so dropout matches
  between the two forwards) is automatic — ``jax.checkpoint`` replays the
  same key. A tracker shim keeps Megatron-style call sites working.

``checkpoint(fn, *args)`` is the drop-in functional API; ``checkpoint_wrapper``
wraps a flax module (``nn.remat``); scanned-block models apply the policy via
``policy_by_name`` (models/llama.py, models/gpt2.py).
"""

import contextlib
import functools

import jax

from deepspeed_tpu.utils.logging import logger

_CONFIG = {
    "partition_activations": False,
    "contiguous_checkpointing": False,
    "num_checkpoints": None,
    "checkpoint_in_cpu": False,
    "synchronize": False,
    "profile": False,
    "policy": "everything",
}


def policy_by_name(name, checkpoint_in_cpu=False):
    """Named remat policies (config key ``activation_checkpointing.policy``):

    - "everything": recompute everything (max memory saving; the reference's
      full activation checkpointing) — ``nothing_saveable``
    - "dots": save matmul outputs, recompute elementwise —
      ``dots_with_no_batch_dims_saveable``, usually the best TPU trade
    - "nothing": no remat (save all activations)

    ``checkpoint_in_cpu`` lifts saved dots to pinned host memory (the
    reference's CPU checkpointing). ``policy="nothing"`` (no remat) takes
    precedence — there is nothing to offload if everything is saved."""
    cp = jax.checkpoint_policies
    if checkpoint_in_cpu and name != "nothing":
        # dots offload to pinned host; the flash output (not a dot_general)
        # is saved on device — still skipping the backward recompute
        return cp.save_from_both_policies(
            cp.offload_dot_with_no_batch_dims("device", "pinned_host"),
            cp.save_only_these_names("flash_attn_out"))
    return {
        "everything": cp.nothing_saveable,
        # projections saved via the dots rule; the Pallas flash kernel is not
        # a dot_general, so its named output is saved explicitly — otherwise
        # backward re-runs the whole attention kernel
        "dots": cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable,
            cp.save_only_these_names("flash_attn_out")),
        "nothing": cp.everything_saveable,
    }[name]


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """reference ``checkpointing.configure`` (:899) — record the global
    activation-checkpointing options."""
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
        if ac is not None:
            _CONFIG.update(partition_activations=ac.partition_activations,
                           contiguous_checkpointing=ac.contiguous_memory_optimization,
                           num_checkpoints=ac.number_checkpoints,
                           checkpoint_in_cpu=ac.cpu_checkpointing,
                           synchronize=ac.synchronize_checkpoint_boundary,
                           profile=ac.profile, policy=ac.policy)
    for k, v in dict(partition_activations=partition_activations,
                     contiguous_checkpointing=contiguous_checkpointing,
                     num_checkpoints=num_checkpoints,
                     checkpoint_in_cpu=checkpoint_in_cpu,
                     synchronize=synchronize, profile=profile).items():
        if v is not None:
            _CONFIG[k] = v


def is_configured():
    return True


def current_policy():
    return policy_by_name(_CONFIG["policy"], _CONFIG["checkpoint_in_cpu"])


def checkpoint(function, *args):
    """Drop-in for reference ``checkpoint(function, *args)`` (:990): runs
    ``function`` now and rematerializes it during backward."""
    return jax.checkpoint(function, policy=current_policy(),
                          prevent_cse=False)(*args)


def checkpoint_wrapper(target, **remat_kwargs):
    """Wrap a flax ``nn.Module`` class or a plain function for remat."""
    import flax.linen as nn
    if isinstance(target, type) and issubclass(target, nn.Module):
        return nn.remat(target, policy=current_policy(), prevent_cse=False,
                        **remat_kwargs)
    return jax.checkpoint(target, policy=current_policy(), prevent_cse=False)


def non_reentrant_checkpoint(function, *args):
    """reference :725 — identical under XLA (there is no reentrant autograd)."""
    return checkpoint(function, *args)


def partition_activations_in_checkpoint(partition_activation):
    """reference :1038 — recorded only; GSPMD already shards residuals."""
    _CONFIG["partition_activations"] = partition_activation
    logger.info(f"partition_activations={partition_activation} (GSPMD shards "
                "saved residuals along the mesh automatically)")


# --------------------------------------------------------------------------
# RNG tracker shim (reference CudaRNGStatesTracker :123). JAX PRNG is
# functional — remat replays the same key, so dropout is consistent between
# the two forwards without stashing device RNG state. The shim preserves the
# Megatron call-site API for ported model code.
# --------------------------------------------------------------------------
class RNGStatesTracker:

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_.clear()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"seed {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name="model-parallel-rng"):
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        key, sub = jax.random.split(self.states_[name])
        self.states_[name] = key
        yield sub


_RNG_TRACKER = RNGStatesTracker()


def get_cuda_rng_tracker():
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed):
    """reference :182 — seed the tracker (data-parallel + model-parallel
    streams)."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718)
    _RNG_TRACKER.add("data-parallel-rng", seed)


def reset():
    _RNG_TRACKER.reset()
