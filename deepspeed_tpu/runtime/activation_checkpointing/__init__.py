from deepspeed_tpu.runtime.activation_checkpointing import checkpointing

__all__ = ["checkpointing"]
