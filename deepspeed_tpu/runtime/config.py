"""DeepSpeed-style JSON config system.

Mirrors reference ``deepspeed/runtime/config.py``: a single JSON/dict is parsed
into ~20 typed sub-configs (``DeepSpeedConfig._initialize_params``,
``config.py:798``) with the train-batch triple auto-derivation
(train_batch = micro_batch × grad_accum × data_parallel_size, ``config.py:789``).
"""

import json
import os

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel, get_scalar_param
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.utils.logging import logger


class FP16Config(DeepSpeedConfigModel):
    """reference fp16 dict (``runtime/config.py`` get_fp16_enabled etc.)."""
    enabled = False
    auto_cast = False
    loss_scale = 0.0  # 0 => dynamic
    initial_scale_power = 16
    loss_scale_window = 1000
    hysteresis = 2
    consecutive_hysteresis = False
    min_loss_scale = 1.0


class BF16Config(DeepSpeedConfigModel):
    enabled = False
    immediate_grad_update = False


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype = None  # None => fp32


class OptimizerConfig(DeepSpeedConfigModel):
    type = "AdamW"
    params = {}
    legacy_fusion = False


class SchedulerConfig(DeepSpeedConfigModel):
    type = None
    params = {}


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """reference ``runtime/activation_checkpointing/config.py``; on TPU this
    selects the ``jax.checkpoint`` (remat) policy applied to scanned blocks."""
    partition_activations = False
    cpu_checkpointing = False
    contiguous_memory_optimization = False
    number_checkpoints = None
    synchronize_checkpoint_boundary = False
    profile = False
    # TPU-specific: named jax.checkpoint policy ("nothing" | "dots" | "everything")
    policy = "everything"


class PipelineConfig(DeepSpeedConfigModel):
    stages = 1
    partition_method = "parameters"
    seed_layers = False
    activation_checkpoint_interval = 0


class TensorParallelConfig(DeepSpeedConfigModel):
    tp_size = 1
    mpu = None


class MonitorWriterConfig(DeepSpeedConfigModel):
    enabled = False
    output_path = ""
    job_name = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled = False
    group = None
    team = None
    project = "deepspeed_tpu"


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled = False
    verbose = False
    prof_all = True
    prof_ops = []
    debug = False


class TelemetryConfig(DeepSpeedConfigModel):
    """``telemetry`` section — the unified observability pipeline
    (deepspeed_tpu/telemetry). Disabled by default: every telemetry entry
    point is then a constant-time no-op (no block_until_ready, no file I/O).
    See docs/OBSERVABILITY.md."""
    enabled = False
    jsonl_path = ""          # "" disables the JSON-lines metrics export
    chrome_trace_path = ""   # "" disables the chrome://tracing span export
    sample_sync = True       # block_until_ready on span tokens when sampling
    jax_annotations = False  # mirror spans into jax.profiler annotations
    monitor = True           # fan aggregates through MonitorMaster at
    #                          steps_per_print cadence
    memory = True            # HBM memory stream (record_memory samples at
    #                          step boundaries, OOM post-mortem)
    flops_per_step = 0       # model FLOPs per optimizer step for the MFU
    #                          gauge (0 -> flops profiler fills it in)
    peak_flops = 0           # aggregate peak FLOP/s denominator (0 -> per
    #                          device-kind table)


class PreemptionConfig(DeepSpeedConfigModel):
    """``resilience.preemption`` — SIGTERM/SIGINT → emergency checkpoint at
    the next step boundary, then exit with ``exit_code`` (the elastic
    agent's "clean preemption" contract, docs/RESILIENCE.md)."""
    enabled = False
    save_dir = ""       # "" -> the last save_checkpoint dir this run used
    tag = "emergency"
    exit_code = 83      # resilience.EXIT_CLEAN_PREEMPTION


class WatchdogConfig(DeepSpeedConfigModel):
    """``resilience.watchdog`` — step-heartbeat stall detector
    (resilience/watchdog.py). A stall is no step progress within
    ``hang_factor`` × rolling-median step time (floored at
    ``min_interval_s``); on trip it dumps all-thread stacks + the telemetry
    summary and, with ``abort``, hard-exits with ``exit_code`` so the
    elastic agent restarts the gang."""
    enabled = False
    hang_factor = 10.0
    min_interval_s = 60.0
    poll_interval_s = 1.0
    window = 32         # rolling step-time samples for the median
    abort = False
    exit_code = 85      # resilience.EXIT_WATCHDOG_ABORT
    dump_file = ""      # also write the hang report here ("" = log only)


class ElasticReshardConfig(DeepSpeedConfigModel):
    """``resilience.elastic`` — slice-loss hand-off for elastic multi-slice
    training (resilience/elastic_reshard.py, docs/RESILIENCE.md). With
    ``enabled``, a slice-loss fault surfacing at the step boundary
    (``slice.lost`` / ``comm.partition``) makes the engine write an
    emergency *universal* checkpoint (topology-independent, so the
    relaunched gang can reshard it onto the survivors) and exit with
    ``exit_code`` — the elastic agent's "reshardable slice loss" contract,
    budget-free like a clean preemption but relaunched at a REDUCED world.
    Disabled (the default), the fault propagates to the caller — the
    in-process :class:`ElasticReshardController` path."""
    enabled = False
    save_dir = ""       # "" -> the last save_checkpoint dir this run used
    exit_code = 84      # resilience.EXIT_RESHARD_SLICE_LOSS
    n_slices = 2        # how many equal device slices the world divides into


class ResilienceConfig(DeepSpeedConfigModel):
    """``resilience`` section — fault injection, preemption-aware save and
    the step watchdog (deepspeed_tpu/resilience, docs/RESILIENCE.md).
    ``faults`` takes the DS_TPU_FAULTS grammar
    (``"point:mode[@stepA[-B]][!action]"``); the env var layers on top.
    ``postmortem_dir`` names the flight-recorder bundle destination
    (telemetry/flightrec.py) — empty leaves bundles governed by the
    ``DS_TPU_POSTMORTEM_DIR`` env var, and unset both means abnormal
    exits leave no bundle (the ring still records)."""
    faults = ""
    fault_seed = 0
    postmortem_dir = ""
    preemption = PreemptionConfig()
    watchdog = WatchdogConfig()
    elastic = ElasticReshardConfig()


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled = False
    recompute_fwd_factor = 0.0
    profile_step = 1
    module_depth = -1
    top_modules = 1
    detailed = True
    output_file = None


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation = "Warn"
    load_universal = False
    use_node_local_storage = False
    parallel_write = {}


class ElasticityConfig(DeepSpeedConfigModel):
    enabled = False
    max_train_batch_size = 2000
    micro_batch_sizes = [2, 4, 6]
    min_gpus = 1
    max_gpus = 10000
    min_time = 0
    version = 0.2
    ignore_non_elastic_batch_info = False
    prefer_larger_batch = True


class CompileConfig(DeepSpeedConfigModel):
    """reference ``runtime/compiler.py`` — on TPU everything is jitted; these
    knobs control donation and jit options."""
    enabled = True
    backend = "xla"
    kwargs = {}
    donate_state = True


class AutotuningConfig(DeepSpeedConfigModel):
    enabled = False
    start_profile_step = 3
    end_profile_step = 5
    metric = "throughput"
    fast = True
    max_train_batch_size = None
    mp_size = 1
    num_tuning_micro_batch_sizes = 3
    tuner_type = "gridsearch"
    tuner_early_stopping = 5
    tuner_num_trials = 50


class OverlapConfig(DeepSpeedConfigModel):
    """Compute/communication overlap schedule (runtime/zero/overlap_schedule.py).

    ``schedule`` turns on the scheduled qgZ step: double-buffered parameter
    block prefetch inside the layer scan plus the bucketized grad exchange at
    the GAS boundary. Default-off — the unscheduled path stays the reference
    numerics until parity is pinned for a model/config combination.
    ``prefetch_depth`` is how many layer blocks of gathered parameters stay
    in flight ahead of compute (0 = fetch-at-use); ``grad_buckets`` is how
    many independent exchange chains the stacked grad reduce splits into."""
    schedule = False
    prefetch_depth = 1
    grad_buckets = 2


class MoEConfig(DeepSpeedConfigModel):
    enabled = False
    ep_size = 1
    moe_param_group = False
    use_residual = False


# Every key DeepSpeedConfig understands at the top level. A key outside this
# set is a config bug (e.g. the classic "zero_optimisation" typo silently
# training at stage 0) and raises — the reference's config system similarly
# validates via pydantic models (``runtime/config_utils.py``).
KNOWN_TOP_LEVEL_KEYS = {
    C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
    C.GRADIENT_ACCUMULATION_STEPS, C.STEPS_PER_PRINT, C.WALL_CLOCK_BREAKDOWN,
    C.DUMP_STATE, C.GRADIENT_CLIPPING, C.PRESCALE_GRADIENTS,
    C.GRADIENT_PREDIVIDE_FACTOR, C.SPARSE_GRADIENTS, C.PREFETCH_BATCHES,
    C.FUSED_STEP,
    C.OPTIMIZER, C.SCHEDULER,
    C.FP16, C.BF16, C.DATA_TYPES, C.ZERO_OPTIMIZATION,
    C.ACTIVATION_CHECKPOINTING, C.PIPELINE, C.TENSOR_PARALLEL,
    C.SEQUENCE_PARALLEL_SIZE, C.EXPERT_PARALLEL_SIZE, C.COMMS_LOGGER,
    C.MONITOR_TENSORBOARD, C.MONITOR_CSV, C.MONITOR_WANDB, C.FLOPS_PROFILER,
    C.TELEMETRY, C.RESILIENCE, C.OVERLAP,
    C.ELASTICITY, C.AUTOTUNING, C.CHECKPOINT, C.COMPILE,
    "moe", "seed", "hybrid_engine", "curriculum_learning", "data_efficiency",
    "compression_training", "eigenvalue", "progressive_layer_drop",
    "correctness_guards",
}

# Reference keys that are accepted but have no TPU effect (the GPU-side
# machinery they control is subsumed by XLA); they log once instead of raising.
INERT_TOP_LEVEL_KEYS = {
    "zero_allow_untested_optimizer", "communication_data_type",
    "seq_parallel_communication_data_type", "memory_breakdown",
    "dataloader_drop_last", "amp", "aio", "use_node_local_storage",
    # further reference keys common in shipped HF/DeepSpeed example configs
    # whose GPU-side machinery XLA subsumes — accepted, logged, inert
    "zero_force_ds_cpu_optimizer", "sparse_attention", "timers",
    "gradient_noise_scale", "sparse_gradients_enabled", "fp8",
}

# Renamed/retired keys (reference pydantic ``deprecated``/``new_param`` field
# metadata, ``config_utils.py``): old key -> replacement hint.
DEPRECATED_TOP_LEVEL_KEYS = {
    "cpu_offload": "zero_optimization.offload_optimizer",
    "cpu_offload_params": "zero_optimization.offload_param",
    "scheduler_params": "scheduler.params",
    "disable_allgather": None,
}

AUTO = "auto"


class DeepSpeedConfigError(ValueError):
    """Configuration error (reference ``runtime/config.py`` DeepSpeedConfigError).
    Subclasses ValueError so existing except-ValueError callers keep working."""


class DeepSpeedConfig:

    def __init__(self, config, mpu=None, mesh_topology=None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise FileNotFoundError(f"DeepSpeed config file not found: {config}")
            with open(config) as f:
                self._param_dict = json.load(f)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        elif config is None:
            self._param_dict = {}
        else:
            raise DeepSpeedConfigError(
                f"Expected dict or path for config, got {type(config)}")
        self.mesh_topology = mesh_topology
        self._validate_top_level_keys(self._param_dict)
        self._initialize_params(self._param_dict)
        self._do_sanity_check()

    def _validate_top_level_keys(self, pd):
        import difflib
        for key in pd:
            if key in KNOWN_TOP_LEVEL_KEYS:
                continue
            if key in INERT_TOP_LEVEL_KEYS:
                logger.info(f"config key '{key}' accepted but has no effect on TPU")
                continue
            if key in DEPRECATED_TOP_LEVEL_KEYS:
                new = DEPRECATED_TOP_LEVEL_KEYS[key]
                hint = f"; use '{new}'" if new else " and has no replacement"
                logger.warning(f"config key '{key}' is deprecated{hint}")
                continue
            close = difflib.get_close_matches(
                key, KNOWN_TOP_LEVEL_KEYS | INERT_TOP_LEVEL_KEYS, n=1)
            hint = f" (did you mean '{close[0]}'?)" if close else ""
            raise DeepSpeedConfigError(f"Unknown top-level config key '{key}'{hint}. "
                             f"Valid keys: {sorted(KNOWN_TOP_LEVEL_KEYS)}")

    @staticmethod
    def _auto(pd, name, default):
        """Scalar lookup with HF-style "auto" support: "auto" means "derive it"
        and resolves to the default (for the batch triple, to None so
        ``resolve_batch_params`` fills it from the other two)."""
        v = get_scalar_param(pd, name, default)
        return default if v == AUTO else v

    # mirrors reference config.py:798 _initialize_params
    def _initialize_params(self, pd):
        self.train_batch_size = self._auto(pd, C.TRAIN_BATCH_SIZE, None)
        self.train_micro_batch_size_per_gpu = self._auto(pd, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, None)
        self.gradient_accumulation_steps = self._auto(pd, C.GRADIENT_ACCUMULATION_STEPS, None)
        self.steps_per_print = get_scalar_param(pd, C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.wall_clock_breakdown = get_scalar_param(pd, C.WALL_CLOCK_BREAKDOWN, False)
        self.dump_state = get_scalar_param(pd, C.DUMP_STATE, False)
        self.gradient_clipping = self._auto(pd, C.GRADIENT_CLIPPING, 0.0)
        self.prescale_gradients = get_scalar_param(pd, C.PRESCALE_GRADIENTS, False)
        self.gradient_predivide_factor = get_scalar_param(pd, C.GRADIENT_PREDIVIDE_FACTOR, 1.0)
        self.sparse_gradients_enabled = get_scalar_param(pd, C.SPARSE_GRADIENTS, False)
        # background input pipeline: 0 disables, N>0 keeps N batches
        # assembled + device_put ahead (runtime/dataloader.py PrefetchLoader)
        self.prefetch_batches = int(get_scalar_param(pd, C.PREFETCH_BATCHES, 0))
        # fuse grad computation + optimizer apply into ONE jit at GAS=1:
        # forward() applies the update at the boundary (standard
        # forward/backward/step training loops only — a bare engine(batch)
        # call also steps the optimizer when this is on)
        self.fused_step = bool(get_scalar_param(pd, C.FUSED_STEP, False))

        self.optimizer = OptimizerConfig(pd.get(C.OPTIMIZER, {}))
        self.scheduler = SchedulerConfig(pd.get(C.SCHEDULER, {}))
        self.fp16 = FP16Config(pd.get(C.FP16, {}))
        self.bf16 = BF16Config(pd.get(C.BF16, {}))
        self.data_types = DataTypesConfig(pd.get(C.DATA_TYPES, {}))
        self.zero_config = DeepSpeedZeroConfig(pd.get(C.ZERO_OPTIMIZATION, {}))
        self.activation_checkpointing = ActivationCheckpointingConfig(pd.get(C.ACTIVATION_CHECKPOINTING, {}))
        self.pipeline = PipelineConfig(pd.get(C.PIPELINE, {}))
        self.tensor_parallel = TensorParallelConfig(pd.get(C.TENSOR_PARALLEL, {}))
        self.sequence_parallel_size = get_scalar_param(pd, C.SEQUENCE_PARALLEL_SIZE, 1)
        self.moe = MoEConfig(pd.get("moe", {}))
        self.expert_parallel_size = get_scalar_param(pd, C.EXPERT_PARALLEL_SIZE, self.moe.ep_size)
        self.comms_config = CommsLoggerConfig(pd.get(C.COMMS_LOGGER, {}))
        self.monitor_config_tb = MonitorWriterConfig(pd.get(C.MONITOR_TENSORBOARD, {}))
        self.monitor_config_csv = MonitorWriterConfig(pd.get(C.MONITOR_CSV, {}))
        self.monitor_config_wandb = WandbConfig(pd.get(C.MONITOR_WANDB, {}))
        self.flops_profiler_config = FlopsProfilerConfig(pd.get(C.FLOPS_PROFILER, {}))
        self.telemetry_config = TelemetryConfig(pd.get(C.TELEMETRY, {}))
        self.overlap_config = OverlapConfig(pd.get(C.OVERLAP, {}))
        self.resilience_config = ResilienceConfig(pd.get(C.RESILIENCE, {}))
        self.checkpoint_config = CheckpointConfig(pd.get(C.CHECKPOINT, {}))
        self.elasticity_config = ElasticityConfig(pd.get(C.ELASTICITY, {}))
        self.compile_config = CompileConfig(pd.get(C.COMPILE, {}))
        self.autotuning_config = AutotuningConfig(pd.get(C.AUTOTUNING, {}))
        self.seed = get_scalar_param(pd, "seed", 42)
        # trace-level correctness guards (runtime/guards.py — the jit-world
        # analog of the reference's safe-mode re-verification, stage3.py:1249)
        cg = dict(pd.get("correctness_guards", {}))
        self.correctness_guards = {
            "enabled": bool(cg.get("enabled", False)),
            "check_every": int(cg.get("check_every", 1)),
            "checkify_on_overflow": bool(cg.get("checkify_on_overflow", True)),
        }
        # data efficiency (reference runtime/data_pipeline/config.py):
        # legacy "curriculum_learning" section + "data_efficiency" umbrella
        # RLHF hybrid engine (reference runtime/hybrid_engine.py config section)
        self.hybrid_engine = dict(pd.get("hybrid_engine", {}))
        self.hybrid_engine_enabled = bool(self.hybrid_engine.get("enabled", False))
        self.curriculum_learning = dict(pd.get("curriculum_learning", {}))
        self.curriculum_enabled_legacy = bool(
            self.curriculum_learning.get("enabled", False))
        self.data_efficiency = dict(pd.get("data_efficiency", {}))

        # convenience views used by topology building
        self.pipeline_stages = self.pipeline.stages
        self.tensor_parallel_size = self.tensor_parallel.tp_size

        self.zero_enabled = self.zero_config.stage > 0
        self.zero_optimization_stage = self.zero_config.stage

    def resolve_batch_params(self, dp_world_size):
        """Auto-derive the train-batch triple (reference ``config.py:789-791``)."""
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is not None:
            pass
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            mb = tb // (gas * dp_world_size)
        elif mb is not None and gas is not None:
            tb = mb * gas * dp_world_size
        elif tb is not None:
            gas = 1
            mb = tb // dp_world_size
        elif mb is not None:
            gas = 1
            tb = mb * dp_world_size
        else:
            raise ValueError(
                "At least one of train_batch_size / train_micro_batch_size_per_gpu "
                "must be set in the config")
        if tb != mb * gas * dp_world_size:
            raise ValueError(
                f"Check batch related parameters. train_batch_size is not equal to "
                f"micro_batch_per_gpu * gradient_acc_step * world_size "
                f"{tb} != {mb} * {gas} * {dp_world_size}")
        if mb < 1 or gas < 1:
            raise ValueError(f"Derived invalid batch params: micro={mb} gas={gas}")
        self.train_batch_size, self.train_micro_batch_size_per_gpu, \
            self.gradient_accumulation_steps = tb, mb, gas
        return tb, mb, gas

    def _do_sanity_check(self):
        if self.fp16.enabled and self.bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        if self.zero_config.stage not in (0, 1, 2, 3):
            raise ValueError(f"invalid ZeRO stage {self.zero_config.stage}")

    def print_config(self):
        logger.info(f"DeepSpeedConfig: {json.dumps(self._param_dict, indent=2, default=str)}")
