"""Random layerwise token dropping (random-LTD).

Reference ``runtime/data_pipeline/data_routing/`` + ``csrc/random_ltd/``
(token_sort.cu, gather_scatter.cu): middle transformer layers process only a
random subset of tokens; the subset grows over training per a schedule. The
CUDA token sort/gather/scatter kernels are one-liners in XLA
(``jnp.argsort``/``take``/``scatter``) — exactly the "trivial in XLA" row of
the native-component inventory.
"""

import jax
import jax.numpy as jnp


def random_ltd_gather(x, keep, rng):
    """Pick ``keep`` random token positions per sequence.

    x: [batch, seq, ...]; returns (selected [batch, keep, ...], sorted index
    [batch, keep]) — indices are sorted so relative order (and any causal
    mask logic) is preserved, matching the reference's token_sort."""
    b, s = x.shape[0], x.shape[1]
    scores = jax.random.uniform(rng, (b, s))
    idx = jnp.argsort(scores, axis=1)[:, :keep]
    idx = jnp.sort(idx, axis=1)
    sel = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return sel, idx


def random_ltd_scatter(base, updates, idx):
    """Scatter processed tokens back into the full sequence (gather_scatter.cu
    inverse): base [batch, seq, ...], updates [batch, keep, ...]."""
    batch_idx = jnp.arange(base.shape[0])[:, None]
    return base.at[batch_idx, idx].set(updates)


class RandomLTDScheduler:
    """Kept-token schedule (reference ``data_routing/scheduler.py``): grows
    linearly from min_value to max_value (full sequence) over
    total_layer_budget steps, in multiples of ``step_size``."""

    def __init__(self, config=None, **kw):
        cfg = dict(config or {}, **kw)
        sched = cfg.get("schedule_config", cfg)
        self.min_value = sched.get("min_value", 128)
        self.max_value = sched.get("max_value", 1024)
        self.step_size = sched.get("step_size", 16)
        self.total_steps = sched.get("total_layer_budget",
                                     sched.get("total_step", 10000))
        self.current_value = self.min_value

    def get_value(self, global_step):
        frac = min(1.0, global_step / max(1, self.total_steps))
        v = self.min_value + frac * (self.max_value - self.min_value)
        v = int(self.step_size * (v // self.step_size))
        self.current_value = max(self.min_value, min(self.max_value, v))
        return self.current_value

    def state_dict(self):
        return {"current_value": self.current_value}

    def load_state_dict(self, sd):
        self.current_value = sd.get("current_value", self.min_value)
