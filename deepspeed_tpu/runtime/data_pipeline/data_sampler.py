"""Curriculum data sampling + data analysis.

Reference ``runtime/data_pipeline/data_sampling/``:
- ``DataAnalyzer`` (``data_analyzer.py:828L``) precomputes per-sample metric
  values over the dataset and writes index maps (sample→metric,
  metric-bucket→samples) backed by mmap ``indexed_dataset.py``.
- ``DeepSpeedDataSampler`` (``data_sampler.py:349L``) draws each batch only
  from samples whose metric is within the current curriculum difficulty.

TPU notes: batches must keep a static shape for jit, so difficulty gates the
*candidate pool*, not the batch size; sampling with replacement tops up when
the pool is smaller than a batch.
"""

import os

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.utils.logging import logger


class DataAnalyzer:
    """Compute per-sample metrics and (optionally) persist index maps."""

    def __init__(self, dataset, metric_names_and_fns, save_path=None,
                 num_workers=1):
        self.dataset = dataset
        self.metrics = dict(metric_names_and_fns)
        self.save_path = save_path

    def _samples(self):
        if isinstance(self.dataset, dict):
            n = len(next(iter(self.dataset.values())))
            for i in range(n):
                yield {k: v[i] for k, v in self.dataset.items()}
        else:
            yield from self.dataset

    def run_map_reduce(self):
        """Returns {metric_name: np.array of per-sample values}, sorted index
        map per metric (ascending difficulty), persisted when save_path set."""
        values = {m: [] for m in self.metrics}
        for sample in self._samples():
            for m, fn in self.metrics.items():
                values[m].append(fn(sample))
        out = {}
        for m, vals in values.items():
            arr = np.asarray(vals)
            order = np.argsort(arr, kind="stable")
            out[m] = {"values": arr, "index_sorted_by_metric": order}
            if self.save_path:
                os.makedirs(self.save_path, exist_ok=True)
                np.save(os.path.join(self.save_path, f"{m}_values.npy"), arr)
                np.save(os.path.join(self.save_path, f"{m}_index.npy"), order)
        return out

    @staticmethod
    def load(save_path, metric):
        return {"values": np.load(os.path.join(save_path, f"{metric}_values.npy")),
                "index_sorted_by_metric":
                    np.load(os.path.join(save_path, f"{metric}_index.npy"))}


class DistributedDataAnalyzer:
    """Multi-worker map-reduce over the corpus (reference
    ``data_sampling/data_analyzer.py`` DataAnalyzer: each worker maps its
    contiguous shard of sample indices and persists per-worker
    ``sample_to_metric`` files backed by the mmap indexed-dataset writer; a
    reduce step merges the shards with ``MMapIndexedDatasetBuilder.merge_file``
    and emits the same ``{metric}_values.npy`` / ``{metric}_index.npy`` maps
    the curriculum sampler consumes — identical to the single-process
    :class:`DataAnalyzer` output).

    Workers are independent processes: ``run_map`` only touches
    ``save_path/worker_<id>/``, so any launcher (ds_tpu ssh fan-out, slurm,
    multiprocessing) can run them; ``run_reduce`` runs once afterwards.
    """

    def __init__(self, dataset, metric_names_and_fns, save_path,
                 num_workers=1, worker_id=0):
        self.dataset = dataset
        self.metrics = dict(metric_names_and_fns)
        self.save_path = save_path
        self.num_workers = int(num_workers)
        self.worker_id = int(worker_id)
        if not (0 <= self.worker_id < self.num_workers):
            raise ValueError(f"worker_id {worker_id} out of range for "
                             f"{num_workers} workers")

    # ---------------------------------------------------------------- map
    def _num_samples(self):
        if isinstance(self.dataset, dict):  # dict-of-columns form
            return len(next(iter(self.dataset.values())))
        return len(self.dataset)

    def shard_indices(self):
        """This worker's contiguous sample range (reference
        ``get_shard_indices``): contiguity keeps the reduce a pure concat."""
        return np.array_split(np.arange(self._num_samples()),
                              self.num_workers)[self.worker_id]

    def _sample(self, i):
        if isinstance(self.dataset, dict):
            return {k: v[i] for k, v in self.dataset.items()}
        return self.dataset[i]

    def run_map(self):
        """Compute this worker's metric values and persist them as one
        indexed-dataset shard per metric under ``worker_<id>/``."""
        from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
            MMapIndexedDatasetBuilder)
        idx = self.shard_indices()
        wdir = os.path.join(self.save_path, f"worker_{self.worker_id}")
        os.makedirs(wdir, exist_ok=True)
        # one pass over the samples, all metrics per sample (corpus reads
        # dominate; M passes would multiply shard I/O by M)
        builders = {m: MMapIndexedDatasetBuilder(
            os.path.join(wdir, f"{m}_sample_to_value"), dtype=np.float64)
            for m in self.metrics}
        for i in idx:
            sample = self._sample(int(i))
            for m, fn in self.metrics.items():
                builders[m].add_item(np.asarray([fn(sample)], dtype=np.float64))
        for b in builders.values():
            b.finalize()
        with open(os.path.join(wdir, "shard.txt"), "w") as f:
            f.write(f"{idx[0] if len(idx) else 0} {len(idx)} "
                    f"{self.num_workers}")
        return wdir

    # -------------------------------------------------------------- reduce
    @staticmethod
    def run_reduce(save_path, metric_names, num_workers):
        """Merge all worker shards (in worker order == original sample order)
        and write the final index maps. Returns the same structure as
        :meth:`DataAnalyzer.run_map_reduce`."""
        from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
            MMapIndexedDataset, MMapIndexedDatasetBuilder, data_file_path)
        # consistency: every worker must have mapped with THIS worker count,
        # and the contiguous shards must cover the corpus exactly
        expected_start = 0
        for w in range(num_workers):
            with open(os.path.join(save_path, f"worker_{w}", "shard.txt")) as f:
                start, count, mapped_with = (int(t) for t in f.read().split())
            if mapped_with != num_workers:
                raise ValueError(
                    f"worker_{w} mapped with num_workers={mapped_with}, "
                    f"reduce called with {num_workers}")
            if count and start != expected_start:
                raise ValueError(
                    f"worker_{w} shard starts at {start}, expected "
                    f"{expected_start} — shards are not contiguous")
            expected_start += count
        out = {}
        for m in metric_names:
            merged_prefix = os.path.join(save_path, f"{m}_sample_to_value")
            builder = MMapIndexedDatasetBuilder(merged_prefix, dtype=np.float64)
            for w in range(num_workers):
                shard = os.path.join(save_path, f"worker_{w}",
                                     f"{m}_sample_to_value")
                builder.merge_file(shard)
            builder.finalize()
            ds = MMapIndexedDataset(merged_prefix)
            if int(ds.sizes.max(initial=1)) != 1 or int(ds.sizes.min(initial=1)) != 1:
                raise ValueError(f"metric {m}: expected one value per sample")
            # every item is one float64: one vectorized read of the .bin
            arr = np.array(np.memmap(data_file_path(merged_prefix),
                                     dtype=np.float64, mode="r")) \
                if len(ds) else np.empty((0,), np.float64)
            if arr.size != expected_start:
                raise ValueError(
                    f"metric {m}: merged {arr.size} values for "
                    f"{expected_start} samples")
            order = np.argsort(arr, kind="stable")
            out[m] = {"values": arr, "index_sorted_by_metric": order}
            np.save(os.path.join(save_path, f"{m}_values.npy"), arr)
            np.save(os.path.join(save_path, f"{m}_index.npy"), order)
        return out


class CurriculumDataSampler:
    """Difficulty-gated batch sampler (reference ``DeepSpeedDataSampler``).

    ``difficulty_type``: "value" (metric <= difficulty) or "percentile"
    (easiest difficulty% of samples are eligible)."""

    def __init__(self, metric_values, batch_size, curriculum_config,
                 difficulty_type="percentile", seed=0, drop_last=True):
        self.values = np.asarray(metric_values)
        self.order = np.argsort(self.values, kind="stable")
        self.batch_size = batch_size
        self.scheduler = CurriculumScheduler(curriculum_config)
        self.difficulty_type = difficulty_type
        self._rng = np.random.default_rng(seed)
        self.global_step = 0

    def set_step(self, step):
        self.global_step = step

    def _eligible(self):
        d = self.scheduler.get_difficulty(self.global_step)
        if self.difficulty_type == "percentile":
            k = max(1, int(len(self.order) * min(100, d) / 100.0))
            return self.order[:k]
        return np.nonzero(self.values <= d)[0]

    def next_batch_indices(self):
        pool = self._eligible()
        if len(pool) == 0:
            pool = self.order[:1]
            logger.warning("curriculum pool empty at current difficulty; "
                           "falling back to the single easiest sample")
        replace = len(pool) < self.batch_size
        idx = self._rng.choice(pool, size=self.batch_size, replace=replace)
        self.global_step += 1
        return idx

    def __iter__(self):
        while True:
            yield self.next_batch_indices()


def apply_seqlen_curriculum(batch, seqlen):
    """Legacy seqlen curriculum (reference engine.py curriculum_seqlen
    truncation): truncate every [batch, seq, ...] array to ``seqlen``."""
    def trunc(v):
        if hasattr(v, "ndim") and v.ndim >= 2 and v.shape[1] > seqlen:
            return v[:, :seqlen]
        return v

    return {k: trunc(v) for k, v in batch.items()}
