"""Curriculum data sampling + data analysis.

Reference ``runtime/data_pipeline/data_sampling/``:
- ``DataAnalyzer`` (``data_analyzer.py:828L``) precomputes per-sample metric
  values over the dataset and writes index maps (sample→metric,
  metric-bucket→samples) backed by mmap ``indexed_dataset.py``.
- ``DeepSpeedDataSampler`` (``data_sampler.py:349L``) draws each batch only
  from samples whose metric is within the current curriculum difficulty.

TPU notes: batches must keep a static shape for jit, so difficulty gates the
*candidate pool*, not the batch size; sampling with replacement tops up when
the pool is smaller than a batch.
"""

import os

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.utils.logging import logger


class DataAnalyzer:
    """Compute per-sample metrics and (optionally) persist index maps."""

    def __init__(self, dataset, metric_names_and_fns, save_path=None,
                 num_workers=1):
        self.dataset = dataset
        self.metrics = dict(metric_names_and_fns)
        self.save_path = save_path

    def _samples(self):
        if isinstance(self.dataset, dict):
            n = len(next(iter(self.dataset.values())))
            for i in range(n):
                yield {k: v[i] for k, v in self.dataset.items()}
        else:
            yield from self.dataset

    def run_map_reduce(self):
        """Returns {metric_name: np.array of per-sample values}, sorted index
        map per metric (ascending difficulty), persisted when save_path set."""
        values = {m: [] for m in self.metrics}
        for sample in self._samples():
            for m, fn in self.metrics.items():
                values[m].append(fn(sample))
        out = {}
        for m, vals in values.items():
            arr = np.asarray(vals)
            order = np.argsort(arr, kind="stable")
            out[m] = {"values": arr, "index_sorted_by_metric": order}
            if self.save_path:
                os.makedirs(self.save_path, exist_ok=True)
                np.save(os.path.join(self.save_path, f"{m}_values.npy"), arr)
                np.save(os.path.join(self.save_path, f"{m}_index.npy"), order)
        return out

    @staticmethod
    def load(save_path, metric):
        return {"values": np.load(os.path.join(save_path, f"{metric}_values.npy")),
                "index_sorted_by_metric":
                    np.load(os.path.join(save_path, f"{metric}_index.npy"))}


class CurriculumDataSampler:
    """Difficulty-gated batch sampler (reference ``DeepSpeedDataSampler``).

    ``difficulty_type``: "value" (metric <= difficulty) or "percentile"
    (easiest difficulty% of samples are eligible)."""

    def __init__(self, metric_values, batch_size, curriculum_config,
                 difficulty_type="percentile", seed=0, drop_last=True):
        self.values = np.asarray(metric_values)
        self.order = np.argsort(self.values, kind="stable")
        self.batch_size = batch_size
        self.scheduler = CurriculumScheduler(curriculum_config)
        self.difficulty_type = difficulty_type
        self._rng = np.random.default_rng(seed)
        self.global_step = 0

    def set_step(self, step):
        self.global_step = step

    def _eligible(self):
        d = self.scheduler.get_difficulty(self.global_step)
        if self.difficulty_type == "percentile":
            k = max(1, int(len(self.order) * min(100, d) / 100.0))
            return self.order[:k]
        return np.nonzero(self.values <= d)[0]

    def next_batch_indices(self):
        pool = self._eligible()
        if len(pool) == 0:
            pool = self.order[:1]
            logger.warning("curriculum pool empty at current difficulty; "
                           "falling back to the single easiest sample")
        replace = len(pool) < self.batch_size
        idx = self._rng.choice(pool, size=self.batch_size, replace=replace)
        self.global_step += 1
        return idx

    def __iter__(self):
        while True:
            yield self.next_batch_indices()


def apply_seqlen_curriculum(batch, seqlen):
    """Legacy seqlen curriculum (reference engine.py curriculum_seqlen
    truncation): truncate every [batch, seq, ...] array to ``seqlen``."""
    def trunc(v):
        if hasattr(v, "ndim") and v.ndim >= 2 and v.shape[1] > seqlen:
            return v[:, :seqlen]
        return v

    return {k: trunc(v) for k, v in batch.items()}
