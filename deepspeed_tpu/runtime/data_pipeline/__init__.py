from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
    CurriculumDataSampler, DataAnalyzer, DistributedDataAnalyzer)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (RandomLTDScheduler,
                                                            random_ltd_gather,
                                                            random_ltd_scatter)

__all__ = ["CurriculumScheduler", "CurriculumDataSampler", "DataAnalyzer",
           "DistributedDataAnalyzer", "RandomLTDScheduler",
           "random_ltd_gather", "random_ltd_scatter"]
