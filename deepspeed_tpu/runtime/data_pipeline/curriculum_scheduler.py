"""Curriculum scheduler (reference
``runtime/data_pipeline/curriculum_scheduler.py``): maps the global step to a
difficulty value under fixed_linear / fixed_root / fixed_discrete / custom
schedules. Difficulty is most commonly sequence length (legacy
``curriculum_learning`` config) or a data-sampler metric percentile
(``data_efficiency`` config).
"""

import math


class CurriculumScheduler:

    def __init__(self, config):
        self.state = {}
        self.config = dict(config)
        self.curriculum_type = config.get("curriculum_type", "seqlen")
        self.min_difficulty = config.get("min_difficulty", 8)
        self.max_difficulty = config.get("max_difficulty", 1024)
        self.schedule_type = config.get("schedule_type", "fixed_linear")
        cfg = config.get("schedule_config", config)
        self.total_step = cfg.get("total_curriculum_step", 10000)
        self.difficulty_step = cfg.get("difficulty_step", 8)
        self.root_degree = cfg.get("root_degree", 2)
        self.difficulties = cfg.get("difficulty", [])
        self.max_steps = cfg.get("max_step", [])
        self.custom_fn = None
        self.current_difficulty = self.min_difficulty

    def set_custom_get_difficulty(self, fn):
        self.custom_fn = fn

    def __fixed_linear(self, step):
        frac = min(1.0, step / max(1, self.total_step))
        d = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        return self._round(d)

    def __fixed_root(self, step):
        frac = min(1.0, step / max(1, self.total_step)) ** (1.0 / self.root_degree)
        d = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        return self._round(d)

    def __fixed_discrete(self, step):
        for d, s in zip(self.difficulties, self.max_steps):
            if step <= s:
                return d
        return self.difficulties[-1] if self.difficulties else self.max_difficulty

    def _round(self, d):
        # quantize to difficulty_step multiples (reference behavior keeps
        # seqlen a multiple of 8 for tensor-core/MXU alignment)
        step = max(1, self.difficulty_step)
        return int(min(self.max_difficulty,
                       max(self.min_difficulty, step * math.floor(d / step))))

    def get_difficulty(self, global_step):
        if self.custom_fn is not None:
            d = self.custom_fn(global_step)
        elif self.schedule_type == "fixed_linear":
            d = self.__fixed_linear(global_step)
        elif self.schedule_type == "fixed_root":
            d = self.__fixed_root(global_step)
        elif self.schedule_type == "fixed_discrete":
            d = self.__fixed_discrete(global_step)
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type}")
        self.current_difficulty = d
        return d

    def update_difficulty(self, global_step):
        return self.get_difficulty(global_step)

    def state_dict(self):
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd):
        self.current_difficulty = sd.get("current_difficulty",
                                         self.min_difficulty)
