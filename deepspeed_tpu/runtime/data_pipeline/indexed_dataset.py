"""Memory-mapped token dataset (.bin/.idx pair).

Capability analog of the reference's MMap indexed dataset
(``deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py:627``,
the Megatron-format pretraining corpus reader the data analyzer and
curriculum sampler run over): random access to billions of tokens without
loading them, O(1) per-sample slicing through ``np.memmap``.

Own format (documented, not byte-compatible): ``<path>.bin`` holds the
concatenated sample token arrays; ``<path>.idx`` holds a small header
(magic, version, dtype code, sample count) followed by int64 sizes and byte
offsets. TPU relevance: the host-side input pipeline feeds
``jax.device_put`` from memmap slices — no Python-object dataset in RAM.
"""

import json
import os
import struct

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16,
           9: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix):
    return prefix + ".bin"


def index_file_path(prefix):
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item`` token arrays, then ``finalize``."""

    def __init__(self, path_prefix, dtype=np.int32):
        self._prefix = path_prefix
        self._dtype = np.dtype(dtype)
        if self._dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(data_file_path(path_prefix), "wb")
        self._sizes = []

    def add_item(self, tokens):
        arr = np.ascontiguousarray(np.asarray(tokens, dtype=self._dtype))
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def merge_file(self, other_prefix):
        """Append another dataset with the same dtype (reference
        ``MMapIndexedDatasetBuilder.merge_file_``: distributed analyzer
        shards merge into one corpus)."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self._dtype:
            raise ValueError(f"dtype mismatch: {other.dtype} vs {self._dtype}")
        with open(data_file_path(other_prefix), "rb") as f:
            while True:
                chunk = f.read(1 << 24)
                if not chunk:
                    break
                self._bin.write(chunk)
        self._sizes.extend(other.sizes.tolist())

    def finalize(self):
        self._bin.close()
        sizes = np.asarray(self._sizes, dtype=np.int64)
        pointers = np.zeros_like(sizes)
        if sizes.size:
            np.cumsum(sizes[:-1] * self._dtype.itemsize, out=pointers[1:])
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<QQQ", _VERSION,
                                _DTYPE_CODES[self._dtype], sizes.size))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))


class MMapIndexedDataset:
    """Zero-copy reader. ``ds[i]`` -> np array view of sample i;
    ``ds.get(i, offset, length)`` slices within a sample (curriculum
    truncation); iteration and ``len`` as usual."""

    def __init__(self, path_prefix):
        self._prefix = path_prefix
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(path_prefix)}: bad magic")
            version, code, count = struct.unpack("<QQQ", f.read(24))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self.dtype = np.dtype(_DTYPES[int(code)])
            self.sizes = np.frombuffer(f.read(8 * count), dtype=np.int64)
            self._pointers = np.frombuffer(f.read(8 * count), dtype=np.int64)
        # np.memmap refuses 0-byte files; an analyzer shard that received no
        # samples is a valid (empty) dataset — but a 0-byte .bin whose index
        # claims tokens is a truncated copy, not an empty corpus
        if int(self.sizes.sum()) == 0:
            self._data = np.empty((0,), dtype=self.dtype)
        else:
            nbytes = os.path.getsize(data_file_path(path_prefix))
            want = int(self.sizes.sum()) * self.dtype.itemsize
            if nbytes < want:
                raise ValueError(
                    f"{data_file_path(path_prefix)}: {nbytes} bytes but the "
                    f"index expects {want} — truncated/corrupt data file")
            self._data = np.memmap(data_file_path(path_prefix),
                                   dtype=self.dtype, mode="r")

    def __len__(self):
        return self.sizes.size

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        ptr = self._pointers[i] // self.dtype.itemsize
        return self._data[ptr:ptr + self.sizes[i]]

    def get(self, i, offset=0, length=None):
        size = int(self.sizes[i])
        length = size - offset if length is None else min(length, size - offset)
        ptr = self._pointers[i] // self.dtype.itemsize + offset
        return self._data[ptr:ptr + length]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    @property
    def num_tokens(self):
        return int(self.sizes.sum())

    def describe(self):
        return json.dumps({"samples": len(self), "tokens": self.num_tokens,
                           "dtype": self.dtype.name})
