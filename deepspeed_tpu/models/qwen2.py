"""Qwen2 — Llama architecture + QKV biases + GQA.

Reference support: ``deepspeed/inference/v2/model_implementations/qwen_v2``
(``engine_factory.py:120``). Qwen2 differs from Llama by biases on the
q/k/v projections (``attention_bias``) and its vocab/geometry; the TPU
implementation parameterizes the Llama module (models/llama.py).
"""

from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

Qwen2ForCausalLM = LlamaForCausalLM


def qwen2_7b_config(**kw):
    defaults = dict(vocab_size=152064, hidden_size=3584, intermediate_size=18944,
                    num_hidden_layers=28, num_attention_heads=28,
                    num_key_value_heads=4, max_position_embeddings=4096,
                    attention_bias=True, rope_theta=1000000.0)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def tiny_qwen2_config(**kw):
    defaults = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=128,
                    attention_bias=True)
    defaults.update(kw)
    return LlamaConfig(**defaults)
