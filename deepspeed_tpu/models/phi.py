"""Phi family configs (reference v2 family ``model_implementations/phi``).
See models/parallel_block.py."""

from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                 ParallelBlockForCausalLM)

PhiForCausalLM = ParallelBlockForCausalLM


def phi_2_config(**kw):
    defaults = dict(vocab_size=51200, hidden_size=2560, intermediate_size=10240,
                    num_hidden_layers=32, num_attention_heads=32,
                    num_key_value_heads=32, max_position_embeddings=2048,
                    use_bias=True, fused_qkv=False, rotary_pct=0.4,
                    gelu_exact=False, lm_head_bias=True)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)


def tiny_phi_config(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=4, max_position_embeddings=128,
                    use_bias=True, fused_qkv=False, rotary_pct=0.5,
                    gelu_exact=False, lm_head_bias=True)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)
