"""GPT-J family configs (reference v1 injection container
``module_inject/containers/gptj.py`` + replace policy). See
models/parallel_block.py — GPT-J is the parallel-residual block with one
shared layernorm, separate un-biased q/k/v and biased MLP, partial
INTERLEAVED rotary (our native convention — loaded without any q/k
permutation, unlike the half-split NeoX/llama checkpoints), and a biased
lm_head."""

from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                 ParallelBlockForCausalLM)

GPTJForCausalLM = ParallelBlockForCausalLM


def gptj_6b_config(**kw):
    defaults = dict(vocab_size=50400, hidden_size=4096, intermediate_size=16384,
                    num_hidden_layers=28, num_attention_heads=16,
                    num_key_value_heads=16, max_position_embeddings=2048,
                    rotary_pct=64 / 256, use_bias=True, qkv_bias=False,
                    dense_bias=False, fused_qkv=False, dual_layernorm=False,
                    gelu_exact=False, lm_head_bias=True)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)


def tiny_gptj_config(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=4, max_position_embeddings=128,
                    rotary_pct=0.5, use_bias=True, qkv_bias=False,
                    dense_bias=False, fused_qkv=False, gelu_exact=False,
                    lm_head_bias=True)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)
