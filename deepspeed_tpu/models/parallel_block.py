"""Parallel-residual decoder families: Falcon and Phi (TPU-native flax).

Reference support surface: Falcon and Phi are two of the eight v2 serving
families (``inference/v2/engine_factory.py:68-129``, ``model_implementations/
{falcon,phi}``) and v1 injection containers. Both use the *parallel* residual
``x + attn(ln(x)) + mlp(ln(x))`` (one shared input layernorm) rather than the
sequential GPT/llama block; they differ in:

- Falcon: no linear biases, fused MQA/GQA qkv projection, full rotary,
  GELU MLP (dense_h_to_4h/dense_4h_to_h), tied lm_head optional.
- Phi: biases everywhere (incl. lm_head), separate q/k/v + dense, PARTIAL
  rotary (only the first ``rotary_dim`` of each head rotates), GELU MLP
  (fc1/fc2), final layernorm with bias.

One configurable module covers both; ``falcon.py`` / ``phi.py`` provide the
family configs. Non-scanned layer naming (``layers_{i}``) like mixtral.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    current_policy as remat_policy)
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.llama import rotary_embed


@dataclasses.dataclass(frozen=True)
class ParallelBlockConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    intermediate_size: int = 18176
    num_hidden_layers: int = 32
    num_attention_heads: int = 71
    num_key_value_heads: int = 1          # MQA (falcon-7b) by default
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    rotary_pct: float = 1.0               # phi/neox/gptj: partial rotary fraction
    use_bias: bool = False                # phi/neox: True
    qkv_bias: Any = None                  # gptj: False while mlp has biases
    dense_bias: Any = None                # (None -> use_bias)
    mlp_bias: Any = None
    fused_qkv: bool = True                # falcon/neox layout; phi/gptj: False
    dual_layernorm: bool = False          # neox: mlp reads its own LN of x
    gelu_exact: bool = True               # falcon/neox: erf; phi/gptj tanh
    lm_head_bias: bool = False            # phi/gptj: True (falcon: never)
    tie_lm_head: bool = False
    remat: bool = True
    dtype: Any = jnp.bfloat16
    # serving-module pins ((interface, impl_name) pairs) installed by
    # InferenceEngineV2 — see inference/v2/modules/module_registry.py
    serve_modules: Any = None

    def _bias(self, which):
        v = getattr(self, which)
        return self.use_bias if v is None else bool(v)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def rotary_dim(self):
        rd = int(self.head_dim * self.rotary_pct)
        return rd - rd % 2


def partial_rotary(x, positions, theta, rotary_dim):
    """Rotate only the leading ``rotary_dim`` of each head (phi-style)."""
    if rotary_dim >= x.shape[-1]:
        return rotary_embed(x, positions, theta)
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    return jnp.concatenate([rotary_embed(rot, positions, theta), rest], axis=-1)


class _LN(nn.Module):
    eps: float
    dtype: Any

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + self.eps) * scale + bias).astype(self.dtype)


class ParallelBlock(nn.Module):
    config: ParallelBlockConfig
    use_cache: bool = False  # module attribute: stays static under nn.remat

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        use_cache = self.use_cache
        B, T, D = x.shape
        H, KV, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        h = _LN(cfg.layer_norm_eps, cfg.dtype, name="input_layernorm")(x)
        # neox-style dual LN: the MLP branch normalizes x independently
        hm = _LN(cfg.layer_norm_eps, cfg.dtype,
                 name="post_attention_layernorm")(x) \
            if cfg.dual_layernorm else h

        dense = lambda feats, name, bias: nn.Dense(feats, use_bias=bias,
                                                   dtype=cfg.dtype, name=name)
        qb = cfg._bias("qkv_bias")
        if cfg.fused_qkv:
            qkv = dense((H + 2 * KV) * Dh, "query_key_value", qb)(h)
            q = qkv[..., : H * Dh].reshape(B, T, H, Dh)
            k = qkv[..., H * Dh: (H + KV) * Dh].reshape(B, T, KV, Dh)
            v = qkv[..., (H + KV) * Dh:].reshape(B, T, KV, Dh)
        else:
            q = dense(H * Dh, "q_proj", qb)(h).reshape(B, T, H, Dh)
            k = dense(KV * Dh, "k_proj", qb)(h).reshape(B, T, KV, Dh)
            v = dense(KV * Dh, "v_proj", qb)(h).reshape(B, T, KV, Dh)
        q = partial_rotary(q, positions, cfg.rope_theta, cfg.rotary_dim)
        k = partial_rotary(k, positions, cfg.rope_theta, cfg.rotary_dim)

        from deepspeed_tpu.ops.flash_attention import NEG_INF, mha
        if use_cache:
            L = cfg.max_position_embeddings
            ck = self.variable("cache", "cached_key", jnp.zeros, (B, L, KV, Dh), cfg.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros, (B, L, KV, Dh), cfg.dtype)
            ci = self.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
            idx = ci.value
            ck.value = jax.lax.dynamic_update_slice(ck.value, k.astype(cfg.dtype), (0, idx, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(cv.value, v.astype(cfg.dtype), (0, idx, 0, 0))
            ci.value = idx + T
            key_pos = jnp.arange(L)[None, :]
            qry_pos = idx + jnp.arange(T)[:, None]
            bias = jnp.where(key_pos <= qry_pos, 0.0, NEG_INF)[None, None]
            rep = H // KV
            qg = q.reshape(B, T, KV, rep, Dh)
            scale = 1.0 / (Dh ** 0.5)
            logits = jnp.einsum("btkrd,bskd->bkrts", qg, ck.value).astype(jnp.float32) * scale
            logits = logits + bias[:, 0][:, None, None]
            probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            attn = jnp.einsum("bkrts,bskd->btkrd", probs, cv.value).reshape(B, T, H * Dh)
        else:
            attn = mha(q, k, v, causal=True).reshape(B, T, H * Dh)
        attn_out = dense(D, "dense", cfg._bias("dense_bias"))(attn)

        mb = cfg._bias("mlp_bias")
        act = nn.gelu(dense(cfg.intermediate_size, "fc1", mb)(hm),
                      approximate=not cfg.gelu_exact)
        mlp = dense(cfg.hidden_size, "fc2", mb)(act)
        return x + attn_out + mlp


class ParallelBlockForCausalLM(nn.Module):
    """Falcon/Phi causal LM; returns loss when the batch carries labels."""
    config: ParallelBlockConfig

    @nn.compact
    def __call__(self, batch, deterministic=True, use_cache=False, positions=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        embed = self.param("embed_tokens", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        x = embed.astype(cfg.dtype)[input_ids]
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        block_cls = nn.remat(ParallelBlock, prevent_cse=False,
                             policy=remat_policy()) \
            if (cfg.remat and not use_cache) else ParallelBlock
        for i in range(cfg.num_hidden_layers):
            x = block_cls(cfg, use_cache, name=f"layers_{i}")(x, positions)
        x = _LN(cfg.layer_norm_eps, cfg.dtype, name="final_layernorm")(x)
        head = embed if cfg.tie_lm_head else self.param(
            "lm_head", nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        hb = self.param("lm_head_bias", nn.initializers.zeros,
                        (cfg.vocab_size,), jnp.float32) \
            if (cfg.lm_head_bias and not cfg.tie_lm_head) else None
        if labels is None or hb is not None:
            # the biased head (phi) keeps the dense path — the fused CE has
            # no bias slot; falcon-size vocabs without bias go fused
            logits = x @ head.astype(cfg.dtype).T
            if hb is not None:
                logits = logits + hb.astype(cfg.dtype)
            if labels is None:
                return logits
            from deepspeed_tpu.models.losses import next_token_loss
            return next_token_loss(logits, labels)
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, head, labels)

    # --- ZeRO-Infinity streaming protocol (runtime/zero/param_offload.py) ---
    # Covers falcon/phi/gptj/gpt-neox in one place (per-layer subtrees
    # stacked at split, like models/mixtral.py).
    @nn.nowrap
    def streaming_plan(self):
        return {"num_blocks": self.config.num_hidden_layers}

    @nn.nowrap
    def streaming_split(self, params):
        L = self.config.num_hidden_layers
        resident = {k: v for k, v in params.items()
                    if not k.startswith("layers_")}
        stacked = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                               *[params[f"layers_{i}"] for i in range(L)])
        return resident, stacked

    @nn.nowrap
    def streaming_merge(self, resident, stacked):
        out = dict(resident)
        for i in range(self.config.num_hidden_layers):
            out[f"layers_{i}"] = jax.tree.map(lambda x: x[i], stacked)
        return out

    @nn.nowrap
    def streaming_apply(self, resident, fetch, batch, deterministic=True,
                        rng=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids, labels = batch["input_ids"], batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        embed = resident["embed_tokens"]
        x = embed.astype(cfg.dtype)[input_ids]
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        block = ParallelBlock(cfg)

        def body(carry, i):
            bp = fetch(i)
            return block.apply({"params": bp}, carry, positions), None

        body = jax.checkpoint(body, prevent_cse=False)
        x, _ = jax.lax.scan(body, x, jnp.arange(cfg.num_hidden_layers))
        x = _LN(cfg.layer_norm_eps, cfg.dtype).apply(
            {"params": resident["final_layernorm"]}, x)
        head = embed if cfg.tie_lm_head else resident["lm_head"]
        hb = resident.get("lm_head_bias") \
            if (cfg.lm_head_bias and not cfg.tie_lm_head) else None
        if labels is None or hb is not None:
            logits = x @ head.astype(cfg.dtype).T
            if hb is not None:
                logits = logits + hb.astype(cfg.dtype)
            if labels is None:
                return logits
            from deepspeed_tpu.models.losses import next_token_loss
            return next_token_loss(logits, labels)
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, head, labels)

    def param_specs(self, params):
        """Megatron TP: qkv/fc1 column-split, dense/fc2 row-split, vocab-split
        embeddings (same pattern as models/llama.py)."""
        def spec_for(path, leaf):
            names = "/".join(str(getattr(p, "key", getattr(p, "name", ""))) for p in path)
            if getattr(leaf, "ndim", 0) <= 1:
                return None
            if "embed_tokens" in names or "lm_head" in names:
                return P("tp", None)
            if any(s in names for s in ("query_key_value", "q_proj", "k_proj",
                                        "v_proj", "fc1")):
                return P(None, "tp")
            if any(s in names for s in ("dense/", "fc2")) or names.endswith("dense/kernel"):
                return P("tp", None)
            return None

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = [spec_for(p, l) for p, l in flat]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(params), specs)
