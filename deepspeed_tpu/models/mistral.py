"""Mistral — Llama architecture + sliding-window attention + GQA.

Reference support: ``deepspeed/inference/v2/model_implementations/mistral``
(``engine_factory.py:83``). Architecturally Mistral is Llama with
``sliding_window`` local attention and 8 KV heads; the TPU implementation is
the Llama module parameterized accordingly (models/llama.py carries the
window mask in both the training and KV-cache paths).
"""

from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

MistralForCausalLM = LlamaForCausalLM


def mistral_config(**kw):
    """mistralai/Mistral-7B-v0.1 geometry."""
    defaults = dict(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                    num_hidden_layers=32, num_attention_heads=32,
                    num_key_value_heads=8, max_position_embeddings=4096,
                    sliding_window=4096, rope_theta=10000.0)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def tiny_mistral_config(**kw):
    defaults = dict(vocab_size=512, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=2, max_position_embeddings=128,
                    sliding_window=16)
    defaults.update(kw)
    return LlamaConfig(**defaults)
