"""BERT encoder model family (TPU-native flax implementation).

Closes the encoder hole vs the reference, which injects fused kernels into
bert/distilbert/roberta (``module_inject/replace_policy.py``,
``module_inject/containers/bert.py``, ``containers/distil_bert.py``) and uses
BERT fixtures throughout its unit tests. Same design stance as the 13 decoder
families here: scan-over-layers + remat + Megatron TP PartitionSpecs, HF
weight interop with exact-logits oracle tests.

Architecture (HF ``BertForMaskedLM`` conventions): learned word/position/
token-type embeddings + post-LN encoder blocks (self-attention -> residual ->
LayerNorm -> GELU MLP -> residual -> LayerNorm) + MLM transform head with the
decoder tied to the word embeddings. Attention is bidirectional; padding is
expressed through the flash kernel's segment-id masking (``attention_mask``
as segment ids — real tokens never attend padding), so no [T, T] mask tensor
is ever materialized. Note: padding *queries* attend padding (their outputs
are unused and masked from the loss); HF instead lets padding queries attend
real tokens, so outputs differ only at padded positions.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    current_policy as remat_policy)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2          # 0 = no token-type embedding (DistilBERT)
    position_offset: int = 0          # RoBERTa: padding_idx+1 = 2
    layer_norm_eps: float = 1e-12
    dropout: float = 0.0
    scan_layers: bool = True
    remat: bool = True
    dtype: Any = jnp.bfloat16

    @staticmethod
    def tiny(**kw):
        return BertConfig(vocab_size=512, hidden_size=64, num_hidden_layers=2,
                          num_attention_heads=4, intermediate_size=128,
                          max_position_embeddings=128, **kw)

    @staticmethod
    def base(**kw):  # 110M
        return BertConfig(**kw)

    @staticmethod
    def large(**kw):  # 340M
        return BertConfig(hidden_size=1024, num_hidden_layers=24,
                          num_attention_heads=16, intermediate_size=4096, **kw)


class BertLayer(nn.Module):
    """One post-LN encoder block (HF ``BertLayer``)."""
    config: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic=True):
        cfg = self.config
        B, T, D = x.shape
        H = cfg.num_attention_heads
        dense = lambda feats, name: nn.Dense(feats, dtype=cfg.dtype, name=name)
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                       dtype=cfg.dtype, name=name)
        from deepspeed_tpu.ops.flash_attention import mha

        q = dense(D, "query")(x).reshape(B, T, H, D // H)
        k = dense(D, "key")(x).reshape(B, T, H, D // H)
        v = dense(D, "value")(x).reshape(B, T, H, D // H)
        seg = None if attention_mask is None else attention_mask.astype(jnp.int32)
        ctx = mha(q, k, v, causal=False, segment_ids=seg).reshape(B, T, D)
        ctx = dense(D, "attn_out")(ctx)
        ctx = nn.Dropout(cfg.dropout)(ctx, deterministic=deterministic)
        x = ln("attn_ln")(x + ctx)

        h = nn.gelu(dense(cfg.intermediate_size, "intermediate")(x),
                    approximate=False)
        h = dense(D, "output")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return ln("out_ln")(x + h)


class ScanBertLayer(nn.Module):
    """``deterministic`` is a module FIELD (static under scan+remat — a
    carried or traced Python bool would crash flax Dropout's bool coercion
    for any dropout > 0, the llama ``use_cache`` pattern); the attention
    mask rides as an ``nn.broadcast`` input."""
    config: BertConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, mask):
        x = BertLayer(self.config, name="block")(x, mask, self.deterministic)
        return x, None


class BertModel(nn.Module):
    """Embeddings + encoder stack; returns ``(hidden [B,T,D], word_embeddings
    [V,D])`` — the table is returned so heads can tie their decoder to it
    (flax compact modules cannot reach into a sibling's params)."""
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic=True):
        cfg = self.config
        B, T = input_ids.shape
        word = self.param("word_embeddings", nn.initializers.normal(0.02),
                          (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        pos = self.param("position_embeddings", nn.initializers.normal(0.02),
                         (cfg.max_position_embeddings + cfg.position_offset,
                          cfg.hidden_size), jnp.float32)
        if cfg.position_offset and attention_mask is not None:
            # RoBERTa position ids are pad-aware: cumsum of the non-pad mask
            # plus padding_idx (pads share padding_idx) — matches HF for any
            # padding layout, not just suffix padding
            m = attention_mask.astype(jnp.int32)
            pos_ids = jnp.cumsum(m, axis=1) * m + (cfg.position_offset - 1)
            x = word[input_ids] + pos[pos_ids]
        else:
            x = word[input_ids] + pos[jnp.arange(T) + cfg.position_offset][None]
        if cfg.type_vocab_size:
            typ = self.param("token_type_embeddings",
                             nn.initializers.normal(0.02),
                             (cfg.type_vocab_size, cfg.hidden_size),
                             jnp.float32)
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + typ[token_type_ids]
        x = x.astype(cfg.dtype)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="embeddings_ln")(x)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        if cfg.scan_layers:
            block = ScanBertLayer
            if cfg.remat:
                block = nn.remat(ScanBertLayer, prevent_cse=False,
                                 policy=remat_policy())
            Scanned = nn.scan(block,
                              variable_axes={"params": 0},
                              split_rngs={"params": True, "dropout": True},
                              length=cfg.num_hidden_layers,
                              in_axes=nn.broadcast,
                              metadata_params={nn.meta.PARTITION_NAME: "layers"})
            x, _ = Scanned(cfg, deterministic, name="layers")(x, attention_mask)
        else:
            block_cls = nn.remat(BertLayer, prevent_cse=False,
                                 policy=remat_policy()) if cfg.remat else BertLayer
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, attention_mask,
                                                       deterministic)
        return x, word


class BertForMaskedLM(nn.Module):
    """MLM head over :class:`BertModel`; returns the masked-LM loss when the
    batch carries ``labels`` (ignore index -100, HF convention), else logits.
    The decoder is tied to the word embeddings (HF default)."""
    config: BertConfig

    @nn.compact
    def __call__(self, batch, deterministic=True):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
            token_type_ids = batch.get("token_type_ids")
            attention_mask = batch.get("attention_mask")
        else:
            input_ids, labels, token_type_ids, attention_mask = batch, None, None, None

        x, word = BertModel(cfg, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic)

        # cls.predictions.transform + tied decoder
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="transform")(x)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         name="transform_ln")(x)
        bias = self.param("decoder_bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.float32)
        logits = (x @ word.astype(cfg.dtype).T).astype(jnp.float32) + bias

        if labels is None:
            return logits
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(jnp.sum(valid), 1)
        return -jnp.sum(jnp.where(valid, tok, 0.0)) / denom

    # --- ZeRO-Infinity streaming protocol (runtime/zero/param_offload.py) ---
    # Encoder family: the attention mask rides the scan as a closed-over
    # broadcast (matching the model's in_axes=nn.broadcast).
    @nn.nowrap
    def streaming_plan(self):
        if not self.config.scan_layers:
            return None
        return {"num_blocks": self.config.num_hidden_layers}

    @nn.nowrap
    def streaming_split(self, params):
        resident = {k: ({kk: vv for kk, vv in v.items() if kk != "layers"}
                        if k == "bert" else v)
                    for k, v in params.items()}
        return resident, params["bert"]["layers"]["block"]

    @nn.nowrap
    def streaming_merge(self, resident, stacked):
        out = {k: (dict(v) if k == "bert" else v) for k, v in resident.items()}
        out.setdefault("bert", {})["layers"] = {"block": stacked}
        return out

    @nn.nowrap
    def streaming_apply(self, resident, fetch, batch, deterministic=True,
                        rng=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
            token_type_ids = batch.get("token_type_ids")
            attention_mask = batch.get("attention_mask")
        else:
            input_ids, labels, token_type_ids, attention_mask = \
                batch, None, None, None
        bert = resident["bert"]
        B, T = input_ids.shape
        word = bert["word_embeddings"]
        pos = bert["position_embeddings"]
        if cfg.position_offset and attention_mask is not None:
            m = attention_mask.astype(jnp.int32)
            pos_ids = jnp.cumsum(m, axis=1) * m + (cfg.position_offset - 1)
            x = word[input_ids] + pos[pos_ids]
        else:
            x = word[input_ids] + pos[jnp.arange(T) + cfg.position_offset][None]
        if cfg.type_vocab_size:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + bert["token_type_embeddings"][token_type_ids]
        x = x.astype(cfg.dtype)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype).apply(
            {"params": bert["embeddings_ln"]}, x)
        stochastic = rng is not None and not deterministic and cfg.dropout > 0
        if stochastic:
            x = nn.Dropout(cfg.dropout).apply(
                {}, x, deterministic=False,
                rngs={"dropout": jax.random.fold_in(rng, -1)})
        layer = BertLayer(cfg)

        def body(carry, i):
            bp = fetch(i)
            rngs = {"dropout": jax.random.fold_in(rng, i)} if stochastic else None
            return layer.apply({"params": bp}, carry, attention_mask,
                               deterministic, rngs=rngs), None

        body = jax.checkpoint(body, prevent_cse=False)
        x, _ = jax.lax.scan(body, x, jnp.arange(cfg.num_hidden_layers))

        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype).apply(
            {"params": resident["transform"]}, x)
        x = nn.gelu(x, approximate=False)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype).apply(
            {"params": resident["transform_ln"]}, x)
        logits = (x @ word.astype(cfg.dtype).T).astype(jnp.float32) + \
            resident["decoder_bias"]
        if labels is None:
            return logits
        valid = labels != -100
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok = jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(jnp.sum(valid), 1)
        return -jnp.sum(jnp.where(valid, tok, 0.0)) / denom

    def param_specs(self, params):
        """Megatron TP specs: q/k/v/intermediate column-split, attn_out/output
        row-split, embeddings vocab-split (same pattern as the decoder
        families; consumed by the engine partitioner and auto-TP)."""
        cfg = self.config

        def spec_for(path, leaf):
            names = "/".join(str(getattr(p, "key", getattr(p, "name", "")))
                             for p in path)
            scan_prefix = (None,) if (cfg.scan_layers and "layers/" in names) else ()
            if leaf.ndim == 1 + len(scan_prefix):
                return None
            if "word_embeddings" in names:
                return P("tp", None)
            if any(k in names for k in ("query", "key", "value", "intermediate")):
                return P(*scan_prefix, None, "tp")
            if any(k in names for k in ("attn_out", "output/")):
                return P(*scan_prefix, "tp", None)
            return None

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = [spec_for(path, leaf) for path, leaf in flat]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), specs)
