"""GPT-2 model family (TPU-native flax implementation).

The reference frames models as user-supplied torch modules plus fused-kernel
shells (``deepspeed/ops/transformer/transformer.py``,
``model_implementations/``); this package ships first-class JAX models so the
engine, ZeRO, TP and the benchmarks have a standard flagship. Design notes:

- optional ``scan_layers``: parameters stacked [L, ...] and the layer stack run
  under ``lax.scan`` — this is what makes ZeRO-3 gather per-block (the
  ``stage3_max_live_parameters`` analog) and keeps compile time O(1) in depth
- optional ``remat``: ``jax.checkpoint`` per block (activation checkpointing,
  reference ``runtime/activation_checkpointing/checkpointing.py``)
- ``param_specs``: tensor-parallel PartitionSpecs (Megatron-style column/row
  split of QKV/MLP, vocab-split embedding) consumed by the engine's partitioner
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    current_policy as remat_policy)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    scan_layers: bool = True
    remat: bool = True
    dtype: Any = jnp.bfloat16

    @staticmethod
    def tiny(**kw):
        return GPT2Config(vocab_size=512, n_positions=128, n_embd=64, n_layer=2,
                          n_head=4, **kw)

    @staticmethod
    def small(**kw):  # 124M
        return GPT2Config(**kw)

    @staticmethod
    def medium(**kw):  # 350M
        return GPT2Config(n_embd=1024, n_layer=24, n_head=16, **kw)

    @staticmethod
    def large(**kw):  # 774M
        return GPT2Config(n_embd=1280, n_layer=36, n_head=20, **kw)


def causal_attention(q, k, v, dtype, dropout_rng=None, dropout=0.0, deterministic=True):
    """Plain causal MHA core — the XLA-fusion path. The Pallas flash-attention
    kernel (ops/flash_attention.py) slots in behind the same signature."""
    from deepspeed_tpu.ops.flash_attention import mha
    return mha(q, k, v, causal=True)


class SelfAttention(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        D, H = cfg.n_embd, cfg.n_head
        qkv = nn.Dense(3 * D, dtype=cfg.dtype, name="c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        B, T = x.shape[0], x.shape[1]
        q = q.reshape(B, T, H, D // H)
        k = k.reshape(B, T, H, D // H)
        v = v.reshape(B, T, H, D // H)
        out = causal_attention(q, k, v, cfg.dtype)
        out = out.reshape(B, T, D)
        out = nn.Dense(D, dtype=cfg.dtype, name="c_proj")(out)
        out = nn.Dropout(cfg.dropout)(out, deterministic=deterministic)
        return out


class MLP(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="c_fc")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return h


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        x = x + SelfAttention(cfg, name="attn")(
            nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_1")(x),
            deterministic)
        x = x + MLP(cfg, name="mlp")(
            nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_2")(x),
            deterministic)
        return x


class ScanBlock(nn.Module):
    """Block adapted for nn.scan. ``deterministic`` is a static module FIELD:
    carried through lax.scan (or traced by remat) it would become a tracer
    and crash flax Dropout's bool coercion for any dropout > 0."""
    config: GPT2Config
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, _):
        x = Block(self.config, name="block")(x, self.deterministic)
        return x, None


class GPT2LMHeadModel(nn.Module):
    """Returns the LM cross-entropy loss when batch has ``labels`` (DeepSpeed
    convention: the wrapped module's forward returns the loss), else logits."""
    config: GPT2Config

    @nn.compact
    def __call__(self, batch, deterministic=True):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None

        B, T = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.n_embd),
                         jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01), (cfg.n_positions, cfg.n_embd),
                         jnp.float32)
        x = wte.astype(cfg.dtype)[input_ids] + wpe.astype(cfg.dtype)[None, :T]
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        if cfg.scan_layers:
            block = ScanBlock
            if cfg.remat:
                block = nn.remat(ScanBlock, prevent_cse=False,
                                 policy=remat_policy(),
                                 static_argnums=())
            ScannedBlocks = nn.scan(block,
                                    variable_axes={"params": 0},
                                    split_rngs={"params": True, "dropout": True},
                                    length=cfg.n_layer,
                                    metadata_params={nn.meta.PARTITION_NAME: "layers"})
            x, _ = ScannedBlocks(cfg, deterministic, name="h")(x, None)
        else:
            block_cls = nn.remat(Block, prevent_cse=False,
                                 policy=remat_policy()) if cfg.remat else Block
            for i in range(cfg.n_layer):
                x = block_cls(cfg, name=f"h_{i}")(x, deterministic)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, name="ln_f")(x)

        if labels is None:
            return x @ wte.astype(cfg.dtype).T  # tied embeddings
        # training: fused chunked linear+CE for large vocabs — never
        # materializes the [B, T, V] logits (models/losses.py)
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, wte, labels)

    # --- ZeRO-Infinity streaming protocol (runtime/zero/param_offload.py) ---
    # Same contract as models/llama.py: the engine's offload_param mode
    # streams block weights from the host tier inside the scan body.
    @nn.nowrap
    def streaming_plan(self):
        if not self.config.scan_layers:
            return None
        return {"num_blocks": self.config.n_layer}

    @nn.nowrap
    def streaming_split(self, params):
        resident = {k: v for k, v in params.items() if k != "h"}
        return resident, params["h"]["block"]

    @nn.nowrap
    def streaming_merge(self, resident, stacked):
        out = dict(resident)
        out["h"] = {"block": stacked}
        return out

    @nn.nowrap
    def streaming_apply(self, resident, fetch, batch, deterministic=True,
                        rng=None, prefetch_depth=0):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids, labels = batch["input_ids"], batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        wte = resident["wte"]
        x = wte.astype(cfg.dtype)[input_ids] + \
            resident["wpe"].astype(cfg.dtype)[None, :T]
        stochastic = rng is not None and not deterministic and cfg.dropout > 0
        if stochastic:
            x = nn.Dropout(cfg.dropout).apply(
                {}, x, deterministic=False,
                rngs={"dropout": jax.random.fold_in(rng, -1)})
        block = Block(cfg)

        def block_fn(carry, bp, i):
            rngs = {"dropout": jax.random.fold_in(rng, i)} if stochastic else None
            return block.apply({"params": bp}, carry, deterministic,
                               rngs=rngs)

        # save-nothing remat inside scheduled_scan: backward re-streams each
        # block (see llama.py); prefetch_depth>0 keeps that many blocks'
        # fetches in flight ahead of compute (overlap_schedule.scheduled_scan)
        from deepspeed_tpu.runtime.zero.overlap_schedule import scheduled_scan
        x = scheduled_scan(block_fn, x, cfg.n_layer, fetch,
                           prefetch_depth=prefetch_depth, remat=True)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype).apply(
            {"params": resident["ln_f"]}, x)
        if labels is None:
            return x @ wte.astype(cfg.dtype).T  # tied embeddings
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, wte, labels)

    def param_specs(self, params):
        """Tensor-parallel PartitionSpecs (Megatron column/row pattern)."""
        cfg = self.config

        def spec_for(path, leaf):
            names = [getattr(p, "key", getattr(p, "name", "")) for p in path]
            joined = "/".join(str(n) for n in names)
            scan_prefix = (None,) if (cfg.scan_layers and "h" in names) else ()
            if leaf.ndim == 1 + len(scan_prefix):  # biases / layernorm scales
                if "c_attn" in joined or "c_fc" in joined:
                    return P(*scan_prefix, "tp")
                return P(*scan_prefix) if scan_prefix else None
            if "wte" in joined or "wpe" in joined:
                return P("tp", None) if "wte" in joined else None
            if "c_attn" in joined or "c_fc" in joined:   # column parallel
                return P(*scan_prefix, None, "tp")
            if "c_proj" in joined:                        # row parallel
                return P(*scan_prefix, "tp", None)
            return None

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = [spec_for(path, leaf) for path, leaf in flat]
        treedef = jax.tree_util.tree_structure(params)
        return jax.tree_util.tree_unflatten(treedef, specs)


def gpt2_flops_per_token(cfg: GPT2Config, seq_len: int) -> float:
    """Approximate training FLOPs/token (6N + attention term) for MFU calc."""
    n_params = (cfg.vocab_size * cfg.n_embd + cfg.n_positions * cfg.n_embd +
                cfg.n_layer * (12 * cfg.n_embd ** 2) + cfg.n_embd * 2)
    return 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * seq_len
