"""GPT-NeoX family configs (reference v1 injection container
``module_inject/containers/gptneox.py`` + replace policy). See
models/parallel_block.py — NeoX is the parallel-residual block with its own
MLP layernorm (``use_parallel_residual=True``), fused interleaved QKV
(normalized to the concat layout at HF load, ``checkpoint/hf.py``), partial
rotary (``rotary_pct``, default 0.25) and biases everywhere."""

from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                 ParallelBlockForCausalLM)

GPTNeoXForCausalLM = ParallelBlockForCausalLM


def gpt_neox_20b_config(**kw):
    defaults = dict(vocab_size=50432, hidden_size=6144, intermediate_size=24576,
                    num_hidden_layers=44, num_attention_heads=64,
                    num_key_value_heads=64, max_position_embeddings=2048,
                    rotary_pct=0.25, use_bias=True, fused_qkv=True,
                    dual_layernorm=True, gelu_exact=True)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)


def tiny_gptneox_config(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=4, max_position_embeddings=128,
                    rotary_pct=0.25, use_bias=True, fused_qkv=True,
                    dual_layernorm=True, gelu_exact=True)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)
