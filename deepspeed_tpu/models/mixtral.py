"""Mixtral (sparse MoE) model family.

Covers the reference's Mixtral support (``inference/v2/model_implementations/
mixtral``) as a first-class training+inference model: Llama backbone with a
top-2-of-8 expert MLP per layer, experts sharded over the ``ep`` mesh axis via
the MoE layer (``deepspeed_tpu/moe``). The per-layer router aux losses are
summed into the LM loss with ``router_aux_loss_coef`` exactly as HF Mixtral
does.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    current_policy as remat_policy)
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.llama import LlamaAttention, LlamaConfig, RMSNorm
from deepspeed_tpu.moe.sharded_moe import MOELayer


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    router_aux_loss_coef: float = 0.02
    capacity_factor: float = 2.0
    # "indices" (routed gather/scatter, default) | "einsum" (GShard oracle) |
    # "gmm" (megablox grouped GEMM, capacity-free; needs 128-aligned dims)
    moe_backend: str = "indices"
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 1e6
    remat: bool = True
    dtype: Any = jnp.bfloat16
    # serving-module pins ((interface, impl_name) pairs) installed by
    # InferenceEngineV2 — see inference/v2/modules/module_registry.py
    serve_modules: Any = None

    @staticmethod
    def tiny(**kw):
        return MixtralConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                             num_hidden_layers=2, num_attention_heads=4,
                             num_key_value_heads=2, num_local_experts=4,
                             max_position_embeddings=128, **kw)

    @staticmethod
    def mixtral_8x7b(**kw):
        return MixtralConfig(**kw)

    def as_llama(self):
        return LlamaConfig(vocab_size=self.vocab_size, hidden_size=self.hidden_size,
                           intermediate_size=self.intermediate_size,
                           num_hidden_layers=self.num_hidden_layers,
                           num_attention_heads=self.num_attention_heads,
                           num_key_value_heads=self.num_key_value_heads,
                           max_position_embeddings=self.max_position_embeddings,
                           rms_norm_eps=self.rms_norm_eps, rope_theta=self.rope_theta,
                           dtype=self.dtype)


class MixtralExpertMLP(nn.Module):
    config: MixtralConfig

    # grouped-GEMM backend contract (moe/sharded_moe.py dispatch_mode="gmm"):
    # silu(x@w1) * (x@w3) @ w2, kernels listed gate/up/down
    GMM_COMPAT = ("w1", "w3", "w2")

    def gmm_shapes(self, d_model):
        f = self.config.intermediate_size
        return {"w1": (d_model, f), "w3": (d_model, f), "w2": (f, d_model)}

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda feats, name: nn.Dense(feats, use_bias=False, dtype=cfg.dtype, name=name)
        gate = nn.silu(dense(cfg.intermediate_size, "w1")(x))
        up = dense(cfg.intermediate_size, "w3")(x)
        return dense(cfg.hidden_size, "w2")(gate * up)


class MixtralBlock(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, x, positions, train=True):
        cfg = self.config
        x = x + LlamaAttention(cfg.as_llama(), name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(x), positions)
        moe_out, l_aux, _ = MOELayer(
            lambda: MixtralExpertMLP(cfg),
            num_experts=cfg.num_local_experts,
            k=cfg.num_experts_per_tok,
            capacity_factor=cfg.capacity_factor,
            eval_capacity_factor=cfg.capacity_factor,
            dispatch_mode=cfg.moe_backend,
            name="block_sparse_moe")(
                RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="post_attention_layernorm")(x),
                train)
        return x + moe_out, l_aux


class MixtralForCausalLM(nn.Module):
    config: MixtralConfig

    @nn.compact
    def __call__(self, batch, deterministic=True):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        embed = self.param("embed_tokens", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        x = embed.astype(cfg.dtype)[input_ids]
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

        total_aux = 0.0
        block_cls = nn.remat(MixtralBlock, prevent_cse=False,
                             policy=remat_policy(),
                             static_argnums=(3,)) if cfg.remat else MixtralBlock
        for i in range(cfg.num_hidden_layers):
            x, l_aux = block_cls(cfg, name=f"layers_{i}")(x, positions,
                                                          not deterministic)
            total_aux = total_aux + l_aux

        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(x)
        lm_head = self.param("lm_head", nn.initializers.normal(0.02),
                             (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        if labels is None:
            return x @ lm_head.astype(cfg.dtype).T
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        lm_loss = lm_head_next_token_loss(x, lm_head, labels)
        return lm_loss + cfg.router_aux_loss_coef * total_aux / cfg.num_hidden_layers

    # --- ZeRO-Infinity streaming protocol (runtime/zero/param_offload.py) ---
    # MoE is the headline Infinity workload: expert weights dominate the
    # parameter count (reference zero/parameter_offload.py was built for
    # trillion-param MoE on few devices). Mixtral's layers are homogeneous
    # per-layer subtrees (layers_i); the split stacks them so the host tier
    # streams one block — attention + ALL its experts — at a time.
    @nn.nowrap
    def streaming_plan(self):
        return {"num_blocks": self.config.num_hidden_layers}

    @nn.nowrap
    def streaming_split(self, params):
        L = self.config.num_hidden_layers
        resident = {k: v for k, v in params.items()
                    if not k.startswith("layers_")}
        stacked = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                               *[params[f"layers_{i}"] for i in range(L)])
        return resident, stacked

    @nn.nowrap
    def streaming_merge(self, resident, stacked):
        out = dict(resident)
        for i in range(self.config.num_hidden_layers):
            out[f"layers_{i}"] = jax.tree.map(lambda x: x[i], stacked)
        return out

    @nn.nowrap
    def streaming_apply(self, resident, fetch, batch, deterministic=True,
                        rng=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids, labels = batch["input_ids"], batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        x = resident["embed_tokens"].astype(cfg.dtype)[input_ids]
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        block = MixtralBlock(cfg)

        def body(carry, i):
            h, aux = carry
            bp = fetch(i)
            rngs = {"dropout": jax.random.fold_in(rng, i)} \
                if (rng is not None and not deterministic) else None
            h, l_aux = block.apply({"params": bp}, h, positions,
                                   not deterministic, rngs=rngs)
            return (h, aux + l_aux.astype(jnp.float32)), None

        body = jax.checkpoint(body, prevent_cse=False)
        (x, total_aux), _ = jax.lax.scan(
            body, (x, jnp.float32(0.0)), jnp.arange(cfg.num_hidden_layers))
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype).apply(
            {"params": resident["norm"]}, x)
        lm_head = resident["lm_head"]
        if labels is None:
            return x @ lm_head.astype(cfg.dtype).T
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        lm_loss = lm_head_next_token_loss(x, lm_head, labels)
        return lm_loss + cfg.router_aux_loss_coef * total_aux / cfg.num_hidden_layers

    def param_specs(self, params):
        """TP specs for attention + ep sharding for stacked experts."""
        def spec_for(path, leaf):
            names = "/".join(str(getattr(p, "key", getattr(p, "name", ""))) for p in path)
            if "experts" in names:
                if leaf.ndim >= 2:
                    # [E, in, out] expert kernels: ep on expert axis, tp on the
                    # column/row dim matching Megatron pattern
                    if "w1" in names or "w3" in names:
                        return P("ep", None, "tp")
                    if "w2" in names:
                        return P("ep", "tp", None)
                return P("ep")
            if leaf.ndim == 1:
                return None
            if "embed_tokens" in names or "lm_head" in names:
                return P("tp", None)
            if any(k in names for k in ("q_proj", "k_proj", "v_proj")):
                return P(None, "tp")
            if "o_proj" in names:
                return P("tp", None)
            return None

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = [spec_for(p, l) for p, l in flat]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(params), specs)
