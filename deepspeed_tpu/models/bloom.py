"""BLOOM model family (TPU-native flax).

Reference support surface: BLOOM is a v1 kernel-injection family
(``module_inject/containers/bloom.py``, policy in
``module_inject/replace_policy.py``) with its fused-softmax ALiBi path in
``csrc/transformer/inference/csrc/softmax.cu`` (the ``alibi`` argument).
TPU design: ALiBi is an additive attention bias — exactly the bias slot the
Pallas flash kernel already carries — so one [1, H, Tq, Tk] bias array gives
BLOOM the same fused fast path as every other family, no dedicated kernel.

Architecture (HF ``BloomForCausalLM``): sequential GPT-style blocks, fused
interleaved QKV ([H, 3, Dh] on the output dim — converted to our q/k/v concat
layout at load, ``checkpoint/hf.py``), LayerNorm on the embedding output,
biases everywhere, tied lm_head, no position embeddings (ALiBi only).
"""

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    current_policy as remat_policy)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 4096
    num_hidden_layers: int = 30
    num_attention_heads: int = 32
    layer_norm_epsilon: float = 1e-5
    max_position_embeddings: int = 2048   # KV-cache length for decode
    scan_layers: bool = True
    remat: bool = True
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def tiny(**kw):
        return BloomConfig(vocab_size=512, hidden_size=64,
                           num_hidden_layers=2, num_attention_heads=4, **kw)


def alibi_slopes(n_heads):
    """Per-head ALiBi slopes (Press et al.; matches HF ``build_alibi_tensor``):
    powers of 2^(-8/n) for the largest power-of-two head count, interleaved
    extras at half step for the remainder."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return jnp.asarray(pow2_slopes(n_heads), jnp.float32)
    base = 2 ** math.floor(math.log2(n_heads))
    slopes = pow2_slopes(base)
    extra = pow2_slopes(2 * base)[0::2][: n_heads - base]
    return jnp.asarray(slopes + extra, jnp.float32)


def alibi_bias(n_heads, q_pos, k_len):
    """[1, H, Tq, Tk] additive bias: slope_h * key_position. Shift-invariant
    per softmax row, so the absolute-key form matches HF's."""
    slopes = alibi_slopes(n_heads)                       # [H]
    keys = jnp.arange(k_len, dtype=jnp.float32)          # [Tk]
    bias = slopes[:, None, None] * keys[None, None, :]   # [H, 1, Tk]
    return jnp.broadcast_to(bias, (n_heads, q_pos.shape[-1], k_len))[None]


class BloomBlock(nn.Module):
    config: BloomConfig
    use_cache: bool = False

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        B, T, D = x.shape
        H, Dh = cfg.num_attention_heads, cfg.head_dim
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                       dtype=cfg.dtype, name=name)
        h = ln("input_layernorm")(x)
        qkv = nn.Dense(3 * D, dtype=cfg.dtype, name="query_key_value")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, Dh)
        k = k.reshape(B, T, H, Dh)
        v = v.reshape(B, T, H, Dh)

        from deepspeed_tpu.ops.flash_attention import NEG_INF, mha
        if self.use_cache:
            L = cfg.max_position_embeddings
            ck = self.variable("cache", "cached_key", jnp.zeros, (B, L, H, Dh), cfg.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros, (B, L, H, Dh), cfg.dtype)
            ci = self.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
            idx = ci.value
            ck.value = jax.lax.dynamic_update_slice(ck.value, k.astype(cfg.dtype), (0, idx, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(cv.value, v.astype(cfg.dtype), (0, idx, 0, 0))
            ci.value = idx + T
            key_pos = jnp.arange(L)[None, :]
            qry_pos = idx + jnp.arange(T)[:, None]
            mask = jnp.where(key_pos <= qry_pos, 0.0, NEG_INF)       # [T, L]
            ab = alibi_bias(H, qry_pos[:, 0], L)[0]                  # [H, T, L]
            bias = (ab + mask[None])[None]                           # [1, H, T, L]
            scale = 1.0 / (Dh ** 0.5)
            logits = jnp.einsum("bthd,bshd->bhts", q, ck.value).astype(jnp.float32) * scale
            logits = logits + bias
            probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            attn = jnp.einsum("bhts,bshd->bthd", probs, cv.value).reshape(B, T, D)
        else:
            qry = jnp.arange(T)
            bias = alibi_bias(H, qry, T)                 # [1, H, T, T]
            attn = mha(q, k, v, bias=bias, causal=True).reshape(B, T, D)
        x = x + nn.Dense(D, dtype=cfg.dtype, name="dense")(attn)

        h = ln("post_attention_layernorm")(x)
        m = nn.gelu(nn.Dense(4 * D, dtype=cfg.dtype, name="dense_h_to_4h")(h),
                    approximate=True)
        x = x + nn.Dense(D, dtype=cfg.dtype, name="dense_4h_to_h")(m)
        return x


class ScanBloomBlock(nn.Module):
    # deterministic is a static FIELD: carried through lax.scan it becomes a
    # tracer and crashes flax Dropout's bool coercion for dropout > 0
    config: BloomConfig
    use_cache: bool = False
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, _):
        x = BloomBlock(self.config, self.use_cache, name="block")(
            x, self.deterministic)
        return x, None


class BloomForCausalLM(nn.Module):
    """Returns the LM loss when the batch carries labels (engine convention),
    else logits. ``use_cache=True`` enables the KV-cache decode path for the
    v1 inference engine / hybrid-engine generation."""
    config: BloomConfig

    @nn.compact
    def __call__(self, batch, deterministic=True, use_cache=False,
                 positions=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        embed = self.param("word_embeddings", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        x = embed.astype(cfg.dtype)[input_ids]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="word_embeddings_layernorm")(x)

        if cfg.scan_layers:
            # scan stays active under use_cache (cache vars get a layer axis)
            # so scan-layout params serve decode without conversion — same
            # approach as models/llama.py
            block = ScanBloomBlock
            if cfg.remat and not use_cache:
                block = nn.remat(ScanBloomBlock, prevent_cse=False,
                                 policy=remat_policy())
            Scanned = nn.scan(block, variable_axes={"params": 0, "cache": 0},
                              split_rngs={"params": True, "dropout": True},
                              length=cfg.num_hidden_layers,
                              metadata_params={nn.meta.PARTITION_NAME: "layers"})
            x, _ = Scanned(cfg, use_cache, deterministic, name="h")((x),
                                                          None)
        else:
            block_cls = nn.remat(BloomBlock, prevent_cse=False,
                                 policy=remat_policy()) \
                if (cfg.remat and not use_cache) else BloomBlock
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, use_cache, name=f"h_{i}")(x, deterministic)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="ln_f")(x)
        if labels is None:
            return x @ embed.astype(cfg.dtype).T        # tied head
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, embed, labels)

    # --- ZeRO-Infinity streaming protocol (runtime/zero/param_offload.py) ---
    @nn.nowrap
    def streaming_plan(self):
        if not self.config.scan_layers:
            return None
        return {"num_blocks": self.config.num_hidden_layers}

    @nn.nowrap
    def streaming_split(self, params):
        resident = {k: v for k, v in params.items() if k != "h"}
        return resident, params["h"]["block"]

    @nn.nowrap
    def streaming_merge(self, resident, stacked):
        out = dict(resident)
        out["h"] = {"block": stacked}
        return out

    @nn.nowrap
    def streaming_apply(self, resident, fetch, batch, deterministic=True,
                        rng=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids, labels = batch["input_ids"], batch.get("labels")
        else:
            input_ids, labels = batch, None
        embed = resident["word_embeddings"]
        x = embed.astype(cfg.dtype)[input_ids]
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype).apply(
            {"params": resident["word_embeddings_layernorm"]}, x)
        block = BloomBlock(cfg)
        stochastic = rng is not None and not deterministic

        def body(carry, i):
            bp = fetch(i)
            rngs = {"dropout": jax.random.fold_in(rng, i)} if stochastic else None
            return block.apply({"params": bp}, carry,
                               deterministic, rngs=rngs), None

        body = jax.checkpoint(body, prevent_cse=False)
        x, _ = jax.lax.scan(body, x, jnp.arange(cfg.num_hidden_layers))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype).apply(
            {"params": resident["ln_f"]}, x)
        if labels is None:
            return x @ embed.astype(cfg.dtype).T
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, embed, labels)

    def param_specs(self, params):
        """Megatron TP: qkv/h_to_4h column-split, dense/4h_to_h row-split,
        vocab-split embedding (same pattern as models/llama.py)."""
        cfg = self.config

        def spec_for(path, leaf):
            names = "/".join(str(getattr(p, "key", getattr(p, "name", "")))
                             for p in path)
            # scanned block params carry a leading [L] axis
            scan_prefix = (None,) if (cfg.scan_layers and "h/block" in names) \
                else ()
            if leaf.ndim == 1 + len(scan_prefix):
                return None
            if "word_embeddings" in names and "layernorm" not in names:
                return P("tp", None)
            if "query_key_value" in names or "dense_h_to_4h" in names:
                return P(*scan_prefix, None, "tp")
            if "dense_4h_to_h" in names or "dense/kernel" in names:
                return P(*scan_prefix, "tp", None)
            return None

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = [spec_for(p, l) for p, l in flat]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), specs)
