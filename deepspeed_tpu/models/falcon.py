"""Falcon family configs (reference v2 family ``model_implementations/falcon``,
v1 container ``module_inject/containers``). See models/parallel_block.py."""

from deepspeed_tpu.models.parallel_block import (ParallelBlockConfig,
                                                 ParallelBlockForCausalLM)

FalconForCausalLM = ParallelBlockForCausalLM


def falcon_7b_config(**kw):
    defaults = dict(vocab_size=65024, hidden_size=4544, intermediate_size=18176,
                    num_hidden_layers=32, num_attention_heads=71,
                    num_key_value_heads=1, use_bias=False, fused_qkv=True,
                    rotary_pct=1.0)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)


def tiny_falcon_config(**kw):
    defaults = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                    num_hidden_layers=2, num_attention_heads=4,
                    num_key_value_heads=1, max_position_embeddings=128,
                    use_bias=False, fused_qkv=True)
    defaults.update(kw)
    return ParallelBlockConfig(**defaults)
