"""Shared loss functions.

``next_token_loss`` uses the logsumexp formulation rather than materializing a
full fp32 log-softmax over the vocab: on a 50k vocab at batch 32 × seq 1024 the
log-probs tensor alone is ~6.6 GB, which is what limits batch size on a 16 GB
HBM chip. With reductions only, XLA fuses the fp32 cast into the reduction and
never materializes the [B, T, V] fp32 intermediate.
"""

import jax
import jax.numpy as jnp


def next_token_loss(logits, labels, ignore_index=None):
    """Causal LM loss: predict labels[:, 1:] from logits[:, :-1].

    logits: [B, T, V] (any float dtype), labels: [B, T] int.
    """
    return cross_entropy(logits[:, :-1], labels[:, 1:], ignore_index=ignore_index)


def _masked_mean(nll, targets, ignore_index):
    if ignore_index is None:
        return nll.mean()
    mask = (targets != ignore_index).astype(jnp.float32)
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def cross_entropy(logits, targets, ignore_index=None):
    """Unshifted CE over the last axis (utility for non-causal tasks)."""
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tgt.astype(jnp.float32)
    return _masked_mean(nll, targets, ignore_index)


# ---------------------------------------------------------------------------
# fused linear + cross-entropy (chunked vocab)
# ---------------------------------------------------------------------------
#
# The lm-head matmul of a 50k-vocab model materializes [B*T, V] logits (bf16
# ~1.6GB at 16x1024) plus their gradient — often the single largest
# activation. This computes loss and gradients by scanning vocab chunks with
# an online logsumexp, so peak memory is O(B*T*chunk): the capability the
# reference gets from fused-softmax kernels, done the XLA way (scan + fused
# reductions; each chunk matmul still saturates the MXU).

import functools


def _pad_head(head, chunk):
    V, D = head.shape
    K = -(-V // chunk)
    pad = K * chunk - V
    if pad:
        head = jnp.pad(head, ((0, pad), (0, 0)))
    return head.reshape(K, chunk, D), V


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_linear_cross_entropy(x, head, labels, chunk=8192):
    """Per-token nll of softmax(x @ head.T) without materializing the logits.

    x: [N, D]; head: [V, D]; labels: [N] int -> nll [N] fp32.
    """
    nll, _ = _flce_fwd_impl(x, head, labels, chunk)
    return nll


def _flce_fwd_impl(x, head, labels, chunk):
    N, D = x.shape
    Wc, V = _pad_head(head, chunk)
    K = Wc.shape[0]

    def step(carry, inputs):
        m, l, tgt = carry
        w, kidx = inputs
        start = kidx * chunk
        logits = (x @ w.T).astype(jnp.float32)             # [N, chunk]
        col = start + jnp.arange(chunk)[None, :]
        logits = jnp.where(col < V, logits, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        rel = labels - start
        in_chunk = (labels >= start) & (labels < start + chunk)
        got = jnp.take_along_axis(logits, jnp.clip(rel, 0, chunk - 1)[:, None],
                                  axis=-1)[:, 0]
        tgt = tgt + jnp.where(in_chunk, got, 0.0)
        return (m_new, l, tgt), None

    init = (jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32), jnp.zeros((N,), jnp.float32))
    (m, l, tgt), _ = jax.lax.scan(step, init, (Wc, jnp.arange(K)))
    lse = m + jnp.log(l)
    return lse - tgt, lse


def _flce_fwd(x, head, labels, chunk):
    nll, lse = _flce_fwd_impl(x, head, labels, chunk)
    return nll, (x, head, labels, lse)


def _flce_bwd(chunk, res, g):
    x, head, labels, lse = res
    N, D = x.shape
    Wc, V = _pad_head(head, chunk)
    K = Wc.shape[0]
    g32 = g.astype(jnp.float32)

    def step(dx, inputs):
        w, kidx = inputs
        start = kidx * chunk
        logits = (x @ w.T).astype(jnp.float32)
        col = start + jnp.arange(chunk)[None, :]
        logits = jnp.where(col < V, logits, -jnp.inf)
        p = jnp.exp(logits - lse[:, None])                 # softmax chunk
        onehot = (labels[:, None] == col).astype(jnp.float32)
        dl = (p - onehot) * g32[:, None]                   # [N, chunk]
        dx = dx + dl @ w.astype(jnp.float32)               # fp32 carry
        dw = dl.T @ x.astype(jnp.float32)                  # [chunk, D]
        return dx, dw

    # dx accumulates in fp32 across chunks (one cast at the end) — a bf16
    # carry would round K times where the dense matmul rounds once
    dx0 = jnp.zeros(x.shape, jnp.float32)
    dx, dWc = jax.lax.scan(step, dx0, (Wc, jnp.arange(K)))
    dW = dWc.reshape(K * chunk, D)[:V].astype(head.dtype)
    return dx.astype(x.dtype), dW, None


fused_linear_cross_entropy.defvjp(_flce_fwd, _flce_bwd)

FUSED_CE_MIN_VOCAB = 16384


def lm_head_next_token_loss(x, head, labels, ignore_index=None, chunk=8192):
    """Causal-LM loss straight from hidden states + lm_head weights.

    x: [B, T, D]; head: [V, D]; labels: [B, T]. Uses the fused chunked path
    for large vocabularies (never materializes [B, T, V]), the plain matmul
    below ``FUSED_CE_MIN_VOCAB``."""
    B, T, D = x.shape
    V = head.shape[0]
    if V < FUSED_CE_MIN_VOCAB:
        logits = x @ head.astype(x.dtype).T
        return next_token_loss(logits, labels, ignore_index=ignore_index)
    xs = x[:, :-1].reshape(-1, D)
    ys = labels[:, 1:].reshape(-1)
    nll = fused_linear_cross_entropy(xs, head.astype(x.dtype), ys, chunk)
    return _masked_mean(nll, ys, ignore_index)
