"""Shared loss functions.

``next_token_loss`` uses the logsumexp formulation rather than materializing a
full fp32 log-softmax over the vocab: on a 50k vocab at batch 32 × seq 1024 the
log-probs tensor alone is ~6.6 GB, which is what limits batch size on a 16 GB
HBM chip. With reductions only, XLA fuses the fp32 cast into the reduction and
never materializes the [B, T, V] fp32 intermediate.
"""

import jax
import jax.numpy as jnp


def next_token_loss(logits, labels, ignore_index=None):
    """Causal LM loss: predict labels[:, 1:] from logits[:, :-1].

    logits: [B, T, V] (any float dtype), labels: [B, T] int.
    """
    return cross_entropy(logits[:, :-1], labels[:, 1:], ignore_index=ignore_index)


def cross_entropy(logits, targets, ignore_index=None):
    """Unshifted CE over the last axis (utility for non-causal tasks)."""
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tgt.astype(jnp.float32)
    if ignore_index is not None:
        mask = (targets != ignore_index).astype(jnp.float32)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll.mean()
