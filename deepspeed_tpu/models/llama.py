"""Llama model family (flagship) — TPU-native flax implementation.

Covers the reference's Llama support surface (inference containers
``module_inject/containers/llama.py``, v2 model implementation
``inference/v2/model_implementations/llama_v2``) as a first-class training +
inference model: RMSNorm, rotary embeddings, SwiGLU MLP, grouped-query
attention. Same TPU design as gpt2.py: scan-over-layers + remat + TP
PartitionSpecs (Megatron column/row pattern).
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    current_policy as remat_policy)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    attention_bias: bool = False      # qkv bias (Qwen2-family)
    attention_out_bias: bool = False  # o_proj bias too (InternLM-family)
    sliding_window: Any = None        # local-window attention (Mistral-family)
    # None/"flash": the Pallas flash kernel (XLA fallback). "ring": blockwise
    # context parallelism over the sp mesh axis (ops/ring_attention.py) — K/V
    # rotate around the ring via ppermute, sequence length scales linearly
    # with ring size; requires the global topology's sp axis > 1.
    attention_impl: Any = None
    head_dim: Any = None              # explicit override (Mistral-Nemo style);
    # None derives hidden_size // num_attention_heads (resolved in __post_init__)
    scan_layers: bool = True
    remat: bool = True
    dtype: Any = jnp.bfloat16
    # serving-module pins ((interface, impl_name) pairs) installed by
    # InferenceEngineV2 so the choice participates in the jit cache key —
    # see inference/v2/modules/module_registry.py
    serve_modules: Any = None

    def __post_init__(self):
        if self.head_dim is None:
            object.__setattr__(self, "head_dim",
                               self.hidden_size // self.num_attention_heads)

    @staticmethod
    def tiny(**kw):
        return LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                           num_hidden_layers=2, num_attention_heads=4,
                           num_key_value_heads=2, max_position_embeddings=128, **kw)

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_13b(**kw):
        return LlamaConfig(hidden_size=5120, intermediate_size=13824,
                           num_hidden_layers=40, num_attention_heads=40,
                           num_key_value_heads=40, **kw)

    @staticmethod
    def llama2_70b(**kw):
        return LlamaConfig(hidden_size=8192, intermediate_size=28672,
                           num_hidden_layers=80, num_attention_heads=64,
                           num_key_value_heads=8, **kw)

    def num_parameters(self):
        c = self
        qo = c.num_attention_heads * c.head_dim
        per_layer = (c.hidden_size * qo  # q
                     + 2 * c.hidden_size * c.num_key_value_heads * c.head_dim  # k,v
                     + qo * c.hidden_size  # o
                     + 3 * c.hidden_size * c.intermediate_size  # gate,up,down
                     + 2 * c.hidden_size)  # norms
        return (c.vocab_size * c.hidden_size * 2  # embed + lm_head
                + c.num_hidden_layers * per_layer + c.hidden_size)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(self.dtype)


def rotary_embed(x, positions, theta=10000.0):
    """Apply rotary position embeddings. x: [B, T, H, Dh]."""
    dh = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, dh, 2, dtype=jnp.float32) / dh))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,dh/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    rx1 = x1 * cos - x2 * sin
    rx2 = x2 * cos + x1 * sin
    out = jnp.stack([rx1, rx2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, deterministic=True, use_cache=False):
        cfg = self.config
        B, T, D = x.shape
        H, KV, Dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        dense = lambda feats, name, bias=False: nn.Dense(
            feats, use_bias=bias, dtype=cfg.dtype, name=name)
        ab = cfg.attention_bias
        q = dense(H * Dh, "q_proj", ab)(x).reshape(B, T, H, Dh)
        k = dense(KV * Dh, "k_proj", ab)(x).reshape(B, T, KV, Dh)
        v = dense(KV * Dh, "v_proj", ab)(x).reshape(B, T, KV, Dh)
        q = rotary_embed(q, positions, cfg.rope_theta)
        k = rotary_embed(k, positions, cfg.rope_theta)
        from deepspeed_tpu.ops.flash_attention import mha, NEG_INF

        if use_cache:
            # KV cache over a fixed max_position window; works for both prefill
            # (T = prompt length at index 0) and incremental decode (T = 1).
            # Functional analog of the reference's inference KV-cache kernels
            # (csrc/transformer/inference/csrc/pt_binding.cpp attention path).
            L = cfg.max_position_embeddings
            cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                     (B, L, KV, Dh), cfg.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                     (B, L, KV, Dh), cfg.dtype)
            cache_index = self.variable("cache", "cache_index",
                                        lambda: jnp.zeros((), jnp.int32))
            idx = cache_index.value
            cached_k.value = jax.lax.dynamic_update_slice(
                cached_k.value, k.astype(cfg.dtype), (0, idx, 0, 0))
            cached_v.value = jax.lax.dynamic_update_slice(
                cached_v.value, v.astype(cfg.dtype), (0, idx, 0, 0))
            cache_index.value = idx + T
            k, v = cached_k.value, cached_v.value
            # position j attends iff j <= idx + i (past + causal-within-block)
            key_pos = jnp.arange(L)[None, :]
            qry_pos = idx + jnp.arange(T)[:, None]
            visible = key_pos <= qry_pos
            if cfg.sliding_window:
                visible = visible & (key_pos > qry_pos - cfg.sliding_window)
            bias = jnp.where(visible, 0.0, NEG_INF)
            # grouped-query attention against the un-repeated cache: expanding
            # only the [B,T,H,Dh] query (not the [B,L,KV,Dh] cache) keeps decode
            # memory traffic at 1x the cache size
            rep = H // KV
            qg = q.reshape(B, T, KV, rep, Dh)
            scale = 1.0 / (Dh ** 0.5)
            logits = jnp.einsum("btkrd,bskd->bkrts", qg, k).astype(jnp.float32) * scale
            logits = logits + bias[None, None, None]
            probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            out = jnp.einsum("bkrts,bskd->btkrd", probs, v).reshape(B, T, H, Dh)
        elif cfg.attention_impl == "ring":
            # context parallelism: sequence stays sharded over sp; K/V blocks
            # rotate on ICI (ring_attention.py). GQA keys/values expand to
            # full heads first — the ring recurrence is per-head.
            from deepspeed_tpu.ops.ring_attention import ring_attention_sharded
            from deepspeed_tpu.parallel import groups
            topo = groups.get_topology()
            if topo.sp_size <= 1:
                raise ValueError(
                    "attention_impl='ring' needs an sp mesh axis > 1 "
                    "(sequence_parallel_size in the engine config)")
            rep = H // KV
            if rep > 1:
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            out = ring_attention_sharded(q, k, v, topo.mesh, causal=True)
        else:
            # GQA k/v pass through un-repeated — both mha implementations
            # handle head grouping internally (flash kernel maps q head h to
            # kv head h // rep in its index maps; no rep× HBM traffic).
            # Mistral-style sliding window goes through the kernel's window
            # parameter (whole-block skipping, O(T·W)) — never a [T,T] bias.
            out = mha(q, k, v, causal=True,
                      window=cfg.sliding_window or None)
        out = out.reshape(B, T, H * Dh)
        return dense(D, "o_proj", cfg.attention_out_bias)(out)


class LlamaMLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = lambda feats, name: nn.Dense(feats, use_bias=False, dtype=cfg.dtype, name=name)
        gate = nn.silu(dense(cfg.intermediate_size, "gate_proj")(x))
        up = dense(cfg.intermediate_size, "up_proj")(x)
        return dense(cfg.hidden_size, "down_proj")(gate * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, deterministic=True, use_cache=False):
        cfg = self.config
        x = x + LlamaAttention(cfg, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="input_layernorm")(x),
            positions, deterministic, use_cache=use_cache)
        x = x + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="post_attention_layernorm")(x))
        return x


class ScanLlamaBlock(nn.Module):
    config: LlamaConfig
    use_cache: bool = False

    @nn.compact
    def __call__(self, carry, _):
        x, positions = carry
        x = LlamaBlock(self.config, name="block")(x, positions,
                                                  use_cache=self.use_cache)
        return (x, positions), None


class LlamaForCausalLM(nn.Module):
    """Returns LM loss when batch carries ``labels`` (DeepSpeed convention)."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, batch, deterministic=True, use_cache=False, positions=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        embed = self.param("embed_tokens", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        x = embed.astype(cfg.dtype)[input_ids]
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

        if cfg.scan_layers:
            block = ScanLlamaBlock
            if cfg.remat and not use_cache:
                block = nn.remat(ScanLlamaBlock, prevent_cse=False,
                                 policy=remat_policy())
            Scanned = nn.scan(block,
                              variable_axes={"params": 0, "cache": 0},
                              split_rngs={"params": True, "dropout": True},
                              length=cfg.num_hidden_layers,
                              metadata_params={nn.meta.PARTITION_NAME: "layers"})
            (x, _), _ = Scanned(cfg, use_cache, name="layers")((x, positions), None)
        else:
            block_cls = nn.remat(LlamaBlock, prevent_cse=False,
                                 policy=remat_policy()) \
                if (cfg.remat and not use_cache) else LlamaBlock
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, positions, deterministic,
                                                       use_cache=use_cache)

        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="norm")(x)
        lm_head = self.param("lm_head", nn.initializers.normal(0.02),
                             (cfg.vocab_size, cfg.hidden_size), jnp.float32)

        if labels is None:
            return x @ lm_head.astype(cfg.dtype).T
        # training: fused chunked linear+CE for large vocabs — never
        # materializes the [B, T, V] logits (models/losses.py)
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, lm_head, labels)

    # --- ZeRO-Infinity streaming protocol (runtime/zero/param_offload.py) ---
    # The engine's offload_param mode drives the layer stack through these
    # instead of __call__: block weights are fetched from the host/NVMe tier
    # inside the scan body, so HBM never holds the stacked parameters.
    @nn.nowrap
    def streaming_plan(self):
        if not self.config.scan_layers:
            return None
        return {"num_blocks": self.config.num_hidden_layers}

    @nn.nowrap
    def streaming_split(self, params):
        """(resident, stacked): resident leaves stay device-side (the
        ``stage3_param_persistence_threshold`` analog), stacked leaves carry
        the leading scan dim and live in the host tier."""
        resident = {k: v for k, v in params.items() if k != "layers"}
        return resident, params["layers"]["block"]

    @nn.nowrap
    def streaming_merge(self, resident, stacked):
        out = dict(resident)
        out["layers"] = {"block": stacked}
        return out

    @nn.nowrap
    def streaming_apply(self, resident, fetch, batch, deterministic=True,
                        rng=None, prefetch_depth=0):
        """Forward pass with per-block parameter streaming. ``fetch(i)``
        returns block ``i``'s parameter tree (engine-provided, differentiable;
        its backward routes the block's grads to the host tier). ``rng`` (a
        PRNGKey) is folded per block for stochastic layers. ``prefetch_depth``
        keeps that many blocks' fetches in flight ahead of compute
        (overlap_schedule.scheduled_scan; 0 = fetch at use). Numerics are
        identical to ``__call__`` — same modules, same order."""
        cfg = self.config
        if isinstance(batch, dict):
            input_ids, labels = batch["input_ids"], batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        x = resident["embed_tokens"].astype(cfg.dtype)[input_ids]
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        block = LlamaBlock(cfg)

        def block_fn(carry, bp, i):
            rngs = {"dropout": jax.random.fold_in(rng, i)} \
                if (rng is not None and not deterministic) else None
            return block.apply({"params": bp}, carry, positions,
                               deterministic, rngs=rngs)

        # save-nothing remat regardless of the configured policy: a policy
        # that saved the fetched weights would pin all L blocks in HBM and
        # defeat the tier. Backward re-streams each block (the reference
        # re-gathers partitions for backward the same way).
        from deepspeed_tpu.runtime.zero.overlap_schedule import scheduled_scan
        x = scheduled_scan(block_fn, x, cfg.num_hidden_layers, fetch,
                           prefetch_depth=prefetch_depth, remat=True)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype).apply(
            {"params": resident["norm"]}, x)
        lm_head = resident["lm_head"]
        if labels is None:
            return x @ lm_head.astype(cfg.dtype).T
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, lm_head, labels)

    def param_specs(self, params):
        """Megatron-style TP specs: q/k/v/gate/up column-split, o/down row-split,
        embeddings vocab-split."""
        cfg = self.config

        def spec_for(path, leaf):
            names = "/".join(str(getattr(p, "key", getattr(p, "name", ""))) for p in path)
            scan_prefix = (None,) if (cfg.scan_layers and "layers/" in names) else ()
            if leaf.ndim == 1 + len(scan_prefix):
                return None
            if "embed_tokens" in names or "lm_head" in names:
                return P("tp", None)
            if any(k in names for k in ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj")):
                return P(*scan_prefix, None, "tp")
            if any(k in names for k in ("o_proj", "down_proj")):
                return P(*scan_prefix, "tp", None)
            return None

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = [spec_for(path, leaf) for path, leaf in flat]
        return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(params), specs)


def llama_flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token ≈ 6N + attention quadratic term."""
    return 6 * cfg.num_parameters() + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq_len
