"""Diffusers UNet building blocks, TPU-native (VERDICT r4 #9).

Capability analog of the reference's diffusers serving path
(``deepspeed/model_implementations/diffusers/unet.py`` wraps the torch UNet
with cuda-graph replay; the spatial CUDA kernels live in ``csrc/spatial`` and
``deepspeed/ops/transformer/inference/bias_add.py``). Here the blocks are
pure JAX functions over a diffusers-layout parameter dict:

- ``resnet_block_2d`` — GroupNorm→SiLU→Conv3x3→(+time emb)→GroupNorm→SiLU→
  Conv3x3 + skip, via the fused spatial ops (``ops/spatial.py``:
  ``bias_groupnorm``/``nhwc_bias_add`` — XLA fuses the elementwise chains the
  reference hand-writes in CUDA).
- ``basic_transformer_block`` / ``transformer_2d`` — diffusers
  BasicTransformerBlock/Transformer2DModel: LayerNorm → self-attention
  (through ``ops/flash_attention.mha``, non-causal) → optional
  cross-attention → GEGLU feed-forward (``ops/spatial.bias_geglu``).

Weights use the DIFFUSERS state-dict naming and layouts (conv kernels OIHW,
linear [out, in]); ``convert_diffusers_weights`` maps them to the NHWC/HWIO
forms these functions consume, so a real UNet block's tensors drop in. Data
layout is NHWC throughout — the TPU-native convolution layout.
"""

from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.flash_attention import mha
from deepspeed_tpu.ops.spatial import bias_geglu, bias_groupnorm, nhwc_bias_add


# ---------------------------------------------------------------- weights

def convert_diffusers_weights(sd, prefix="") -> Dict[str, Any]:
    """Torch diffusers state dict (numpy arrays) -> NHWC/HWIO param dict.

    Conv weights [O, I, kH, kW] -> [kH, kW, I, O]; linear weights [out, in]
    -> [in, out]; biases/norm affines pass through. Keys keep the diffusers
    dotted names (e.g. ``conv1.weight``) so block code reads naturally.
    """
    out = {}
    for k, v in sd.items():
        if prefix and not k.startswith(prefix):
            continue
        name = k[len(prefix):]
        v = np.asarray(v, np.float32)
        if name.endswith(".weight") and v.ndim == 4:
            v = v.transpose(2, 3, 1, 0)          # OIHW -> HWIO
        elif name.endswith(".weight") and v.ndim == 2:
            v = v.T                               # [out,in] -> [in,out]
        out[name] = jnp.asarray(v)
    return out


def _conv(x, w, b, stride=1):
    pad = (w.shape[0] - 1) // 2
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return nhwc_bias_add(y, b)


# ---------------------------------------------------------------- resnet

def resnet_block_2d(p, x, temb, groups=32, eps=1e-5):
    """Diffusers ResnetBlock2D forward. x: [N, H, W, C_in], temb: [N, T].

    Weight keys (diffusers naming): norm1/conv1/time_emb_proj/norm2/conv2
    [+ conv_shortcut when C_in != C_out].
    """
    h = bias_groupnorm(x, p["norm1.weight"], p["norm1.bias"], groups, eps)
    h = _conv(jax.nn.silu(h), p["conv1.weight"], p["conv1.bias"])
    if temb is not None and "time_emb_proj.weight" in p:
        t = jax.nn.silu(temb) @ p["time_emb_proj.weight"] + \
            p["time_emb_proj.bias"]
        h = h + t[:, None, None, :]
    h = bias_groupnorm(h, p["norm2.weight"], p["norm2.bias"], groups, eps)
    h = _conv(jax.nn.silu(h), p["conv2.weight"], p["conv2.bias"])
    if "conv_shortcut.weight" in p:
        x = _conv(x, p["conv_shortcut.weight"], p["conv_shortcut.bias"])
    return x + h


# ---------------------------------------------------------------- attention

def _layernorm(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def _attention(p, prefix, x, context, heads):
    """Diffusers Attention: to_q/to_k/to_v (no bias) + to_out.0 (bias);
    non-causal, through the shared mha op (flash kernel when eligible)."""
    ctx = x if context is None else context
    B, Tq, D = x.shape
    Tk = ctx.shape[1]
    dh = D // heads
    q = (x @ p[prefix + "to_q.weight"]).reshape(B, Tq, heads, dh)
    k = (ctx @ p[prefix + "to_k.weight"]).reshape(B, Tk, heads, dh)
    v = (ctx @ p[prefix + "to_v.weight"]).reshape(B, Tk, heads, dh)
    out = mha(q, k, v, causal=False).reshape(B, Tq, D)
    return out @ p[prefix + "to_out.0.weight"] + p[prefix + "to_out.0.bias"]


def basic_transformer_block(p, x, context=None, heads=8):
    """Diffusers BasicTransformerBlock: norm1→attn1 (self), norm2→attn2
    (cross; attends to x when context is None, as diffusers does), norm3→
    GEGLU ff (ff.net.0.proj + ff.net.2)."""
    h = _layernorm(x, p["norm1.weight"], p["norm1.bias"])
    x = x + _attention(p, "attn1.", h, None, heads)
    if "attn2.to_q.weight" in p:
        h = _layernorm(x, p["norm2.weight"], p["norm2.bias"])
        x = x + _attention(p, "attn2.", h, context, heads)
    h = _layernorm(x, p["norm3.weight"], p["norm3.bias"])
    h = bias_geglu(h @ p["ff.net.0.proj.weight"], p["ff.net.0.proj.bias"])
    return x + (h @ p["ff.net.2.weight"] + p["ff.net.2.bias"])


def transformer_2d(p, x, context=None, heads=8, groups=32, eps=1e-6,
                   num_layers=1):
    """Diffusers Transformer2DModel (linear-projection variant): GroupNorm →
    proj_in → spatial tokens → blocks → proj_out + residual.
    x: [N, H, W, C]."""
    N, H, W, C = x.shape
    res = x
    h = bias_groupnorm(x, p["norm.weight"], p["norm.bias"], groups, eps)
    h = h.reshape(N, H * W, C)
    h = h @ p["proj_in.weight"] + p["proj_in.bias"]
    for i in range(num_layers):
        blk = {k[len(f"transformer_blocks.{i}."):]: v for k, v in p.items()
               if k.startswith(f"transformer_blocks.{i}.")}
        h = basic_transformer_block(blk, h, context=context, heads=heads)
    h = h @ p["proj_out.weight"] + p["proj_out.bias"]
    return h.reshape(N, H, W, C) + res


# ---------------------------------------------------------------- unet block

def unet_down_block(p, x, temb, context=None, *, heads=8, groups=32,
                    num_resnets=1, has_attention=True):
    """One diffusers CrossAttnDownBlock2D-style step: resnet(s) + spatial
    transformer(s). ``context``: encoder hidden states ([N, Tctx, Dctx]) for
    the blocks' cross-attention (attn2); None = self-attention configuration.
    Parameter keys: resnets.{i}.*, attentions.{i}.*."""
    for i in range(num_resnets):
        rp = {k[len(f"resnets.{i}."):]: v for k, v in p.items()
              if k.startswith(f"resnets.{i}.")}
        x = resnet_block_2d(rp, x, temb, groups=groups)
        if has_attention:
            ap = {k[len(f"attentions.{i}."):]: v for k, v in p.items()
                  if k.startswith(f"attentions.{i}.")}
            x = transformer_2d(ap, x, context=context, heads=heads,
                               groups=groups)
    return x
