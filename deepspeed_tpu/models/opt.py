"""OPT model family (TPU-native flax implementation).

Reference support: v1 kernel-injection container
(``module_inject/containers/opt.py``) and v2 implementation
(``inference/v2/model_implementations/opt``, ``engine_factory.py:99``).
Architecture vs GPT-2: learned positional embeddings with OPT's +2 offset,
biased projections, ReLU FFN, pre-LayerNorm, untied final LN. Same TPU
design as gpt2.py: scan-over-layers + remat + TP param specs.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    current_policy as remat_policy)
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    scan_layers: bool = True
    # serving-module pins ((interface, impl_name) pairs) installed by
    # InferenceEngineV2 — see inference/v2/modules/module_registry.py
    serve_modules: Any = None
    remat: bool = True
    dtype: Any = jnp.bfloat16

    POSITION_OFFSET = 2  # OPT reserves positions 0/1 (HF modeling_opt)

    @staticmethod
    def tiny(**kw):
        return OPTConfig(vocab_size=512, hidden_size=64, ffn_dim=128,
                         num_hidden_layers=2, num_attention_heads=4,
                         max_position_embeddings=128, **kw)

    @staticmethod
    def opt_125m(**kw):
        return OPTConfig(**kw)

    @staticmethod
    def opt_1_3b(**kw):
        return OPTConfig(hidden_size=2048, ffn_dim=8192, num_hidden_layers=24,
                         num_attention_heads=32, **kw)

    @staticmethod
    def opt_13b(**kw):
        return OPTConfig(hidden_size=5120, ffn_dim=20480, num_hidden_layers=40,
                         num_attention_heads=40, **kw)

    @staticmethod
    def opt_30b(**kw):
        return OPTConfig(hidden_size=7168, ffn_dim=28672, num_hidden_layers=48,
                         num_attention_heads=56, **kw)


class OPTAttention(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        B, T, D = x.shape
        H = cfg.num_attention_heads
        Dh = D // H
        dense = lambda name: nn.Dense(D, use_bias=True, dtype=cfg.dtype, name=name)
        q = dense("q_proj")(x).reshape(B, T, H, Dh)
        k = dense("k_proj")(x).reshape(B, T, H, Dh)
        v = dense("v_proj")(x).reshape(B, T, H, Dh)
        from deepspeed_tpu.ops.flash_attention import mha
        out = mha(q, k, v, causal=True).reshape(B, T, D)
        return dense("out_proj")(out)


class OPTBlock(nn.Module):
    config: OPTConfig

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.config
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                       dtype=cfg.dtype, name=name)
        x = x + OPTAttention(cfg, name="self_attn")(
            ln("self_attn_layer_norm")(x), deterministic)
        h = ln("final_layer_norm")(x)
        h = nn.Dense(cfg.ffn_dim, dtype=cfg.dtype, name="fc1")(h)
        h = nn.relu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="fc2")(h)
        h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return x + h


class ScanOPTBlock(nn.Module):
    # deterministic is a static FIELD (see ScanBloomBlock note)
    config: OPTConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x, _):
        x = OPTBlock(self.config, name="block")(x, self.deterministic)
        return x, None


class OPTForCausalLM(nn.Module):
    """Loss when batch carries ``labels``, else logits (engine convention)."""
    config: OPTConfig

    @nn.compact
    def __call__(self, batch, deterministic=True):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids = batch["input_ids"]
            labels = batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        embed = self.param("embed_tokens", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        pos = self.param("embed_positions", nn.initializers.normal(0.01),
                         (cfg.max_position_embeddings + cfg.POSITION_OFFSET,
                          cfg.hidden_size), jnp.float32)
        x = embed.astype(cfg.dtype)[input_ids] + \
            pos.astype(cfg.dtype)[None, cfg.POSITION_OFFSET:cfg.POSITION_OFFSET + T]
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        if cfg.scan_layers:
            block = ScanOPTBlock
            if cfg.remat:
                block = nn.remat(ScanOPTBlock, prevent_cse=False,
                                 policy=remat_policy())
            Scanned = nn.scan(block, variable_axes={"params": 0},
                              split_rngs={"params": True, "dropout": True},
                              length=cfg.num_hidden_layers,
                              metadata_params={nn.meta.PARTITION_NAME: "layers"})
            x, _ = Scanned(cfg, deterministic, name="layers")(x, None)
        else:
            blk = nn.remat(OPTBlock, prevent_cse=False,
                           policy=remat_policy()) if cfg.remat else OPTBlock
            for i in range(cfg.num_hidden_layers):
                x = blk(cfg, name=f"layers_{i}")(x, deterministic)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                         name="final_layer_norm")(x)
        if labels is None:
            return x @ embed.astype(cfg.dtype).T  # tied embeddings
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, embed, labels)

    # --- ZeRO-Infinity streaming protocol (runtime/zero/param_offload.py) ---
    @nn.nowrap
    def streaming_plan(self):
        if not self.config.scan_layers:
            return None
        return {"num_blocks": self.config.num_hidden_layers}

    @nn.nowrap
    def streaming_split(self, params):
        resident = {k: v for k, v in params.items() if k != "layers"}
        return resident, params["layers"]["block"]

    @nn.nowrap
    def streaming_merge(self, resident, stacked):
        out = dict(resident)
        out["layers"] = {"block": stacked}
        return out

    @nn.nowrap
    def streaming_apply(self, resident, fetch, batch, deterministic=True,
                        rng=None):
        cfg = self.config
        if isinstance(batch, dict):
            input_ids, labels = batch["input_ids"], batch.get("labels")
        else:
            input_ids, labels = batch, None
        B, T = input_ids.shape
        embed = resident["embed_tokens"]
        x = embed.astype(cfg.dtype)[input_ids] + \
            resident["embed_positions"].astype(cfg.dtype)[
                None, cfg.POSITION_OFFSET:cfg.POSITION_OFFSET + T]
        stochastic = rng is not None and not deterministic and cfg.dropout > 0
        if stochastic:
            x = nn.Dropout(cfg.dropout).apply(
                {}, x, deterministic=False,
                rngs={"dropout": jax.random.fold_in(rng, -1)})
        block = OPTBlock(cfg)

        def body(carry, i):
            bp = fetch(i)
            rngs = {"dropout": jax.random.fold_in(rng, i)} if stochastic else None
            return block.apply({"params": bp}, carry, deterministic,
                               rngs=rngs), None

        body = jax.checkpoint(body, prevent_cse=False)
        x, _ = jax.lax.scan(body, x, jnp.arange(cfg.num_hidden_layers))
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype).apply(
            {"params": resident["final_layer_norm"]}, x)
        if labels is None:
            return x @ embed.astype(cfg.dtype).T
        from deepspeed_tpu.models.losses import lm_head_next_token_loss
        return lm_head_next_token_loss(x, embed, labels)

    def param_specs(self, params):
        """Megatron column/row TP pattern over q/k/v/fc1 (column) and
        out_proj/fc2 (row)."""
        cfg = self.config

        def spec_for(path, leaf):
            names = [str(getattr(p, "key", getattr(p, "name", ""))) for p in path]
            joined = "/".join(names)
            scan_prefix = (None,) if (cfg.scan_layers and "layers" in names) else ()
            col = any(n in joined for n in ("q_proj", "k_proj", "v_proj", "fc1"))
            row = any(n in joined for n in ("out_proj", "fc2"))
            if leaf.ndim == 1 + len(scan_prefix):
                if col:
                    return P(*scan_prefix, "tp")
                return P(*scan_prefix) if scan_prefix else None
            if "embed_tokens" in joined:
                return P("tp", None)
            if col:
                return P(*scan_prefix, None, "tp")
            if row:
                return P(*scan_prefix, "tp", None)
            return None

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        specs = [spec_for(path, leaf) for path, leaf in flat]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), specs)
