"""Compression config (reference ``deepspeed/compression/config.py`` +
``constants.py`` key names).

The reference nests each technique under ``compression_training`` with
``shared_parameters`` and per-module-pattern ``different_groups``. The same
shape is accepted here; ``modules`` patterns are matched against parameter
tree paths (``jax.tree_util.keystr``) instead of nn.Module names.
"""

TECHNIQUES = ("weight_quantization", "activation_quantization",
              "sparse_pruning", "row_pruning", "head_pruning",
              "channel_pruning")


class TechniqueGroup:

    def __init__(self, name, params, modules):
        self.name = name
        self.params = dict(params)
        self.modules = list(modules) if modules else ["*"]

    def matches(self, key):
        return any(m == "*" or m in key for m in self.modules)


class TechniqueConfig:

    def __init__(self, name, section):
        self.name = name
        shared = dict(section.get("shared_parameters", {}))
        self.enabled = bool(shared.get("enabled", False))
        self.schedule_offset = int(shared.get("schedule_offset", 0))
        self.frequency = int(shared.get("frequency", 1) or 1)
        self.shared = shared
        self.groups = []
        for gname, g in section.get("different_groups", {}).items():
            self.groups.append(TechniqueGroup(
                gname, g.get("params", {}), g.get("modules", ["*"])))
        if self.enabled and not self.groups:
            self.groups.append(TechniqueGroup("default", {}, ["*"]))

    def group_for(self, key):
        for g in self.groups:
            if g.matches(key):
                return g
        return None


class CompressionConfig:

    def __init__(self, param_dict):
        section = (param_dict or {}).get("compression_training", {})
        self.techniques = {t: TechniqueConfig(t, section.get(t, {}))
                           for t in TECHNIQUES}
        lr = section.get("layer_reduction", {})
        self.layer_reduction_enabled = bool(lr.get("enabled", False))
        self.layer_reduction = lr

    @property
    def any_enabled(self):
        return self.layer_reduction_enabled or \
            any(t.enabled for t in self.techniques.values())
