"""Compression — QAT quantization + structured/unstructured pruning.

Reference ``deepspeed/compression/``: ``init_compression`` (:239
``compress.py``) replaces matched layers with compressed variants
(``LinearLayer_Compress``, ``basic_layer.py:840L``) whose forward fake-
quantizes weights/activations and applies pruning masks; a scheduler
(``scheduler.py:173L``) enables each technique at its ``schedule_offset``;
``redundancy_clean`` materializes the pruned model.

TPU-native design: compression is a *pure parameter transform* applied
inside the jitted micro-step, not a module surgery. ``init_compression``
inspects the config + parameter tree once and returns a ``CompressionState``
whose ``transform(params, step)`` fake-quantizes and masks matched leaves —
XLA fuses the transform into the forward, exactly where the reference's
compressed-layer forward does it eagerly. The engine applies it via its
``param_transform`` hook. Masks for structured pruning are computed from
weight magnitude at the technique's ``schedule_offset`` boundary (dense
warmup, like the reference) and can be refreshed with ``update_masks``.
"""

import fnmatch

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.compression.config import CompressionConfig
from deepspeed_tpu.ops.quantizer import dequantize_lastdim, quantize_lastdim
from deepspeed_tpu.utils.logging import log_dist


def _fake_quant(x, bits, group_size=256):
    """Symmetric groupwise fake quantization (QAT forward; reference
    ``basic_layer.py`` weight quantization with STE — the straight-through
    gradient falls out of dequant(quant(x)) being piecewise identity-shaped)."""
    if x.ndim < 2:
        return x  # biases/scalars stay full precision (reference behavior)
    if bits >= 16:
        return x
    if bits in (8,):
        q, s = quantize_lastdim(x, group_size=group_size)
        return dequantize_lastdim(q, s, group_size=group_size, dtype=x.dtype)
    # generic low-bit (4/2/1): per-row amax scaling
    qmax = float(2 ** (bits - 1) - 1) if bits > 1 else 1.0
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
    return (q * scale).astype(x.dtype)


def _sparse_mask(w, ratio, structured=None):
    """Magnitude mask keeping the top (1-ratio) fraction.

    structured=None: elementwise (sparse_pruning, method "l1"/"topk").
    structured="row": whole output rows (row_pruning) — score rows by L1.
    structured="head": groups of rows (head_pruning) — needs num_heads.
    structured="channel": input columns (channel_pruning).
    """
    if structured is None:
        flat = jnp.abs(w).reshape(-1)
        k = max(1, int(flat.shape[0] * (1.0 - ratio)))
        thresh = jnp.sort(flat)[-k]
        return (jnp.abs(w) >= thresh).astype(w.dtype)
    if structured == "row":
        score = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
        k = max(1, int(score.shape[0] * (1.0 - ratio)))
        thresh = jnp.sort(score)[-k]
        mask = (score >= thresh).astype(w.dtype)
        return jnp.broadcast_to(mask, w.shape)
    if structured == "channel":
        score = jnp.sum(jnp.abs(w), axis=tuple(range(1, w.ndim)))
        k = max(1, int(score.shape[0] * (1.0 - ratio)))
        thresh = jnp.sort(score)[-k]
        mask = (score >= thresh).astype(w.dtype)
        return jnp.broadcast_to(mask.reshape((-1,) + (1,) * (w.ndim - 1)), w.shape)
    raise ValueError(f"unknown structure {structured}")


def _head_mask(w, ratio, num_heads):
    """head_pruning: the last dim is [heads * head_dim] (attention output
    projection input, reference ``head_pruning`` on attn output matrices)."""
    d = w.shape[-1]
    assert d % num_heads == 0, f"dim {d} not divisible by heads {num_heads}"
    hd = d // num_heads
    grouped = w.reshape(w.shape[:-1] + (num_heads, hd))
    head_axis = grouped.ndim - 2
    score = jnp.sum(jnp.abs(grouped),
                    axis=tuple(i for i in range(grouped.ndim) if i != head_axis))
    k = max(1, int(num_heads * (1.0 - ratio)))
    thresh = jnp.sort(score)[-k]
    mask = (score >= thresh).astype(w.dtype)  # [heads]
    mask = jnp.broadcast_to(mask[:, None], (num_heads, hd)).reshape(d)
    return jnp.broadcast_to(mask, w.shape)


class CompressionState:
    """Per-leaf technique plan + frozen pruning masks."""

    def __init__(self, config, params):
        aq = config.techniques.get("activation_quantization")
        if aq is not None and aq.enabled:
            raise ValueError(
                "compression: activation_quantization.enabled is set, but "
                "activation quantization is not implemented in deepspeed_tpu "
                "— refusing to silently ignore it. Remove the section (or "
                "set enabled: false) until an implementation lands.")
        self.config = config
        self.plans = {}   # keystr -> list of (technique, params dict)
        self.masks = {}   # keystr -> mask array (pruning techniques)
        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        for path, leaf in leaves:
            key = jax.tree_util.keystr(path)
            plan = []
            for tname, tcfg in config.techniques.items():
                if not tcfg.enabled:
                    continue
                group = tcfg.group_for(key)
                if group is None or (not hasattr(leaf, "ndim")) or leaf.ndim < 2:
                    continue
                plan.append((tname, dict(group.params),
                             tcfg.schedule_offset))
            if plan:
                self.plans[key] = plan
        n = sum(len(p) for p in self.plans.values())
        log_dist(f"compression: {n} technique applications over "
                 f"{len(self.plans)} leaves", ranks=[0])

    def update_masks(self, params):
        """(Re)compute pruning masks from current magnitudes (called at each
        technique's schedule_offset; reference scheduler boundary)."""
        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        for path, leaf in leaves:
            key = jax.tree_util.keystr(path)
            for tname, p, _ in self.plans.get(key, []):
                mkey = f"{key}::{tname}"
                if tname == "sparse_pruning":
                    self.masks[mkey] = _sparse_mask(
                        jnp.asarray(leaf), p.get("dense_ratio", 0.5))
                elif tname == "row_pruning":
                    self.masks[mkey] = _sparse_mask(
                        jnp.asarray(leaf), p.get("dense_ratio", 0.5), "row")
                elif tname == "channel_pruning":
                    self.masks[mkey] = _sparse_mask(
                        jnp.asarray(leaf), p.get("dense_ratio", 0.5), "channel")
                elif tname == "head_pruning":
                    self.masks[mkey] = _head_mask(
                        jnp.asarray(leaf), p.get("dense_ratio", 0.5),
                        int(p.get("num_heads", 1)))

    def transform(self, params, step):
        """Pure transform applied inside the jitted step. ``step`` may be a
        traced scalar; technique activation uses jnp.where so the program
        stays static."""
        def tx(path, leaf):
            key = jax.tree_util.keystr(path)
            plan = self.plans.get(key)
            if not plan:
                return leaf
            out = leaf
            for tname, p, offset in plan:
                if tname == "weight_quantization":
                    bits = int(p.get("target_bits", p.get("start_bits", 8)))
                    # STE: forward sees quantized values, gradients flow as if
                    # identity (reference QAT straight-through estimator)
                    qd = out + jax.lax.stop_gradient(_fake_quant(out, bits) - out)
                    out = jnp.where(step >= offset, qd, out)
                else:
                    mask = self.masks.get(f"{key}::{tname}")
                    if mask is not None:
                        out = jnp.where(step >= offset, out * mask, out)
            return out

        return jax.tree_util.tree_map_with_path(tx, params)

    def sparsity_report(self, params):
        rows = {}
        p = self.transform(params, step=jnp.int32(10**9))
        for path, leaf in jax.tree_util.tree_flatten_with_path(p)[0]:
            key = jax.tree_util.keystr(path)
            if key in self.plans:
                arr = np.asarray(jax.device_get(leaf))
                rows[key] = float((arr == 0).mean())
        return rows


def init_compression(params, ds_config):
    """Build the compression plan (reference ``init_compression``,
    ``compress.py:239``). ``ds_config`` is the raw dict (or DeepSpeedConfig
    ``_param_dict``)."""
    pd = ds_config._param_dict if hasattr(ds_config, "_param_dict") else ds_config
    cfg = CompressionConfig(pd)
    state = CompressionState(cfg, params)
    state.update_masks(params)
    return state


def apply_compression(engine, ds_config=None):
    """Attach compression to a live engine: the transform runs inside the
    jitted micro/eval steps via the engine's param_transform hook."""
    state = init_compression(
        engine.state.master if engine.state.master is not None
        else engine.state.params,
        ds_config or engine.config)
    engine.set_param_transform(
        lambda p, step: state.transform(p, step))
    return state


def redundancy_clean(params, state):
    """Materialize pruning into the stored weights (reference
    ``redundancy_clean``). Shapes are preserved (XLA needs static shapes);
    pruned entries become exact zeros so sparsity is checkpointed."""
    return state.transform(params, step=jnp.int32(10**9))


def layer_reduction(stacked_params, keep_layers):
    """Layer-reduction / depth distillation (reference ``layer_reduction``
    config): for scan-stacked layer trees (leading axis = layer), keep the
    given layer indices."""
    idx = jnp.asarray(keep_layers)

    def slice_leaf(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and \
                leaf.shape[0] > int(idx.max()):
            return jnp.take(leaf, idx, axis=0)
        return leaf

    return jax.tree.map(slice_leaf, stacked_params)
