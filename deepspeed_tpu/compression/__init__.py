from deepspeed_tpu.compression.compress import (CompressionState, apply_compression,
                                                init_compression, redundancy_clean)

__all__ = ["CompressionState", "apply_compression", "init_compression",
           "redundancy_clean"]
