"""Per-family injection policies (reference ``module_inject/policy.py``
``TransformerPolicy`` + the ``containers/`` tree: one class per HF family
declaring how to find qkv/dense/MLP/norm parameters and how to split them
for tensor parallelism).

The torch reference needs a class per family because it must *surgically
replace* ``nn.Module`` objects; on TPU the same knowledge is declarative —
a policy is a frozen record of the family's parameter roles, and GSPMD does
the splitting from the PartitionSpecs derived here. ``auto_tp.AutoTP``
consults this registry FIRST (exact per-family knowledge) and only falls
back to the global name heuristics (``infer_tp_specs``) for unknown
architectures — the same precedence the reference gives replace policies
over its graph-walk AutoTP (``replace_module.py``).

Coverage mirrors the reference's containers: llama/llama2 (+ mistral, qwen2,
internlm — same tree), qwen v1, gpt2, opt, bloom, falcon (gptneox-style
fused qkv), phi, gptj, gpt_neox, mixtral, bert (+ roberta, distilbert),
megatron-GPT (via the gpt2 policy — same tree after ``initialize(mpu=...)``
interop), and the diffusers unet/vae containers map to
``models/diffusion.py`` (spatial blocks carry no TP policy — the reference
serves them replicated too).
"""

import dataclasses
import re
from typing import Callable, Optional, Tuple

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.module_inject.auto_tp import _SCAN_RE


@dataclasses.dataclass(frozen=True)
class TransformerPolicy:
    """Declarative analog of reference ``TransformerPolicy`` subclasses.

    Name fragments are matched against '/'-joined param paths. ``fused_qkv``
    names a column-parallel leaf holding q|k|v stacked on the OUTPUT dim
    (reference ``fusedqkv_utils.py`` handles the interleavings; our model
    trees keep fused qkv only in gpt2/falcon/neox layouts).
    """
    family: str                       # model_type(s), comma-joined
    orig_layer_class: str             # reference container's torch class name
    column_parallel: Tuple[str, ...]  # output-dim split, no inbound collective
    row_parallel: Tuple[str, ...]     # input-dim split, psum on the way out
    vocab_parallel: Tuple[str, ...] = ("embed_tokens", "wte", "lm_head",
                                       "word_embeddings", "embed_in",
                                       "embed_out")
    fused_qkv: Optional[str] = None
    mlp_act: str = "gelu"             # reference ActivationFuncType analog
    norm_type: str = "layernorm"      # reference NormType analog
    pre_attn_norm: bool = True
    config_cls: str = ""              # our flax config class name
    # column biases normally split with the output dim; families whose
    # fused-qkv output layout is HEAD-INTERLEAVED (bloom/falcon/neox) keep
    # biases replicated — an interleaved split would scatter head fragments
    split_column_bias: bool = True
    # expert-stacked [E, ...] leaves whose leading dim shards over "ep"
    expert_parallel: Tuple[str, ...] = ()
    # disambiguator when several policies share config_cls (falcon vs phi
    # both use ParallelBlockConfig): first registered policy whose predicate
    # accepts the config wins — deterministic, unlike set iteration
    config_predicate: Optional[Callable] = None


_REGISTRY = {}
_ORDERED = []    # registration order: deterministic config-object lookup


def register_policy(policy):
    for fam in policy.family.split(","):
        _REGISTRY[fam.strip()] = policy
    _ORDERED.append(policy)
    return policy


register_policy(TransformerPolicy(
    family="llama,llama2,mistral,qwen2,internlm",
    orig_layer_class="LlamaDecoderLayer",
    column_parallel=("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"),
    row_parallel=("o_proj", "down_proj"),
    mlp_act="silu-glu", norm_type="rmsnorm", config_cls="LlamaConfig"))

register_policy(TransformerPolicy(
    family="qwen",                    # v1: same flax tree as llama (hf.py
    orig_layer_class="QWenBlock",     # maps c_attn/w1/w2 onto it)
    column_parallel=("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"),
    row_parallel=("o_proj", "down_proj"),
    mlp_act="silu-glu", norm_type="rmsnorm", config_cls="LlamaConfig"))

register_policy(TransformerPolicy(
    family="gpt2,megatron-gpt",
    orig_layer_class="GPT2Block",
    column_parallel=("c_fc",), row_parallel=("c_proj", "mlp/c_proj"),
    fused_qkv="c_attn", mlp_act="gelu-new", config_cls="GPT2Config"))

register_policy(TransformerPolicy(
    family="opt",
    orig_layer_class="OPTDecoderLayer",
    column_parallel=("q_proj", "k_proj", "v_proj", "fc1"),
    row_parallel=("out_proj", "fc2"),
    mlp_act="relu", config_cls="OPTConfig"))

register_policy(TransformerPolicy(
    family="bloom",
    orig_layer_class="BloomBlock",
    column_parallel=("dense_h_to_4h",),
    row_parallel=("dense_4h_to_h", "dense"),
    fused_qkv="query_key_value", config_cls="BloomConfig",
    split_column_bias=False))

register_policy(TransformerPolicy(
    family="falcon,gpt_neox",
    orig_layer_class="FalconDecoderLayer",
    column_parallel=("fc1",), row_parallel=("dense", "fc2"),
    fused_qkv="query_key_value", config_cls="ParallelBlockConfig",
    split_column_bias=False,
    config_predicate=lambda c: bool(getattr(c, "fused_qkv", True))))

register_policy(TransformerPolicy(
    family="phi,gptj",
    orig_layer_class="PhiDecoderLayer",
    column_parallel=("q_proj", "k_proj", "v_proj", "fc1"),
    row_parallel=("fc2", "dense"),
    config_cls="ParallelBlockConfig", split_column_bias=False,
    config_predicate=lambda c: not getattr(c, "fused_qkv", True)))

register_policy(TransformerPolicy(
    family="mixtral",
    orig_layer_class="MixtralDecoderLayer",
    column_parallel=("q_proj", "k_proj", "v_proj", "w1", "w3"),
    row_parallel=("o_proj", "w2"),
    mlp_act="silu-glu", norm_type="rmsnorm", config_cls="MixtralConfig",
    expert_parallel=("w1", "w2", "w3")))

register_policy(TransformerPolicy(
    family="bert,roberta,distilbert",
    orig_layer_class="BertLayer",
    column_parallel=("query", "key", "value", "intermediate"),
    row_parallel=("attn_out", "output"),
    pre_attn_norm=False, config_cls="BertConfig",
    split_column_bias=False))


def policy_for(model_type_or_config):
    """Look up the policy by HF model_type string or by our config object.

    Config-object lookup walks policies in REGISTRATION order and applies
    each policy's ``config_predicate`` (when set) so families sharing a
    config class (falcon vs phi on ParallelBlockConfig) resolve
    deterministically by config content, never by hash order."""
    if isinstance(model_type_or_config, str):
        return _REGISTRY.get(model_type_or_config)
    cfg = model_type_or_config
    name = type(cfg).__name__
    for pol in _ORDERED:
        if pol.config_cls != name:
            continue
        if pol.config_predicate is None or pol.config_predicate(cfg):
            return pol
    return None


def registered_families():
    return sorted(_REGISTRY)


def tp_specs_from_policy(policy, params, axis="tp"):
    """PartitionSpec pytree from a family policy — the declarative form of
    the reference container's ``attention()``/``mlp()`` split methods."""
    def kind_of(name):
        for frag in policy.vocab_parallel:
            if re.search(frag + r"\b", name):
                return "vocab"
        for frag in policy.row_parallel:
            if re.search(frag + r"\b", name):
                return "row"
        cols = policy.column_parallel + \
            ((policy.fused_qkv,) if policy.fused_qkv else ())
        for frag in cols:
            if re.search(frag + r"\b", name):
                return "column"
        return None

    def spec_for(path, leaf):
        name = "/".join(str(getattr(p, "key", getattr(p, "name", "")))
                        for p in path)
        kind = kind_of(name)
        if kind is None:
            return None
        expert = leaf.ndim == 3 and any(
            re.search(frag + r"\b", name) for frag in policy.expert_parallel)
        stacked = expert or bool(_SCAN_RE.search(name)) or \
            (leaf.ndim == 3 and kind in ("column", "row"))
        lead = ("ep",) if expert else ((None,) if stacked else ())
        base_ndim = leaf.ndim - (1 if stacked else 0)
        if base_ndim == 1 and kind == "column":
            # column-parallel BIAS: output dim is split, so the bias splits
            # with it (a row-parallel bias stays replicated — it is added
            # once after the psum); head-interleaved fused layouts opt out
            if not policy.split_column_bias:
                return None
            return P(*(lead + (axis,)))
        if base_ndim != 2:
            return None
        spec = {"vocab": (axis, None), "row": (axis, None),
                "column": (None, axis)}[kind]
        return P(*(lead + spec))

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs = [spec_for(p, l) for p, l in flat]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), specs)
