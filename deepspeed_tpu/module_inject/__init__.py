"""Automatic tensor-parallel policy inference (reference ``module_inject``).

The reference rewrites torch modules in place (``replace_module.py:182``) and
its AutoTP walks module graphs to decide which Linears split column- vs
row-wise (``auto_tp.py``). The TPU analog needs no module surgery — a TP
"policy" here is a PartitionSpec pytree consumed by the engine's partitioner —
so this package provides the same capability as pure functions:

- :func:`infer_tp_specs`: name-heuristic column/row/vocab classification for
  ANY flax param tree (models without a hand-written ``param_specs``);
- in-tree models still ship exact ``param_specs`` methods; this is the
  generic fallback the reference's AutoTP plays for unseen architectures.
"""

from deepspeed_tpu.module_inject.auto_tp import AutoTP, infer_tp_specs  # noqa: F401
from deepspeed_tpu.module_inject.replace_policy import (  # noqa: F401
    TransformerPolicy, policy_for, registered_families, tp_specs_from_policy)


def replace_transformer_layer(orig_layer_impl, model, checkpoint_dict=None,
                              config=None, model_config=None):
    """Reference ``module_inject.replace_transformer_layer``: swap torch
    layers for fused-kernel containers. On TPU kernel injection is ALWAYS on
    — every in-tree model routes attention through the ops registry, which
    selects the Pallas kernels on TPU hardware — so this is the identity,
    kept for API parity with reference call sites."""
    from deepspeed_tpu.utils.logging import logger
    logger.info("replace_transformer_layer: TPU kernel injection is always "
                "on (ops registry); returning the model unchanged")
    return model


def revert_transformer_layer(orig_layer_impl, model, config=None,
                             preln=False):
    """Inverse of :func:`replace_transformer_layer` — identity here too."""
    return model
