"""AutoTP — infer Megatron-style TP PartitionSpecs for any flax param tree.

Reference ``module_inject/auto_tp.py:491`` walks the torch module graph and
classifies Linears as all-reduce (row) or split (column) layers by tracing
which ones feed residual sums. Weight NAMES carry the same signal in every
transformer implementation, so the TPU version classifies by name:

- column-parallel (output dim split, no collective on the way in):
  q/k/v/gate/up projections, fused qkv, first MLP matmuls;
- row-parallel (input dim split, psum on the way out — the reference's
  LinearAllreduce): attention output and second MLP matmuls;
- vocab-split: embeddings and lm heads;
- everything else (norms, biases, scalars): replicated.

The column/row pairing keeps each transformer block collective-count
identical to Megatron: one psum after attention, one after the MLP.
"""

import re

import jax
from jax.sharding import PartitionSpec as P

# ordered: the ROW patterns must win over generic matches
ROW_PATTERNS = re.compile(
    r"(o_proj|out_proj|down_proj|dense_4h_to_h|dense/kernel"
    r"|fc2|fc_out|c_proj|wo|attn_out)\b")
COLUMN_PATTERNS = re.compile(
    r"(q_proj|k_proj|v_proj|query_key_value|c_attn|qkv"
    r"|gate_proj|up_proj|dense_h_to_4h|fc1|fc_in|c_fc|wi"
    r"|query|key|value|intermediate)\b")
# parent-qualified column matches that must beat the generic ROW
# "dense/kernel" rule (HF-flax BERT: intermediate/dense is the up-projection)
COLUMN_FIRST_PATTERNS = re.compile(r"intermediate/dense\b")
VOCAB_PATTERNS = re.compile(
    r"(embed_tokens|word_embeddings$|wte|embed_in|lm_head|embed_out|shared)\b")


def _classify(name):
    if VOCAB_PATTERNS.search(name):
        return "vocab"
    if COLUMN_FIRST_PATTERNS.search(name):
        return "column"
    if ROW_PATTERNS.search(name):
        return "row"
    if COLUMN_PATTERNS.search(name):
        return "column"
    return None


# scan-stacked containers: "layers/block" (in-tree lax.scan trees), but NOT
# "layers/0" — HF-Flax nests per-layer dicts under digit keys
_SCAN_RE = re.compile(r"(layers/(?!\d)|h/block|/block/)")


def infer_tp_specs(params, axis="tp"):
    """PartitionSpec pytree for ``params`` by weight-name heuristics.

    Scanned ([L, ...]-stacked) leaves — recognized by scan-container path
    fragments or structurally (a 3D classified kernel is a stacked 2D one) —
    get a leading None axis. 1D leaves (biases, norm scales) and
    unrecognized kernels stay replicated (None spec).
    """
    def spec_for(path, leaf):
        name = "/".join(str(getattr(p, "key", getattr(p, "name", "")))
                        for p in path)
        stacked = bool(_SCAN_RE.search(name)) or \
            (leaf.ndim == 3 and _classify(name) in ("column", "row"))
        base_ndim = leaf.ndim - (1 if stacked else 0)
        kind = _classify(name)
        if kind == "vocab":
            if base_ndim != 2:
                return None
            spec = (axis, None)
        elif base_ndim != 2:
            return None
        elif kind == "column":
            spec = (None, axis)
        elif kind == "row":
            spec = (axis, None)
        else:
            return None
        return P(*(((None,) if stacked else ()) + spec))

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs = [spec_for(p, l) for p, l in flat]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), specs)


class AutoTP:
    """reference ``AutoTP`` surface: policy discovery for a model/params."""

    @staticmethod
    def get_policy(model, params):
        """Precedence mirrors the reference (replace policies outrank the
        graph-walk AutoTP, ``replace_module.py``):
        1. the model's exact ``param_specs`` (ground truth for in-tree models)
        2. a registered per-family injection policy
           (``replace_policy.policy_for`` by config class)
        3. global name heuristics (``infer_tp_specs``)."""
        if hasattr(model, "param_specs"):
            return model.param_specs(params)
        from deepspeed_tpu.module_inject.replace_policy import (
            policy_for, tp_specs_from_policy)
        cfg = getattr(model, "config", model)
        pol = policy_for(cfg) if cfg is not None else None
        if pol is not None:
            return tp_specs_from_policy(pol, params)
        return infer_tp_specs(params)
