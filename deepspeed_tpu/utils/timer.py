"""Wall-clock + throughput timers.

Mirrors reference ``deepspeed/utils/timer.py``: ``SynchronizedWallClockTimer``
(:43) keyed by name with start/stop/elapsed/mean, and ``ThroughputTimer`` (:198)
reporting samples/sec and TFLOPS. TPU twist: there are no CUDA events; JAX
dispatch is async, so "synchronized" timing calls ``block_until_ready`` on a
token array when one is supplied, else falls back to host perf_counter.
"""

import time

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


def _sync(token=None):
    if token is not None:
        try:
            import jax
            jax.block_until_ready(token)
            return
        except Exception:
            pass


class _Timer:

    def __init__(self, name):
        self.name_ = name
        self.started_ = False
        self.elapsed_ = 0.0
        self.records = []
        self.start_time = 0.0

    def start(self):
        assert not self.started_, f"{self.name_} timer has already been started"
        self.start_time = time.perf_counter()
        self.started_ = True

    def stop(self, record=True, token=None):
        assert self.started_, f"{self.name_} timer is not started"
        _sync(token)
        dt = time.perf_counter() - self.start_time
        self.elapsed_ += dt
        if record:
            self.records.append(dt)
        self.started_ = False

    def reset(self):
        self.started_ = False
        self.elapsed_ = 0.0

    def elapsed(self, reset=True):
        started = self.started_
        if started:
            self.stop(record=False)
        e = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return e

    def mean(self):
        return (sum(self.records) / len(self.records)) if self.records else 0.0


class SynchronizedWallClockTimer:
    """Named timer group (reference ``utils/timer.py:43``)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has_timer(self, name):
        return name in self.timers

    @staticmethod
    def memory_usage():
        try:
            from deepspeed_tpu.accelerator import get_accelerator
            stats = get_accelerator().memory_stats()
            gb = 1024**3
            return (f"MemAllocated={stats.get('bytes_in_use', 0) / gb:.2f} GB "
                    f"MaxMemAllocated={stats.get('peak_bytes_in_use', 0) / gb:.2f} GB")
        except Exception:
            return "MemAllocated=? MaxMemAllocated=?"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        from deepspeed_tpu.utils.logging import log_dist
        log_dist(f"time (ms) | {' | '.join(parts)}", ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                means[name] = self.timers[name].mean() * 1000.0 / normalizer
                if reset:
                    self.timers[name].records = []
        return means


class ThroughputTimer:
    """Samples/sec + TFLOPS reporting (reference ``utils/timer.py:198``)."""

    def __init__(self, batch_size, start_step=2, steps_per_output=50, monitor_memory=False, logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: None)
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            self.start_time = time.perf_counter()

    def stop(self, global_step=False, report_speed=True, token=None):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _sync(token)
            self.end_time = time.perf_counter()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step and report_speed and self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, RunningAvgSamplesPerSec="
                    f"{self.avg_samples_per_sec():.2f}, CurrSamplesPerSec="
                    f"{self.batch_size / self.step_elapsed_time:.2f}")
                self.step_elapsed_time = 0
            elif global_step:
                self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("-inf")
