"""Wall-clock + throughput timers.

Mirrors reference ``deepspeed/utils/timer.py``: ``SynchronizedWallClockTimer``
(:43) keyed by name with start/stop/elapsed/mean, and ``ThroughputTimer`` (:198)
reporting samples/sec and TFLOPS. TPU twist: there are no CUDA events; JAX
dispatch is async, so "synchronized" timing calls ``block_until_ready`` on a
token array when one is supplied, else falls back to host perf_counter.
"""

import time

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


def _sync(token=None):
    if token is not None:
        try:
            import jax
            jax.block_until_ready(token)
            return
        except Exception:
            pass


class _Timer:

    def __init__(self, name, clock=time.perf_counter):
        self.name_ = name
        self.clock = clock
        self.started_ = False
        self.elapsed_ = 0.0
        self.records = []
        self.start_time = 0.0

    def start(self):
        assert not self.started_, f"{self.name_} timer has already been started"
        self.start_time = self.clock()
        self.started_ = True

    def stop(self, record=True, token=None):
        assert self.started_, f"{self.name_} timer is not started"
        _sync(token)
        dt = self.clock() - self.start_time
        self.elapsed_ += dt
        if record:
            self.records.append(dt)
        self.started_ = False

    def reset(self):
        self.started_ = False
        self.elapsed_ = 0.0

    def elapsed(self, reset=True):
        """Cumulative elapsed seconds, including the in-flight interval of a
        running timer. Reading while running must NOT stop/restart the timer:
        the old stop(record=False)/reset()/start() dance dropped the running
        interval from a later ``stop(record=True)``'s record (corrupting
        ``mean()``) and rewrote ``start_time``. Now a running timer is only
        observed; ``reset=True`` zeroes the banked total and rebases the
        in-flight interval at "now" without touching ``started_``/records."""
        now = self.clock()
        e = self.elapsed_
        if self.started_:
            e += now - self.start_time
        if reset:
            self.elapsed_ = 0.0
            if self.started_:
                self.start_time = now
        return e

    def mean(self):
        return (sum(self.records) / len(self.records)) if self.records else 0.0


class SynchronizedWallClockTimer:
    """Named timer group (reference ``utils/timer.py:43``)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has_timer(self, name):
        return name in self.timers

    @staticmethod
    def memory_usage():
        try:
            from deepspeed_tpu.accelerator import get_accelerator
            stats = get_accelerator().memory_stats()
            gb = 1024**3
            return (f"MemAllocated={stats.get('bytes_in_use', 0) / gb:.2f} GB "
                    f"MaxMemAllocated={stats.get('peak_bytes_in_use', 0) / gb:.2f} GB")
        except Exception:
            return "MemAllocated=? MaxMemAllocated=?"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}")
        from deepspeed_tpu.utils.logging import log_dist
        log_dist(f"time (ms) | {' | '.join(parts)}", ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                means[name] = self.timers[name].mean() * 1000.0 / normalizer
                if reset:
                    self.timers[name].records = []
        return means


class ThroughputTimer:
    """Samples/sec + TFLOPS reporting (reference ``utils/timer.py:198``).

    ``clock`` is injectable for deterministic tests; ``flops_per_sample``
    (model FLOPs for ONE sample, e.g. from the flops profiler) enables the
    achieved-TFLOPS readout."""

    def __init__(self, batch_size, start_step=2, steps_per_output=50,
                 monitor_memory=False, logging_fn=None,
                 clock=time.perf_counter, flops_per_sample=0):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.clock = clock
        self.flops_per_sample = flops_per_sample
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda msg: None)
        self.initialized = False

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        self._init_timer()
        self.started = True
        if self.global_step_count >= self.start_step:
            self.start_time = self.clock()

    def stop(self, global_step=False, report_speed=True, token=None):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            _sync(token)
            self.end_time = self.clock()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if global_step and report_speed and self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, RunningAvgSamplesPerSec="
                    f"{self.avg_samples_per_sec():.2f}, CurrSamplesPerSec="
                    f"{self.batch_size / self.step_elapsed_time:.2f}")
                self.step_elapsed_time = 0
            elif global_step:
                self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("-inf")

    def avg_tflops(self):
        """Achieved TFLOPS from the running samples/sec average; 0.0 until
        ``flops_per_sample`` is set and warmup (start_step) has passed."""
        sps = self.avg_samples_per_sec()
        if self.flops_per_sample <= 0 or sps <= 0:
            return 0.0
        return sps * self.flops_per_sample / 1e12
