"""Shared accelerator-backend probe.

``jax.devices()`` HANGS (not raises) when the chip is held by another
process — any in-process probe can wedge the caller. This helper takes the
hang in a CHILD process with a deadline and reports what actually happened.
Used by bench.py and ds_tpu_report; keep it the only copy.
"""

import subprocess
import sys


import os


# the child's probe body — module-level so tests can substitute a fake.
# The probe must test the platform the PARENT will actually use. The axon
# sitecustomize ignores JAX_PLATFORMS from the environment, so the only real
# signal is the parent's IN-PYTHON pin (jax.config.jax_platforms), mirrored
# into the child via DS_PROBE_PLATFORMS — a child that honored the env var
# while the parent ran on the default platform would report "ok" for a
# backend the caller never touches.
PROBE_CODE = (
    "import os, jax\n"
    "p = os.environ.get('DS_PROBE_PLATFORMS', '')\n"
    "if p:\n"
    "    jax.config.update('jax_platforms', p)\n"
    "print(len(jax.devices()))")


def probe_backend(timeout_s=None, _code=None):
    """-> (kind, detail) where kind is "ok" | "hang" | "error".

    "hang": the child never returned within the deadline — consistent with
    (but not proof of) the accelerator being held by another process, or a
    genuinely slow cold init; raise the timeout to distinguish.
    "error": the child exited nonzero; detail carries its stderr tail
    (e.g. a libtpu/jaxlib mismatch — NOT a held chip)."""
    if timeout_s is None:   # read at call time: callers set the env late
        timeout_s = float(os.environ.get("DS_BACKEND_PROBE_TIMEOUT", "90"))
    # manual Popen dance: subprocess.run's TimeoutExpired path kills the
    # child then WAITS for it — a child stuck in an uninterruptible tunnel
    # syscall never dies and the "bounded" probe blocks forever. Here the
    # final wait is itself bounded; an unkillable child gets ABANDONED.
    env = dict(os.environ)
    try:  # mirror the parent's effective in-Python platform pin (see above)
        if "jax" in sys.modules:
            import jax
            plats = getattr(jax.config, "jax_platforms", None)
            if plats:
                env["DS_PROBE_PLATFORMS"] = plats
    except Exception:
        pass
    proc = subprocess.Popen(
        [sys.executable, "-c", PROBE_CODE if _code is None else _code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=5)
        except subprocess.TimeoutExpired:
            pass  # unkillable (D-state): abandon it
        return "hang", (f"backend probe returned nothing within "
                        f"{timeout_s:.0f}s (accelerator held by another "
                        f"process, or a very slow init)")
    except BaseException:   # KeyboardInterrupt etc: never leak a live child
        proc.kill()
        raise
    if proc.returncode != 0:
        tail = (err or "").strip().splitlines()
        return "error", "probe failed: " + (tail[-1] if tail
                                            else f"rc={proc.returncode}")
    return "ok", (out or "").strip()
