"""Shared accelerator-backend probe.

``jax.devices()`` HANGS (not raises) when the chip is held by another
process — any in-process probe can wedge the caller. This helper takes the
hang in a CHILD process with a deadline and reports what actually happened.
Used by bench.py and ds_tpu_report; keep it the only copy.
"""

import subprocess
import sys


def probe_backend(timeout_s=30.0):
    """-> (ok, detail). ``ok`` False means hung (detail explains) or the
    child failed (detail carries its stderr tail, e.g. a libtpu mismatch —
    NOT necessarily a held chip)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, (f"probe hung >{timeout_s:.0f}s — accelerator held by "
                       f"another process")
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return False, "probe failed: " + (tail[-1] if tail
                                          else f"rc={r.returncode}")
    return True, (r.stdout or "").strip()
