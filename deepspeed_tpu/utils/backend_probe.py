"""Shared accelerator-backend probe.

``jax.devices()`` HANGS (not raises) when the chip is held by another
process — any in-process probe can wedge the caller. This helper takes the
hang in a CHILD process with a deadline and reports what actually happened.
Used by bench.py and ds_tpu_report; keep it the only copy.
"""

import subprocess
import sys


import os


def probe_backend(timeout_s=None):
    """-> (kind, detail) where kind is "ok" | "hang" | "error".

    "hang": the child never returned within the deadline — consistent with
    (but not proof of) the accelerator being held by another process, or a
    genuinely slow cold init; raise the timeout to distinguish.
    "error": the child exited nonzero; detail carries its stderr tail
    (e.g. a libtpu/jaxlib mismatch — NOT a held chip)."""
    if timeout_s is None:   # read at call time: callers set the env late
        timeout_s = float(os.environ.get("DS_BACKEND_PROBE_TIMEOUT", "90"))
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "hang", (f"backend probe returned nothing within "
                        f"{timeout_s:.0f}s (accelerator held by another "
                        f"process, or a very slow init)")
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return "error", "probe failed: " + (tail[-1] if tail
                                            else f"rc={r.returncode}")
    return "ok", (r.stdout or "").strip()
