"""Tensor fragment API — safe access to fp32 master weights, optimizer state
and gradients across ZeRO stages.

Reference ``utils/tensor_fragment.py:123`` (``safe_get_full_fp32_param``,
``safe_set_full_fp32_param``, ``safe_get_full_optimizer_state``,
``safe_get_full_grad``): under ZeRO the "real" fp32 value of a parameter is
scattered over the DP world, so user/debugging code needs gather/scatter
helpers. Here state lives in the engine's TrainState as GSPMD global arrays,
so "gather" is a device_get (XLA all-gathers) and "scatter" a device_put with
the original sharding; the host-offload tier is handled transparently.

Parameters are addressed by tree path string (``jax.tree_util.keystr``) — the
functional analog of the reference's param object, e.g.
``"['Dense_0']['kernel']"``.
"""

import numpy as np

import jax


def _find_leaf(tree, key):
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if jax.tree_util.keystr(path) == key:
            return leaf
    return None


def moment_leaves(opt_state, param_path_by_key):
    """Map optimizer-moment leaves to their parameters by *path components*.

    ``param_path_by_key``: {keystr: path-tuple} of the tree the optimizer was
    built over. A moment leaf matches parameter P iff its path ends with P's
    exact component sequence AND the component just before it is the optax
    field ``mu``/``nu`` — this disambiguates params whose paths are suffixes
    of other params' and is robust to dict-keyed (offload) master trees,
    unlike string suffix matching on keystr. Returns
    {"<key>::exp_avg"/"::exp_avg_sq": (path-tuple, leaf)}."""
    by_suffix = {}
    lengths = set()
    for pk, ppath in param_path_by_key.items():
        ppath = tuple(ppath)
        by_suffix[ppath] = pk
        lengths.add(len(ppath))
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(opt_state)[0]:
        path = tuple(path)
        for L in lengths:  # O(opt_leaves x distinct-depths), not x params
            if len(path) <= L:
                continue
            pk = by_suffix.get(path[-L:])
            if pk is None:
                continue
            field = getattr(path[-L - 1], "name", None)
            if field == "mu":
                out[f"{pk}::exp_avg"] = (path, leaf)
            elif field == "nu":
                out[f"{pk}::exp_avg_sq"] = (path, leaf)
    return out


def param_paths_by_key(tree):
    return {jax.tree_util.keystr(p): tuple(p)
            for p, _ in jax.tree_util.tree_flatten_with_path(tree)[0]}


def opt_param_paths(engine):
    """{canonical param key: path tuple inside the optimizer target tree}.
    In offload mode the optimizer target is the dict {key: leaf} of the
    device remainder, so the path is a single DictKey whose value IS the
    canonical key (keystr-of-path would double-quote it)."""
    if engine._offload is not None:
        from jax.tree_util import DictKey
        return {k: (DictKey(k),) for k in engine.state.master}
    tree = engine.state.master if engine.state.master is not None \
        else engine.state.params
    return param_paths_by_key(tree)


def _replace_leaf(tree, key, value):
    def rep(path, leaf):
        if jax.tree_util.keystr(path) == key:
            return jax.device_put(value.astype(leaf.dtype), leaf.sharding) \
                if hasattr(leaf, "sharding") else value
        return leaf

    return jax.tree_util.tree_map_with_path(rep, tree)


def param_names(engine):
    """All addressable parameter paths."""
    if engine._offload is not None:
        return list(engine._flat_keys)
    tree = engine.state.master if engine.state.master is not None else engine.state.params
    return [jax.tree_util.keystr(p)
            for p, _ in jax.tree_util.tree_flatten_with_path(tree)[0]]


def safe_get_full_fp32_param(engine, key):
    """Gathered fp32 master value of a parameter (reference :123)."""
    if engine._offload is not None:
        if key in engine._offload.masters:
            return engine._offload.masters[key].reshape(engine._offload.shapes[key]).copy()
        leaf = engine.state.master.get(key)
        return None if leaf is None else np.asarray(jax.device_get(leaf))
    tree = engine.state.master if engine.state.master is not None else engine.state.params
    leaf = _find_leaf(tree, key)
    return None if leaf is None else np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_set_full_fp32_param(engine, key, value):
    """Scatter a new fp32 master value (reference safe_set_full_fp32_param).
    The working copy is NOT updated until the next optimizer step, matching
    the reference's master/working split."""
    value = np.asarray(value, dtype=np.float32)
    if engine._offload is not None:
        if key in engine._offload.masters:
            engine._offload.masters[key][:] = value.reshape(-1)
            return True
        if key in engine.state.master:
            engine.state = engine.state._replace(
                master=_replace_leaf(engine.state.master, key, value))
            return True
        return False
    if engine.state.master is not None:
        engine.state = engine.state._replace(
            master=_replace_leaf(engine.state.master, key, value))
    else:
        engine.state = engine.state._replace(
            params=_replace_leaf(engine.state.params, key, value))
    return True


def safe_get_full_optimizer_state(engine, key, state_name):
    """Gathered optimizer-state fragment, ``state_name`` in
    {"exp_avg", "exp_avg_sq"} (reference safe_get_full_optimizer_state)."""
    field = {"exp_avg": "mu", "exp_avg_sq": "nu"}.get(state_name)
    if field is None:
        return None  # reference returns None for absent state names
    if engine._offload is not None and key in engine._offload.masters:
        n = engine._offload.masters[key].size
        if engine._offload.swapper is not None:
            m, v = engine._offload.swapper.fetch(key)
            out = (m if field == "mu" else v).reshape(engine._offload.shapes[key]).copy()
            engine._offload.swapper.commit(key)
            engine._offload.swapper.finish_step()
            return out
        m, v = engine._offload.adam.state_for(key, n)
        return (m if field == "mu" else v).reshape(engine._offload.shapes[key]).copy()
    frag_name = {"mu": "exp_avg", "nu": "exp_avg_sq"}[field]
    frags = moment_leaves(engine.state.opt_state, opt_param_paths(engine))
    hit = frags.get(f"{key}::{frag_name}")
    return None if hit is None else np.asarray(jax.device_get(hit[1]),
                                               dtype=np.float32)


def safe_get_full_grad(engine, key):
    """Gathered accumulated gradient (reference safe_get_full_grad). Nonzero
    between backward and the accumulation-boundary step."""
    leaf = _find_leaf(engine.state.grad_acc, key)
    return None if leaf is None else np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_set_full_optimizer_state(engine, key, value, state_name):
    """Scatter a new optimizer-state value (reference
    safe_set_full_optimizer_state); state_name in {"exp_avg", "exp_avg_sq"}."""
    import jax.numpy as jnp
    if state_name not in ("exp_avg", "exp_avg_sq"):
        return False
    value = np.asarray(value, dtype=np.float32)
    if engine._offload is not None and key in engine._offload.masters:
        n = engine._offload.masters[key].size
        idx = 0 if state_name == "exp_avg" else 1
        if engine._offload.swapper is not None:
            # NVMe tier owns the moments: fetch, modify, write back through
            # the swapper (a bare adam.state_for would be throwaway zeros)
            m, v = engine._offload.swapper.fetch(key)
            pair = [m, v]
            pair[idx] = value.reshape(-1)
            engine._offload.swapper.commit(key)
            engine._offload.swapper.finish_step()
            engine._offload.swapper.load_state_arrays({key: tuple(pair)})
            return True
        state = engine._offload.adam.state_for(key, n)
        if idx >= len(state):  # Lion/Adagrad host steps carry one moment
            return False
        state[idx][:] = value.reshape(-1)
        return True
    frags = moment_leaves(engine.state.opt_state, opt_param_paths(engine))
    hit = frags.get(f"{key}::{state_name}")
    if hit is None:
        return False
    path, leaf = hit
    new = jax.device_put(jnp.asarray(value, leaf.dtype), leaf.sharding)

    def rep(p, l):
        return new if tuple(p) == tuple(path) else l

    engine.state = engine.state._replace(
        opt_state=jax.tree_util.tree_map_with_path(rep, engine.state.opt_state))
    return True


def _local_shard(arr):
    """Process-local shard of a (possibly sharded) array (the reference's
    rank-local fragment view: under GSPMD the addressable shard IS the local
    partition)."""
    if arr is None:
        return None
    if hasattr(arr, "addressable_shards") and arr.addressable_shards:
        return np.asarray(arr.addressable_shards[0].data)
    return np.asarray(arr)


def safe_get_local_fp32_param(engine, key):
    """Rank-local shard of the fp32 master (reference
    safe_get_local_fp32_param)."""
    if engine._offload is not None:
        return safe_get_full_fp32_param(engine, key)  # host tier is local
    tree = engine.state.master if engine.state.master is not None \
        else engine.state.params
    leaf = _find_leaf(tree, key)
    return None if leaf is None else _local_shard(leaf).astype(np.float32)


def safe_get_local_grad(engine, key):
    """Rank-local shard of the accumulated gradient."""
    leaf = _find_leaf(engine.state.grad_acc, key)
    return None if leaf is None else _local_shard(leaf).astype(np.float32)


def safe_get_local_optimizer_state(engine, key, state_name):
    """Rank-local shard of an optimizer-state fragment."""
    if engine._offload is not None and key in engine._offload.masters:
        return safe_get_full_optimizer_state(engine, key, state_name)
    frags = moment_leaves(engine.state.opt_state, opt_param_paths(engine))
    hit = frags.get(f"{key}::{state_name}")
    return None if hit is None else _local_shard(hit[1]).astype(np.float32)
