

class OnDevice:
    """reference ``deepspeed.OnDevice`` (meta-device model construction).

    In torch this context routes tensor allocation to the meta device so
    huge models can be DESCRIBED without materializing weights. flax modules
    are already lazy — construction allocates nothing until ``init`` runs —
    and sharded materialization is ``deepspeed_tpu.zero.Init`` /
    ``runtime/zero/sharded_init.py``. Kept as a no-op context for scripts
    ported from the reference."""

    def __init__(self, dtype=None, device="meta", enabled=True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# tensor-fragment API re-exports (reference deepspeed/utils/__init__.py:14-18)
from deepspeed_tpu.utils.tensor_fragment import (  # noqa: E402,F401
    safe_get_full_fp32_param, safe_get_full_grad,
    safe_get_full_optimizer_state, safe_set_full_fp32_param,
    safe_set_full_optimizer_state, safe_get_local_fp32_param,
    safe_get_local_grad, safe_get_local_optimizer_state)
