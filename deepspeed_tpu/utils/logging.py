"""Rank-aware logging (mirrors reference ``deepspeed/utils/logging.py``)."""

import logging
import os
import sys

_LOGGER_NAME = "deepspeed_tpu"

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name=_LOGGER_NAME, level=logging.INFO):
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        lg.addHandler(handler)
    return lg


logger = _create_logger(level=log_levels.get(os.environ.get("DST_LOG_LEVEL", "info"), logging.INFO))


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log on selected process ranks only (reference ``utils/logging.py`` log_dist).

    ``ranks=None`` or ``[-1]`` logs everywhere; otherwise only the listed
    ``jax.process_index()`` values log.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message):
    if _process_index() == 0:
        logger.info(message)


def warning_once(message, _seen=set()):
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
