"""Comms logger with algorithmic/bus bandwidth calculation.

Mirrors reference ``deepspeed/utils/comms_logging.py``: per-op size/latency
records (:67) and ``calc_bw_log`` (:34) computing algbw and busbw with the
standard ring-collective correction factors.
"""

from collections import defaultdict

from deepspeed_tpu.utils.logging import logger


def get_caller_func(frame=3):
    import sys
    return sys._getframe(frame).f_code.co_name


def calc_bw_log(comm_op, size_bytes, duration_s, n=None):
    """Algorithmic and bus bandwidth in GB/s (reference ``comms_logging.py:34``)."""
    if duration_s <= 0:
        return 0.0, 0.0
    if n is None:
        try:
            import jax
            n = max(jax.device_count(), 1)
        except Exception:
            n = 1
    tput = size_bytes / duration_s
    if comm_op in ("all_to_all", "all_to_all_single"):
        busbw = tput * ((n - 1) / n)
    elif comm_op in ("all_gather", "all_gather_into_tensor", "reduce_scatter",
                     "reduce_scatter_tensor"):
        busbw = tput * ((n - 1) / n)
    elif comm_op in ("all_reduce", "inference_all_reduce"):
        busbw = tput * (2 * (n - 1) / n)
    else:  # pt2pt, broadcast, reduce
        busbw = tput
    return tput / 1e9, busbw / 1e9


class CommsLogger:
    """reference ``comms_logging.py:67`` CommsLogger."""

    def __init__(self):
        self.enabled = False
        self.prof_all = False
        self.prof_ops = []
        self.verbose = False
        self.debug = False
        self.comms_dict = defaultdict(lambda: defaultdict(lambda: [0, 0.0, 0.0, 0.0]))

    def configure(self, comms_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None):
        if comms_config is not None:
            self.enabled = getattr(comms_config, "enabled", self.enabled)
            self.prof_all = getattr(comms_config, "prof_all", self.prof_all)
            self.prof_ops = getattr(comms_config, "prof_ops", self.prof_ops)
            self.verbose = getattr(comms_config, "verbose", self.verbose)
        if enabled is not None:
            self.enabled = enabled
        if prof_all is not None:
            self.prof_all = prof_all
        if prof_ops is not None:
            self.prof_ops = prof_ops
        if verbose is not None:
            self.verbose = verbose

    def append(self, raw_name, record_name, latency_s, msg_size):
        if self.prof_ops and raw_name not in self.prof_ops and not self.prof_all:
            return
        algbw, busbw = calc_bw_log(raw_name, msg_size, latency_s)
        rec = self.comms_dict[record_name][msg_size]
        rec[0] += 1
        rec[1] += latency_s * 1000.0
        rec[2] += algbw
        rec[3] += busbw
        if self.verbose:
            logger.info(f"comm op: {record_name} | time(ms): {latency_s*1000:.2f} | "
                        f"msg size: {msg_size} | algbw (Gbps): {algbw*8:.2f} | "
                        f"busbw (Gbps): {busbw*8:.2f}")

    def format_summary(self):
        """The summary table as a string (stable format — pinned by the
        golden-output test in tests/test_aux_subsystems.py)."""
        lines = [f"{'Comm. Op':<20}{'Message Size':<20}{'Count':<10}"
                 f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<20}"
                 f"{'tput_avg (GB/s)':<20}{'busbw_avg (GB/s)':<20}"]
        for record_name, sizes in self.comms_dict.items():
            for size, (count, total_ms, algbw, busbw) in sorted(sizes.items()):
                lines.append(f"{record_name:<20}{size:<20}{count:<10}"
                             f"{total_ms:<20.2f}{total_ms/max(count,1):<20.2f}"
                             f"{algbw/max(count,1):<20.2f}{busbw/max(count,1):<20.2f}")
        return "\n".join(lines)

    def log_all(self, print_log=True, show_straggler=False):
        if print_log:
            logger.info("\n" + self.format_summary())
        return self.comms_dict
