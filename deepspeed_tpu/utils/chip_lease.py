"""Shared flock-based chip lease + backend init retry.

ROADMAP item 5: every bench round so far died because the TPU was held by
another process — and the holders were usually OUR OWN concurrent legs
(pytest, bench, scripts) racing for the chip. The fix is a single lease
protocol shared by every entrypoint: one ``flock``'d file per host; whoever
holds it owns the chip, everyone else QUEUES (bounded) instead of wedging
the backend and killing both runs.

``flock`` gives exactly the semantics a crashy harness needs: the lock dies
with the process (SIGKILL included), so a crashed bench can never wedge the
queue the way a stale libtpu lockholder wedges the chip. Holder metadata
(pid/run id/argv) is written into the lock file for diagnostics — readable
by waiters even while locked.

CPU-pinned runs (``JAX_PLATFORMS=cpu`` or the in-Python pin) skip the lease
entirely: there is no chip to serialize on, and the tier-1 CPU lane must
never queue behind a TPU job.

``init_backend_with_retry`` — previously bench.py-private — lives here so
``bench.py``, ``scripts/bench_serving.py``, ``scripts/bench_llama.py`` and
the ``onchip`` pytest marker (tests/conftest.py) all share one probe/retry/
lease path. bench.py injects its stale-holder ``_active_recovery`` as the
``recovery`` hook; the kill policy stays there — this module only queues.
"""

import atexit
import json
import os
import sys
import tempfile
import time

#: default bound on how long a waiter queues for the chip before giving up
#: (seconds). Long on purpose: the queue exists so concurrent runs SERIALIZE;
#: a short timeout would just reintroduce the wedge-and-die failure mode.
LEASE_TIMEOUT_S = float(os.environ.get("DS_TPU_CHIP_LEASE_TIMEOUT", "1800"))


def default_lock_path():
    """One lock file per host (override: DS_TPU_CHIP_LOCK). tempdir, not the
    repo: two checkouts benching the same chip must share the lease."""
    return os.environ.get("DS_TPU_CHIP_LOCK") or \
        os.path.join(tempfile.gettempdir(), "ds_tpu_chip.lease")


def cpu_only():
    """True when this process is pinned to CPU (env var or the in-Python
    ``jax.config`` pin — the axon sitecustomize ignores the env var, so the
    in-Python pin is the one that counts when jax is already imported)."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats and all(p.strip() in ("cpu", "") for p in plats.split(",")):
        return True
    if "jax" in sys.modules:
        try:
            import jax
            pin = getattr(jax.config, "jax_platforms", None)
            if pin and all(p.strip() in ("cpu", "")
                           for p in str(pin).split(",")):
                return True
        except Exception:
            pass
    return False


class ChipLeaseTimeout(TimeoutError):
    """The lease stayed held past the waiter's deadline."""


class ChipLease:
    """An exclusive ``flock`` on the per-host chip lock file.

    Usable as a context manager; ``acquire`` polls (the lock holder may be
    another process OR another fd in this process — both conflict, which is
    what makes the protocol testable without subprocesses)."""

    def __init__(self, name="harness", path=None):
        self.name = name
        self.path = path or default_lock_path()
        self._fh = None

    @property
    def held(self):
        return self._fh is not None

    def holder(self):
        """Metadata JSON of the current/most-recent holder, or None."""
        try:
            with open(self.path) as f:
                return json.load(f)
        except Exception:
            return None

    def acquire(self, timeout_s=None, poll_s=1.0):
        if self._fh is not None:
            return self
        import fcntl
        if timeout_s is None:
            timeout_s = LEASE_TIMEOUT_S
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        fh = open(self.path, "a+")
        deadline = time.monotonic() + timeout_s
        next_warn = 0.0
        while True:
            try:
                fcntl.flock(fh.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                now = time.monotonic()
                if now >= deadline:
                    fh.close()
                    raise ChipLeaseTimeout(
                        f"chip lease {self.path} still held after "
                        f"{timeout_s:.0f}s (holder: {self.holder()})")
                if now >= next_warn:
                    print(f"chip_lease: {self.name} queued for {self.path} "
                          f"(holder: {self.holder()})", file=sys.stderr)
                    next_warn = now + 30.0
                time.sleep(min(poll_s, max(deadline - now, 0.01)))
        self._fh = fh
        try:  # holder metadata for waiters' diagnostics (best-effort)
            fh.seek(0)
            fh.truncate()
            json.dump({"name": self.name, "pid": os.getpid(),
                       "run_id": os.environ.get("DS_TPU_HARNESS_RUN_ID"),
                       "argv": sys.argv[:4],
                       "acquired_at": time.strftime("%Y-%m-%d %H:%M:%S")},
                      fh)
            fh.flush()
        except OSError:
            pass
        return self

    def release(self):
        if self._fh is None:
            return
        import fcntl
        try:
            fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)
            self._fh.close()
        except Exception:
            pass
        finally:
            self._fh = None

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False


_PROCESS_LEASE = None


def process_lease(name="harness", timeout_s=None, path=None):
    """Acquire the chip lease ONCE for this process's lifetime (released at
    exit; flock also drops it on any crash). Returns the lease, or None on
    CPU-pinned runs where there is no chip to serialize on."""
    global _PROCESS_LEASE
    if cpu_only():
        return None
    if _PROCESS_LEASE is not None and _PROCESS_LEASE.held:
        return _PROCESS_LEASE
    lease = ChipLease(name=name, path=path)
    lease.acquire(timeout_s=timeout_s)
    _PROCESS_LEASE = lease
    atexit.register(lease.release)
    return lease


def init_backend_with_retry(attempts=None, backoff_s=None,
                            probe_timeout_s=None, recovery=None,
                            lease_name="harness", lease_timeout_s=None):
    """Take the chip lease, then initialize the JAX backend with a
    subprocess probe + bounded retries (moved here from bench.py so every
    entrypoint shares it).

    A held/wedged chip either raises RuntimeError('Unable to initialize
    backend ...') or HANGS; the child-process probe
    (``utils/backend_probe.probe_backend``) takes the hang with a deadline
    so the caller keeps control. ``recovery`` (optional callable) runs after
    each failed attempt and may return a holder list — bench.py passes its
    stale-holder reaper. Returns the device list, or raises the last error
    (with ``.bench_holders`` attached when recovery reported any)."""
    if attempts is None:
        attempts = int(os.environ.get("DS_BENCH_INIT_ATTEMPTS", "4"))
    if backoff_s is None:
        backoff_s = float(os.environ.get("DS_BENCH_INIT_BACKOFF", "15"))
    # queue for the chip BEFORE probing: a probe racing the holder would
    # read "hang" and burn retry budget on a chip that was merely busy
    process_lease(name=lease_name, timeout_s=lease_timeout_s)
    from deepspeed_tpu.utils.backend_probe import probe_backend
    last = None
    holders_seen = []
    for attempt in range(1, attempts + 1):
        try:
            kind, detail = probe_backend(timeout_s=probe_timeout_s)
            if kind == "hang":
                raise RuntimeError(f"backend init UNAVAILABLE: {detail}")
            if kind != "ok":
                raise RuntimeError(f"backend {detail}")
            import jax
            devs = jax.devices()
            if devs:
                return devs
        except Exception as e:
            last = e
            print(f"chip_lease: backend init attempt {attempt}/{attempts} "
                  f"failed: {type(e).__name__}: {e}", file=sys.stderr)
            if recovery is not None:
                try:
                    holders_seen = recovery() or holders_seen
                except Exception as rec_err:
                    print(f"chip_lease: recovery hook failed: {rec_err}",
                          file=sys.stderr)
            # the parent's own init can fail transiently even when the probe
            # succeeded (chip grabbed in between); jax caches the failed
            # backend — clear it so the next attempt re-probes
            try:
                import jax
                jax.extend.backend.clear_backends()
            except Exception:
                try:
                    import jax
                    jax.clear_backends()
                except Exception:
                    pass
        if attempt < attempts:
            time.sleep(backoff_s * attempt)
    if last is not None and holders_seen:
        last.bench_holders = holders_seen  # surfaced in the error JSON
    raise last if last is not None else RuntimeError("no devices found")
