"""Version-portable ``shard_map``.

The repo targets the modern ``jax.shard_map`` API (keyword ``check_vma`` /
``axis_names``); older jax (< 0.6) only ships
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` / ``auto``
spelling and no top-level ``jax.shard_map`` attribute. This module exposes one
``shard_map`` callable that translates between the two so the rest of the
codebase (and tests importing ``jax.shard_map`` directly) run on either.

Translation rules (old-API backend):
  - ``check_vma=<bool>``        -> ``check_rep=<bool>``
  - ``axis_names={...}``        -> ``auto = mesh.axis_names - axis_names``
    (modern API names the *manual* axes; the legacy API names the *auto* ones)

``install()`` additionally patches ``jax.shard_map`` when the attribute is
missing, so third-party-style call sites keep working unmodified. It is
invoked on import.
"""

import jax

__all__ = ["shard_map", "install", "HAS_NATIVE_SHARD_MAP"]

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")

if HAS_NATIVE_SHARD_MAP:

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, *,
                  check_vma=None, check_rep=None, axis_names=None,
                  auto=None, **kw):
        if check_vma is None and check_rep is not None:
            check_vma = check_rep
        if axis_names is None and auto is not None:
            axis_names = frozenset(mesh.axis_names) - frozenset(auto)
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)

else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, *,
                  check_vma=None, check_rep=None, axis_names=None,
                  auto=None, **kw):
        if check_rep is None:
            check_rep = True if check_vma is None else check_vma
        if auto is None and axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = frozenset(auto)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_rep,
                                 **kw)


def axis_size(axis_name):
    """Static size of a bound mesh axis (modern ``jax.lax.axis_size``)."""
    from jax._src import core as _jcore
    return _jcore.get_axis_env().axis_size(axis_name)


def install():
    """Give ``jax`` a top-level ``shard_map`` (and ``lax.axis_size``) when
    the running version lacks them — call sites and tests written against
    the modern API then work unmodified."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = axis_size


install()
