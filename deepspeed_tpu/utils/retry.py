"""Retry with exponential backoff + full jitter + deadline.

The one backoff policy shared by every transient-failure path in the
runtime: ``comm.init_distributed`` (coordinator races at gang start),
checkpoint host I/O (NFS/GCS blips), and the elastic agent's relaunch loop
(docs/RESILIENCE.md). Full jitter follows the AWS architecture-blog result:
``delay = uniform(0, min(max_delay, base * factor**attempt))`` decorrelates
a gang of workers all retrying the same failed resource.

Deterministic by construction: the RNG, clock and sleep are all injectable,
so tests (and the fault drill) can pin exact delay sequences.
"""

import random
import time


class RetryError(RuntimeError):
    """Raised when retries are exhausted or the deadline would be exceeded.
    ``last`` holds the final underlying exception; ``attempts`` how many
    calls were made."""

    def __init__(self, msg, last=None, attempts=0):
        super().__init__(msg)
        self.last = last
        self.attempts = attempts


class BackoffPolicy:
    """Exponential backoff with optional full jitter.

    ``delay(attempt)`` maps a 1-based attempt number to a sleep in seconds:
    cap = min(max_delay, base * factor**(attempt-1)); full jitter draws
    uniform(0, cap), "none" returns the cap itself (deterministic ladders
    for tests and for the elastic agent's logged schedule).
    """

    def __init__(self, base=0.5, factor=2.0, max_delay=30.0, jitter="full",
                 rng=None):
        if base < 0 or factor < 1.0 or max_delay < 0:
            raise ValueError(f"invalid backoff: base={base} factor={factor} "
                             f"max_delay={max_delay}")
        if jitter not in ("full", "none"):
            raise ValueError(f"jitter must be 'full' or 'none', got {jitter!r}")
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self._rng = rng if rng is not None else random.Random()

    def cap(self, attempt):
        """The un-jittered ceiling for ``attempt`` (1-based)."""
        if attempt < 1:
            raise ValueError(f"attempt is 1-based, got {attempt}")
        return min(self.max_delay, self.base * self.factor ** (attempt - 1))

    def delay(self, attempt):
        c = self.cap(attempt)
        if self.jitter == "none":
            return c
        return self._rng.uniform(0.0, c)


def retry_call(fn, *args, retries=3, base_delay=0.5, factor=2.0,
               max_delay=30.0, deadline=None, jitter="full",
               retry_on=(OSError,), rng=None, sleep=time.sleep,
               clock=time.monotonic, on_retry=None, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying on ``retry_on`` exceptions.

    - ``retries``: number of retries AFTER the first attempt (so up to
      ``retries + 1`` calls total).
    - ``deadline``: wall-clock budget in seconds from the first attempt; a
      retry whose backoff sleep would overrun it raises :class:`RetryError`
      immediately instead of sleeping past the budget.
    - ``on_retry(attempt, exc, delay)``: observation hook (logging,
      telemetry) before each sleep.

    Exhaustion raises :class:`RetryError` with the last exception chained
    (``raise ... from last``); non-matching exceptions propagate untouched.
    """
    policy = BackoffPolicy(base=base_delay, factor=factor,
                           max_delay=max_delay, jitter=jitter, rng=rng)
    t0 = clock()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if attempt > retries:
                raise RetryError(
                    f"{getattr(fn, '__name__', fn)!s} failed after "
                    f"{attempt} attempts: {type(e).__name__}: {e}",
                    last=e, attempts=attempt) from e
            d = policy.delay(attempt)
            if deadline is not None and (clock() - t0) + d > deadline:
                raise RetryError(
                    f"{getattr(fn, '__name__', fn)!s}: deadline {deadline}s "
                    f"would be exceeded after {attempt} attempts "
                    f"({type(e).__name__}: {e})",
                    last=e, attempts=attempt) from e
            if on_retry is not None:
                on_retry(attempt, e, d)
            sleep(d)


def retryable(**retry_kwargs):
    """Decorator form of :func:`retry_call`::

        @retryable(retries=2, retry_on=(OSError,))
        def write_shard(path): ...
    """
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, **retry_kwargs, **kwargs)
        return wrapper
    return deco
