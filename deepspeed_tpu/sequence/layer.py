"""DeepSpeed-Ulysses sequence parallelism.

Mirrors reference ``deepspeed/sequence/layer.py``: ``_SeqAllToAll`` (:44) and
``DistributedAttention`` (:60) — before attention, all-to-all over the SP group
scatters heads and gathers sequence (each rank goes from [B, T/sp, H, Dh] to
[B, T, H/sp, Dh]); after local attention the inverse all-to-all restores
sequence sharding. On TPU the all-to-all is ``lax.all_to_all`` over the ``sp``
mesh axis riding ICI; these functions are called inside ``shard_map`` (or any
context where the ``sp`` axis name is bound).
"""

from typing import Callable

import jax
from jax import lax


def seq_all_to_all(x, axis_name="sp", scatter_axis=2, gather_axis=1):
    """reference ``_SeqAllToAll.forward`` (layer.py:44): redistribute a local
    tensor by scattering ``scatter_axis`` and gathering ``gather_axis``."""
    return lax.all_to_all(x, axis_name, split_axis=scatter_axis,
                          concat_axis=gather_axis, tiled=True)


class DistributedAttention:
    """reference ``DistributedAttention`` (layer.py:60): wraps any local
    attention callable. Inputs are sequence-sharded [B, T/sp, H, Dh]; the
    wrapped attention sees full sequence with H/sp heads."""

    def __init__(self, local_attention: Callable, axis_name="sp",
                 scatter_idx=2, gather_idx=1):
        self.local_attn = local_attention
        self.axis_name = axis_name
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, query, key, value, *args, **kwargs):
        a, s, g = self.axis_name, self.scatter_idx, self.gather_idx
        q = seq_all_to_all(query, a, s, g)
        k = seq_all_to_all(key, a, s, g)
        v = seq_all_to_all(value, a, s, g)
        ctx = self.local_attn(q, k, v, *args, **kwargs)
        # inverse: scatter seq back, gather heads
        return seq_all_to_all(ctx, a, scatter_axis=g, gather_axis=s)


def ulysses_attention(q, k, v, local_attention, axis_name="sp"):
    """Functional form of DistributedAttention."""
    return DistributedAttention(local_attention, axis_name)(q, k, v)
