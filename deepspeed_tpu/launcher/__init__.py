from deepspeed_tpu.launcher import runner

__all__ = ["runner"]
