"""Multinode runners — PDSH / SLURM / OpenMPI / MPICH command builders.

Reference ``launcher/multinode_runner.py``: ``PDSHRunner`` (:51),
``OpenMPIRunner`` (:118), ``MPICHRunner`` (:182), ``IMPIRunner`` (:244),
``SlurmRunner`` (:328), ``MVAPICHRunner``. Each turns (exports, resource pool,
user command) into one scheduler invocation that starts every node.

TPU adaptation: one process per HOST (a single JAX process drives all local
chips), so every runner launches exactly ``len(pool)`` tasks, one per node.
The per-process rank is NOT baked into the command — it comes from the
scheduler at runtime (``SLURM_PROCID`` / ``OMPI_COMM_WORLD_RANK`` /
``PMI_RANK``), or, for PDSH (which has no rank concept), from the node's
hostname position in the broadcast ``DS_WORLD_INFO`` — all resolved by
``comm.init_distributed`` discovery (comm/comm.py).
"""

import os
import shlex
import shutil
from abc import ABC, abstractmethod

from deepspeed_tpu.launcher.runner import EXPORT_ENVS, encode_world_info


class MultiNodeRunner(ABC):
    """One scheduler's command builder (reference ``MultiNodeRunner:21``)."""

    def __init__(self, pool, master_addr, master_port):
        self.pool = pool  # OrderedDict host -> slots
        self.master_addr = master_addr
        self.master_port = master_port
        self.exports = {}

    @property
    def hosts(self):
        return list(self.pool)

    def add_export(self, key, value):
        self.exports[key.strip()] = str(value).strip()

    def base_env(self):
        """The launch contract every node receives. RANK is intentionally
        absent — the scheduler (or hostname lookup) supplies it."""
        env = {
            "MASTER_ADDR": str(self.master_addr),
            "MASTER_PORT": str(self.master_port),
            "WORLD_SIZE": str(len(self.pool)),
            "DS_WORLD_INFO": encode_world_info(self.pool),
        }
        for k in EXPORT_ENVS:
            if k in os.environ:
                env[k] = os.environ[k]
        env.update(self.exports)
        return env

    @property
    @abstractmethod
    def name(self):
        ...

    @abstractmethod
    def backend_exists(self):
        """Is the scheduler binary on PATH (reference ``backend_exists``)?"""
        ...

    @abstractmethod
    def get_cmd(self, program):
        """Full argv launching ``program`` (a token list) on every node."""
        ...


class PDSHRunner(MultiNodeRunner):
    """reference ``PDSHRunner:51`` — parallel ssh fanout."""

    @property
    def name(self):
        return "pdsh"

    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self, program):
        env = self.base_env()
        exports = [f"export {k}={shlex.quote(v)};" for k, v in env.items()]
        remote = " ".join(exports + [f"cd {shlex.quote(os.getcwd())};"]
                          + [shlex.quote(t) for t in program])
        # -S: propagate the largest remote exit code; fanout covers all nodes
        # at once (reference PDSH_MAX_FAN_OUT)
        return ["pdsh", "-S", "-f", "1024", "-w", ",".join(self.hosts), remote]


class SlurmRunner(MultiNodeRunner):
    """reference ``SlurmRunner:328`` — srun, one task per node. The natural
    launcher for TPU pods driven by a SLURM-managed CPU fleet; rank/size come
    from SLURM_PROCID/SLURM_NTASKS at runtime."""

    @property
    def name(self):
        return "slurm"

    def backend_exists(self):
        return shutil.which("srun") is not None

    def get_cmd(self, program):
        env = self.base_env()
        cmd = ["srun", "-n", str(len(self.pool)), "--ntasks-per-node=1"]
        if self.hosts and self.hosts != ["localhost"]:
            cmd += ["--nodelist", ",".join(self.hosts)]
        # ALL keeps the submitting shell's env; explicit pairs pin the contract
        pairs = ",".join(f"{k}={v}" for k, v in env.items())
        cmd += [f"--export=ALL,{pairs}"]
        return cmd + list(program)


class OpenMPIRunner(MultiNodeRunner):
    """reference ``OpenMPIRunner:118`` — mpirun with per-env -x flags."""

    @property
    def name(self):
        return "openmpi"

    def backend_exists(self):
        return shutil.which("ompi_info") is not None

    def get_cmd(self, program):
        env = self.base_env()
        cmd = ["mpirun", "-n", str(len(self.pool)),
               "--host", ",".join(f"{h}:1" for h in self.hosts),
               "--mca", "btl", "^openib"]  # TCP control plane; data rides ICI
        # NIC selection is site-specific (GCP TPU-VMs use ens*, not eth0):
        # only pin the interface when the operator names one
        iface = os.environ.get("DS_MPI_TCP_IF_INCLUDE")
        if iface:
            cmd += ["--mca", "btl_tcp_if_include", iface]
        for k, v in env.items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + list(program)


class MPICHRunner(MultiNodeRunner):
    """reference ``MPICHRunner:182`` — hydra mpirun (-hosts/-genv)."""

    @property
    def name(self):
        return "mpich"

    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self, program):
        env = self.base_env()
        cmd = ["mpirun", "-n", str(len(self.pool)),
               "-hosts", ",".join(self.hosts), "-ppn", "1"]
        for k, v in env.items():
            cmd += ["-genv", k, str(v)]
        return cmd + list(program)


class IMPIRunner(MPICHRunner):
    """reference ``IMPIRunner:244`` — Intel MPI; hydra-compatible flags."""

    @property
    def name(self):
        return "impi"


RUNNERS = {
    "pdsh": PDSHRunner,
    "slurm": SlurmRunner,
    "openmpi": OpenMPIRunner,
    "mpich": MPICHRunner,
    "impi": IMPIRunner,
}


def build_runner(launcher, pool, master_addr, master_port):
    cls = RUNNERS.get(launcher)
    if cls is None:
        raise ValueError(f"unknown launcher {launcher!r}; have {sorted(RUNNERS)}")
    return cls(pool, master_addr, master_port)
