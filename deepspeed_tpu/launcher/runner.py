"""Multi-host launcher — the ``deepspeed`` CLI for TPU pods.

Reference ``launcher/runner.py``: parses a hostfile (:200), applies
include/exclude filters (:255), encodes world info, and uses
PDSH/MPI/SLURM runners (``multinode_runner.py``) to start ``launch.py`` on
every node, which spawns one process per GPU.

TPU differences that shape this port:
- One process per HOST, not per chip: a single JAX process drives all local
  chips, and ``jax.distributed.initialize(coordinator, num_processes,
  process_id)`` forms the multi-host mesh over ICI/DCN.
- "Slots" in the hostfile are chips per host (informational — JAX discovers
  local chips itself).
- The per-node contract is environment variables (MASTER_ADDR/PORT, RANK,
  WORLD_SIZE, LOCAL_RANK) consumed by ``comm.init_distributed``
  (comm/comm.py analog), same names as the reference so user scripts port
  unchanged.

Usage::

    python -m deepspeed_tpu.launcher.runner --hostfile hosts.txt \
        [--include "host1@host2"] [--master_addr ...] train.py --args
"""

import argparse
import base64
import collections
import json
import os
import shlex
import signal
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ("PYTHONPATH", "PATH", "LD_LIBRARY_PATH", "XLA_FLAGS",
               "LIBTPU_INIT_ARGS", "JAX_PLATFORMS", "TPU_CHIPS_PER_HOST_BOUNDS",
               "DS_TPU_FAULTS", "DS_TPU_FAULT_SEED")


def parse_hostfile(path):
    """hostfile lines: ``hostname slots=N`` (reference fetch_hostfile :200).
    Returns an ordered {hostname: slots}."""
    if not os.path.isfile(path):
        raise FileNotFoundError(f"hostfile {path} not found")
    resource_pool = collections.OrderedDict()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError:
                raise ValueError(f"hostfile line not of form 'host slots=n': {line!r}")
            if hostname in resource_pool:
                raise ValueError(f"hostfile contains duplicate host {hostname}")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_filter(spec):
    """``host1:0,1@host2`` -> {host: [slot,...] or None} (reference
    _parse_hostfile inclusion syntax)."""
    out = {}
    for part in spec.split("@"):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, slots = part.split(":")
            out[host] = [int(s) for s in slots.split(",")]
        else:
            out[part] = None
    return out


def filter_resources(resource_pool, include="", exclude=""):
    """Apply include/exclude filters (reference parse_resource_filter :255)."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    pool = collections.OrderedDict(resource_pool)
    if include:
        inc = _parse_filter(include)
        unknown = set(inc) - set(pool)
        if unknown:
            raise ValueError(f"include names unknown hosts {sorted(unknown)}")
        for h, ids in inc.items():
            if ids is not None:
                bad = [i for i in set(ids) if i < 0 or i >= pool[h]]
                if bad:
                    raise ValueError(f"include lists invalid slot ids {bad} "
                                     f"for {h} (has {pool[h]})")
        pool = collections.OrderedDict(
            (h, len(set(inc[h])) if inc[h] is not None else pool[h])
            for h in pool if h in inc)
    elif exclude:
        exc = _parse_filter(exclude)
        unknown = set(exc) - set(pool)
        if unknown:
            raise ValueError(f"exclude names unknown hosts {sorted(unknown)}")
        out = collections.OrderedDict()
        for h, slots in pool.items():
            if h in exc:
                if exc[h] is None:
                    continue  # whole host excluded
                ids = set(exc[h])
                bad = [i for i in ids if i < 0 or i >= slots]
                if bad:
                    raise ValueError(f"exclude lists invalid slot ids {bad} "
                                     f"for {h} (has {slots})")
                remaining = slots - len(ids)
                if remaining > 0:
                    out[h] = remaining
            else:
                out[h] = slots
        pool = out
    if not pool:
        raise ValueError("no hosts remain after include/exclude filtering")
    return pool


def encode_world_info(resource_pool):
    """base64 world info passed to per-node launchers (reference
    encode_world_info)."""
    return base64.urlsafe_b64encode(
        json.dumps(dict(resource_pool)).encode()).decode()


def decode_world_info(blob):
    return json.loads(base64.urlsafe_b64decode(blob.encode()).decode())


def node_env(node_rank, n_nodes, master_addr, master_port):
    """The per-node environment contract (reference launch.py env setup —
    same variable names, but RANK is the host/process rank)."""
    return {
        "MASTER_ADDR": str(master_addr),
        "MASTER_PORT": str(master_port),
        "RANK": str(node_rank),
        "LOCAL_RANK": "0",
        "WORLD_SIZE": str(n_nodes),
        "NODE_RANK": str(node_rank),
    }


def build_ssh_command(host, env, program):
    """One node's ssh launch line (the PDSHRunner analog,
    ``multinode_runner.py:51``). Every program token is quoted so args with
    spaces/metacharacters survive the remote shell."""
    exports = [f"export {k}={shlex.quote(v)};" for k, v in env.items()]
    for k in EXPORT_ENVS:
        if k in os.environ:
            exports.append(f"export {k}={shlex.quote(os.environ[k])};")
    quoted = [shlex.quote(tok) for tok in program]
    remote = " ".join(exports + [f"cd {shlex.quote(os.getcwd())};"] + quoted)
    return ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]


_LOCAL_HOSTS = ("localhost", "127.0.0.1", "::1")


def main(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu multi-host launcher")
    parser.add_argument("--hostfile", default=DLTS_HOSTFILE)
    parser.add_argument("--include", default="")
    parser.add_argument("--exclude", default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", default="ssh",
                        choices=["ssh", "local", "pdsh", "slurm", "openmpi",
                                 "mpich", "impi"],
                        help="'ssh' launches remote hosts over ssh; 'local' "
                             "spawns every node locally (debug/dry-run); "
                             "pdsh/slurm/openmpi/mpich/impi delegate to that "
                             "scheduler (launcher/multinode_runner.py)")
    parser.add_argument("--force_multi", action="store_true",
                        help="use the ssh path even for localhost entries")
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(args)

    if os.path.isfile(args.hostfile):
        pool = parse_hostfile(args.hostfile)
        pool = filter_resources(pool, args.include, args.exclude)
        if args.num_nodes > 0:
            pool = collections.OrderedDict(list(pool.items())[:args.num_nodes])
    elif args.hostfile != DLTS_HOSTFILE:
        # an explicitly passed hostfile must exist — only the default path
        # silently falls back to single-node (reference runner behavior)
        raise FileNotFoundError(f"hostfile {args.hostfile} not found")
    else:
        pool = collections.OrderedDict([("localhost", 0)])

    hosts = list(pool)
    remote_hosts = [h for h in hosts if h not in _LOCAL_HOSTS]
    master = args.master_addr or hosts[0]
    if args.launcher == "local":
        # every "node" is a local process; the coordinator must be reachable
        # locally no matter what the hostfile names the nodes
        master = args.master_addr or "127.0.0.1"
    elif remote_hosts and master in _LOCAL_HOSTS:
        raise ValueError(
            "remote hosts present but the coordinator address resolves to "
            "localhost — pass --master_addr with an address the workers can "
            "reach (reference runner.py master_addr resolution)")
    program = [sys.executable, args.user_script] + args.user_args
    world_info = encode_world_info(pool)
    logger.info(f"launching on {len(hosts)} host(s): {hosts} "
                f"(coordinator {master}:{args.master_port})")

    if args.launcher in ("pdsh", "slurm", "openmpi", "mpich", "impi"):
        # one scheduler invocation starts every node; ranks resolve at
        # runtime (scheduler env / DS_WORLD_INFO hostname lookup)
        from deepspeed_tpu.launcher.multinode_runner import build_runner
        runner = build_runner(args.launcher, pool, master, args.master_port)
        if not runner.backend_exists():
            raise RuntimeError(
                f"--launcher {args.launcher}: backend binary not found on "
                f"PATH (reference multinode_runner backend_exists check)")
        cmd = runner.get_cmd(program)
        logger.info(f"{runner.name} cmd: {cmd}")
        proc = subprocess.Popen(cmd)

        def forward(signum, frame):
            try:
                proc.send_signal(signum)
            except ProcessLookupError:
                pass

        signal.signal(signal.SIGINT, forward)
        signal.signal(signal.SIGTERM, forward)
        proc.wait()
        return proc.returncode

    procs = []
    for rank, host in enumerate(hosts):
        env = node_env(rank, len(hosts), master, args.master_port)
        env["DS_WORLD_INFO"] = world_info  # slots-per-host for user scripts
        use_ssh = (args.launcher == "ssh"
                   and (host not in _LOCAL_HOSTS or args.force_multi))
        if use_ssh:
            procs.append(subprocess.Popen(build_ssh_command(host, env, program)))
        else:
            procs.append(subprocess.Popen(program, env=dict(os.environ, **env)))

    def forward_signal(signum, frame):  # reference launch.py:132 signal handling
        for p in procs:
            try:
                p.send_signal(signum)
            except ProcessLookupError:
                pass

    signal.signal(signal.SIGINT, forward_signal)
    signal.signal(signal.SIGTERM, forward_signal)

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
