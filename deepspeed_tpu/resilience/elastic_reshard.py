"""Elastic multi-slice training — survive slice loss mid-step, reshard to
the survivors, and re-expand, without losing the loss trajectory.

A multi-slice TPU job loses whole slices, not single chips: a DCN partition
or a preempted slice takes out a contiguous block of devices while the rest
of the gang is healthy. The reference DeepSpeed answer is elasticity
(``deepspeed/elasticity``): tear the job down, relaunch at the surviving
world size, resume from the last checkpoint. This module is the jax-native
version, and because sharding here is data (a ``jax.sharding.Mesh``) rather
than process groups, *resharding is a rebuild, not a renegotiation*:

1. a slice-loss fault surfaces (``slice.lost`` / ``comm.partition`` from
   :mod:`~deepspeed_tpu.resilience.faults`, or exit code
   :data:`EXIT_RESHARD_SLICE_LOSS` at the elastic-agent level),
2. :func:`build_topology_for` derives a :class:`MeshTopology` over the
   survivors — the ZeRO partition, QgzPlan and hpZ primary-exchange layout
   all re-derive from it at engine construction,
3. the newest durable universal-checkpoint tag (name-keyed fp32 fragments,
   crash-consistently published) is loaded under the new mesh —
   ``device_put`` against the survivor sharding IS the reshard,
4. the step loop resumes at exactly ``engine.global_steps`` — no step lost,
   none double-applied — and the loss trajectory continues bitwise (the
   fp32 master update is reduction-order independent across dp worlds for
   the fragment layout we save),
5. when capacity returns, the same path runs in reverse (expand).

Two consumers:

- **in-process** (:class:`ElasticReshardController` + :func:`run_elastic`):
  the CPU drill — 8 forced host devices, kill 4-of-8 mid-step, continue on
  4, re-expand to 8. Used by ``tests/test_elastic_reshard.py`` and
  ``scripts/fault_drill.py --drill slice-loss``.
- **cross-process** (:data:`EXIT_RESHARD_SLICE_LOSS`): the engine's
  ``_handle_slice_loss`` saves an emergency universal checkpoint and exits
  84; ``elasticity/elastic_agent.py`` classifies that exit, drops the dead
  hosts, and relaunches the survivors budget-free.

Module scope imports only the standard library (the resilience package
contract) — jax and the runtime are imported lazily inside functions.
"""

import math
import os
import time

from deepspeed_tpu.resilience import faults

#: Exit code a worker uses to report "my gang lost a slice but MY state is
#: durable — relaunch me on the survivors". Sibling of
#: ``EXIT_CLEAN_PREEMPTION`` (83) / ``EXIT_WATCHDOG_ABORT`` (85); like 83 it
#: does not burn elastic restart budget (the fault is the platform's, not
#: the job's).
EXIT_RESHARD_SLICE_LOSS = 84


class SliceLostError(RuntimeError):
    """A slice-loss condition detected outside the fault registry (e.g. a
    collective timeout the caller maps to a lost slice). Carries the set of
    lost slice indices when known."""

    def __init__(self, msg="slice lost", lost_slices=()):
        super().__init__(msg)
        self.lost_slices = tuple(lost_slices)


def is_slice_loss(exc):
    """Is this exception a reshardable slice loss (vs a real crash)?"""
    if isinstance(exc, SliceLostError):
        return True
    return (isinstance(exc, faults.InjectedFault)
            and exc.point in faults.SLICE_LOSS_POINTS)


# --------------------------------------------------------------- topology

def slice_devices(devices, n_slices):
    """Partition a flat device list into ``n_slices`` contiguous slices —
    the multi-slice model where devices [0..n/k) share slice 0's ICI."""
    n = len(devices)
    if n_slices < 1 or n % n_slices:
        raise ValueError(
            f"{n} devices do not split into {n_slices} equal slices")
    per = n // n_slices
    return [list(devices[i * per:(i + 1) * per]) for i in range(n_slices)]


def surviving_devices(devices, lost_slices, n_slices):
    """The devices left after the given slice indices die."""
    lost = set(lost_slices)
    keep = [s for i, s in enumerate(slice_devices(devices, n_slices))
            if i not in lost]
    if not keep:
        raise SliceLostError("all slices lost — nothing to reshard onto",
                             lost_slices=lost_slices)
    return [d for s in keep for d in s]


def build_topology_for(devices, like=None):
    """Derive the survivor/expanded :class:`MeshTopology` for ``devices``.

    ``like`` is the previous topology: model-parallel axes (pp/ep/sp/tp)
    are preserved — a slice loss shrinks the *data-parallel* world — and
    the hpZ/MiCS shard-group size is clamped to the largest divisor of the
    new dp world (collapsing the hierarchy entirely when the survivors fit
    a single shard group)."""
    from deepspeed_tpu.parallel.topology import MeshTopology
    if like is None:
        return MeshTopology(devices=devices)
    fixed = like.pp_size * like.ep_size * like.sp_size * like.tp_size
    n = len(devices)
    if n % fixed:
        raise SliceLostError(
            f"{n} surviving devices cannot carry the model-parallel layout "
            f"pp{like.pp_size} x ep{like.ep_size} x sp{like.sp_size} x "
            f"tp{like.tp_size} (= {fixed}); shrink is dp-only")
    new_dp = n // fixed
    shard, hierarchy = None, None
    if like.zero_hierarchy is not None:
        want = like.dp_size  # old shard-group size
        shard = math.gcd(want, new_dp)
        while new_dp % shard:  # pragma: no cover - gcd already divides
            shard -= 1
        if shard >= new_dp or shard <= 1:
            shard = None  # hierarchy collapses to plain ZeRO
        else:
            hierarchy = like.zero_hierarchy
    return MeshTopology(pp=like.pp_size, ep=like.ep_size, sp=like.sp_size,
                        tp=like.tp_size, devices=devices,
                        zero_shard_size=shard, zero_hierarchy=hierarchy)


# ----------------------------------------------------------------- replan

def replan_for_world(model, model_parameters, base_config, batch_fn, world,
                     compile_fn=None, **tune_kwargs):
    """Chip-free re-plan for a resharded world size: rank the config grid
    for an ``elastic:<world>x1`` topology (the autotuner parses the dp
    world straight out of the name) and return ``(config, ranking)``.
    ``compile_fn`` is injectable exactly as in ``tune_chip_free`` so the
    CPU drill re-plans without AOT compiles."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    tuner = Autotuner(model, model_parameters, base_config, batch_fn)
    return tuner.tune_chip_free(topology_name=f"elastic:{world}x1",
                                compile_fn=compile_fn, **tune_kwargs)


# ------------------------------------------------------------- controller

class ElasticReshardController:
    """Drives one training gang through shrink/expand reshard cycles.

    ``build_engine(mesh_topology)`` is the caller's closure that constructs
    a fresh engine (model init + ``deepspeed_tpu.initialize(mesh=...)``) —
    the controller owns *when* to rebuild, the closure owns *how*. Every
    rebuild re-derives the ZeRO partition, the quantized-gradient plan and
    the hpZ primary-exchange layout for the new mesh; state then arrives
    via the universal checkpoint, which is topology-free by construction.

    The step loop contract (:meth:`train_step`): a return of ``None`` means
    "a slice died and I resharded — replay this batch"; the caller indexes
    batches by ``engine.global_steps``, which the restore path rewinds to
    the last durable step, so no step is ever lost or double-applied.
    """

    def __init__(self, build_engine, ckpt_dir, n_slices=2, checkpoint_every=1,
                 replan_fn=None, restore_retries=2, restore_delay=0.05,
                 sleep=None, devices=None):
        self.build_engine = build_engine
        self.ckpt_dir = str(ckpt_dir)
        self.n_slices = n_slices
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.replan_fn = replan_fn          # world -> plan (or None)
        self.restore_retries = restore_retries
        self.restore_delay = restore_delay
        self._sleep = sleep                 # injectable for tests
        self._all_devices = list(devices) if devices is not None else None
        self.engine = None
        self.last_plan = None
        self.world_history = []             # world size after every (re)build
        self.reshard_events = []            # dicts: kind/world/seconds/step/...

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Build the full-world engine and write the step-0 tag so even a
        fault on the very first step has a durable restore point."""
        import jax
        if self._all_devices is None:
            self._all_devices = list(jax.devices())
        self._build(self._all_devices, kind="start")
        self.checkpoint(force=True)
        return self.engine

    def _build(self, devices, kind, like=None):
        from deepspeed_tpu.parallel import groups
        groups.reset()
        topo = build_topology_for(devices, like=like)
        self.engine = self.build_engine(topo)
        world = topo.world_size()
        self.world_history.append(world)
        self._record("elastic/world_size", world, kind_tag=kind)
        return topo

    # -- step loop -------------------------------------------------------
    def train_step(self, batch):
        """One fwd/bwd/step. Returns the step's loss as a float, or ``None``
        if a slice was lost mid-step (state resharded to the survivors; the
        caller must replay the batch at the — rewound — current step)."""
        engine = self.engine
        try:
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
        except BaseException as e:  # InjectedFault / SliceLostError
            if not is_slice_loss(e):
                raise
            self.shrink(lost_slices=getattr(e, "lost_slices", None) or (
                tuple(range(self.n_slices // 2, self.n_slices))))
            return None
        if engine.global_steps % self.checkpoint_every == 0:
            self.checkpoint()
        import numpy as np
        # the recorded loss is the trajectory evidence — the host read is
        # the point
        return float(
            np.asarray(loss))  # graftlint: allow[GL004] loss record is host

    def checkpoint(self, force=False):
        from deepspeed_tpu.checkpoint.universal import save_universal_checkpoint
        step = self.engine.global_steps
        tag = f"ustep{step}"
        if not force and os.path.isdir(os.path.join(self.ckpt_dir, tag)):
            return tag
        save_universal_checkpoint(self.engine, self.ckpt_dir, tag=tag)
        return tag

    # -- reshard ---------------------------------------------------------
    def shrink(self, lost_slices=None):
        """Reshard onto the survivors of ``lost_slices`` (default: the
        upper half of the slice set — the injected-drill convention)."""
        if lost_slices is None:
            lost_slices = tuple(range(self.n_slices // 2, self.n_slices))
        survivors = surviving_devices(self._all_devices, lost_slices,
                                      self.n_slices)
        return self._reshard(survivors, kind="shrink",
                             lost_slices=tuple(lost_slices))

    def expand(self, devices=None):
        """Re-expand onto the full (or given) device set — the reverse path
        of :meth:`shrink`, restoring the original partition layout."""
        return self._reshard(list(devices) if devices is not None
                             else list(self._all_devices), kind="expand")

    def _reshard(self, devices, kind, lost_slices=()):
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.checkpoint.universal import (
            latest_universal_tag, load_universal_checkpoint,
            read_universal_meta, topology_remap)
        from deepspeed_tpu.utils.logging import logger
        from deepspeed_tpu.utils.retry import retry_call
        t0 = time.perf_counter()
        old = self.engine.topology if self.engine is not None else None
        span = telemetry.span_begin("Recovery/reshard", event=kind,
                                    world=len(devices))
        try:
            topo = self._build(devices, kind=kind, like=old)
            tag = latest_universal_tag(self.ckpt_dir)
            if tag is None:
                raise SliceLostError(
                    f"no durable universal tag under {self.ckpt_dir!r} to "
                    f"reshard from", lost_slices=lost_slices)
            tag_dir = os.path.join(self.ckpt_dir, tag)
            remap = topology_remap(read_universal_meta(tag_dir), topo)
            retry_call(lambda: load_universal_checkpoint(self.engine, tag_dir),
                       retries=self.restore_retries,
                       base_delay=self.restore_delay,
                       retry_on=(OSError, ValueError), sleep=self._sleep)
            if self.replan_fn is not None:
                self.last_plan = self.replan_fn(topo.world_size())
        finally:
            span.end()
        seconds = time.perf_counter() - t0
        event = {"kind": kind, "world": topo.world_size(),
                 "from_world": remap["from_world"], "tag": tag,
                 "step": self.engine.global_steps, "seconds": seconds,
                 "lost_slices": tuple(lost_slices),
                 "axis_deltas": remap["axis_deltas"]}
        self.reshard_events.append(event)
        self._record("elastic/reshard_s", seconds, kind_tag=kind)
        telemetry.count("Recovery/reshard", event=kind,
                        world=topo.world_size())
        logger.warning(
            f"elastic reshard ({kind}): world {remap['from_world']} -> "
            f"{topo.world_size()}, resumed at step {self.engine.global_steps} "
            f"from tag {tag!r} in {seconds:.3f}s")
        return event

    def _record(self, name, value, kind_tag=""):
        from deepspeed_tpu import telemetry
        telemetry.record(name, value, kind="gauge", event=kind_tag)


def run_elastic(controller, batches, expand_at=None):
    """Drive ``controller`` over ``batches``, replaying on reshard.

    Batches are indexed by ``engine.global_steps`` — after a shrink the
    restore path rewinds that counter to the last durable step, so the
    replay picks up the exact batch whose optimizer step never applied.
    ``expand_at``: step number before which to re-expand to the full world
    (checked when the loop reaches it, i.e. after step ``expand_at - 1``
    committed). Returns ``{"losses": {step: loss}, "opt_steps": [...]}``
    plus the controller's world/reshard history."""
    if controller.engine is None:
        controller.start()
    losses = {}
    opt_steps = []
    n = len(batches)
    while controller.engine.global_steps < n:
        step = controller.engine.global_steps
        if expand_at is not None and step >= expand_at and \
                controller.world_history[-1] < controller.world_history[0]:
            controller.expand()
            continue  # re-read global_steps under the restored engine
        loss = controller.train_step(batches[step])
        if loss is None:
            continue  # slice lost — replay at the rewound step
        losses[step] = loss
        opt_steps.append(controller.engine.global_steps)
    return {"losses": losses, "opt_steps": opt_steps,
            "world_history": list(controller.world_history),
            "reshard_events": list(controller.reshard_events)}


# ------------------------------------------------------------------ drill

def run_elastic_drill(ckpt_dir, steps=6, fail_at_step=2, expand_at=4,
                      n_slices=2, hidden_dim=32, replan=False):
    """The in-process 8→4→8 drill (CPU, 8 forced host devices): train with
    a ``slice.lost`` fault armed mid-run, shrink to the surviving half,
    re-expand, and compare the loss trajectory bitwise against a fault-free
    full-world reference run. Returns the baseline payload consumed by
    ``perf_gate.py check_elastic_baseline`` and asserted by the e2e test.
    """
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.universal import _opt_step_count
    from deepspeed_tpu.parallel import groups
    from tests.simple_model import SimpleModel, random_batches

    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 0},
    }
    batches = random_batches(steps, batch_size=8, seed=1)
    model = SimpleModel(hidden_dim=hidden_dim)
    init_params = model.init(jax.random.PRNGKey(0), batches[0])["params"]

    def build_engine(topo):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=init_params, config=dict(config),
            mesh=topo)
        return engine

    # fault-free full-world reference trajectory
    faults.reset()
    groups.reset()
    ref_engine = build_engine(build_topology_for(list(jax.devices())))
    ref_losses = {}
    for i, b in enumerate(batches):
        loss = ref_engine(b)
        ref_engine.backward(loss)
        ref_engine.step()
        ref_losses[i] = float(
            np.asarray(loss))  # graftlint: allow[GL004] bitwise reference

    replan_calls = []

    def replan_fn(world):
        plan, _ = replan_for_world(
            model, init_params, dict(config),
            lambda mbs: batches[0], world,
            compile_fn=_drill_compile_fn)
        replan_calls.append(world)
        return plan

    groups.reset()
    controller = ElasticReshardController(
        build_engine, ckpt_dir, n_slices=n_slices,
        replan_fn=replan_fn if replan else None)
    controller.start()
    # arm AFTER start: the whole upper half of the slice set dies exactly
    # once, mid-step (before the optimizer apply)
    faults.configure(f"slice.lost:once@step{fail_at_step}", seed=0)
    try:
        result = run_elastic(controller, batches, expand_at=expand_at)
    finally:
        faults.reset()

    worlds = result["world_history"]
    # bitwise identity is asserted AT each restore step (the replayed
    # forward under the resharded mesh against the full-world reference) —
    # steps after it may drift by ~1 ulp from the survivors' different
    # gradient reduction order, which is trajectory continuity, not loss
    restore_steps = [e["step"] for e in result["reshard_events"]]
    bitwise = all(result["losses"][s] == ref_losses[s]
                  for s in restore_steps if s in ref_losses)
    traj_rel_err = max(
        abs(result["losses"][i] - ref_losses[i]) / max(abs(ref_losses[i]),
                                                       1e-12)
        for i in ref_losses)
    payload = {
        "drill": "elastic-reshard-8-4-8",
        "steps": steps,
        "fail_at_step": fail_at_step,
        "expand_at": expand_at,
        "world_sequence": worlds,
        "reshard_count": len(result["reshard_events"]),
        "reshard_s": {e["kind"]: round(e["seconds"], 4)
                      for e in result["reshard_events"]},
        "steps_lost": steps - len(result["losses"]),
        "steps_double_applied": sum(
            1 for a, b in zip(result["opt_steps"], result["opt_steps"][1:])
            if b <= a),
        "final_optimizer_step": _opt_step_count(
            controller.engine.state.opt_state),
        "restore_steps": restore_steps,
        "restore_loss_bitwise_equal": bool(bitwise),
        "trajectory_max_rel_err": traj_rel_err,
        "losses": {str(k): v for k, v in sorted(result["losses"].items())},
        "ref_losses": {str(k): v for k, v in sorted(ref_losses.items())},
        "replan_worlds": replan_calls,
    }
    return payload


def _drill_compile_fn(fn, abstract):
    """Synthetic compile for chip-free re-planning inside the CPU drill."""
    class _Mem:
        temp_size_in_bytes = 1 << 20
        output_size_in_bytes = 1 << 20
    return {"flops": 1e9, "bytes accessed": 1e8}, _Mem()
