"""Step watchdog — hang detection for the training loop.

A daemon thread fed heartbeats from the engine's step boundary. A stall is
"no beat within ``hang_factor`` × the rolling median step time" (floored at
``min_interval_s`` so compile/warmup steps don't false-positive). On
detection it dumps every thread's stack plus the telemetry summary, emits a
``Fault/hang`` telemetry event, and — when ``abort`` is set — hard-exits
with a distinct code so the elastic agent can restart the gang
(docs/RESILIENCE.md exit-code contract).

The clock is injectable and the detector core (``check()``) is callable
directly, so tests pin the trigger math without real sleeps.
"""

import collections
import os
import statistics
import sys
import threading
import time
import traceback

#: exit code for a watchdog-initiated abort (see docs/RESILIENCE.md)
EXIT_WATCHDOG_ABORT = 85


def format_all_stacks():
    """Every live thread's current stack, watchdog thread included —
    the ``py-spy dump`` a preempted-in-CI run never got."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = []
    for tid, frame in sorted(sys._current_frames().items()):
        lines.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        lines.extend(ln.rstrip("\n")
                     for ln in traceback.format_stack(frame))
    return "\n".join(lines)


class StepWatchdog:
    """Heartbeat-driven stall detector.

    Usage (what the engine does when ``resilience.watchdog.enabled``)::

        wd = StepWatchdog(hang_factor=10.0, min_interval_s=60.0)
        wd.start()
        for batch in loader:
            train_step(batch)
            wd.beat()          # step boundary = heartbeat
        wd.stop()

    ``beat()`` with no argument uses the inter-beat interval as the step
    time sample, so the rolling median tracks the full loop cadence
    (forward+backward+step+data), which is what a hang interrupts.
    """

    def __init__(self, hang_factor=10.0, min_interval_s=60.0,
                 poll_interval_s=1.0, window=32, abort=False,
                 exit_code=EXIT_WATCHDOG_ABORT, on_hang=None,
                 clock=time.monotonic, dump_file=None):
        if hang_factor <= 0 or min_interval_s <= 0 or poll_interval_s <= 0:
            raise ValueError("watchdog intervals/factor must be positive")
        self.hang_factor = float(hang_factor)
        self.min_interval_s = float(min_interval_s)
        self.poll_interval_s = float(poll_interval_s)
        self.abort = bool(abort)
        self.exit_code = int(exit_code)
        self.on_hang = on_hang
        self.dump_file = dump_file
        self._clock = clock
        self._samples = collections.deque(maxlen=int(window))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._last_beat = None
        self._beat_seq = 0
        self._fired_seq = -1   # fire at most once per stall (re-arm on beat)
        self.fired = 0
        self.last_report = None

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._last_beat = self._clock()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="ds-tpu-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5 * self.poll_interval_s)

    # -- heartbeat -------------------------------------------------------
    def beat(self, step_seconds=None):
        now = self._clock()
        with self._lock:
            if step_seconds is None and self._last_beat is not None:
                step_seconds = now - self._last_beat
            if step_seconds is not None and step_seconds > 0:
                self._samples.append(step_seconds)
            self._last_beat = now
            seq = self._beat_seq = self._beat_seq + 1
        try:
            # black-box heartbeat: the postmortem bundle's ring shows the
            # last beat before death (telemetry/flightrec.py, O(1))
            from deepspeed_tpu.telemetry import flightrec
            flightrec.record("watchdog", "watchdog/beat",
                             {"seq": seq,
                              "step_seconds": round(step_seconds, 6)
                              if step_seconds is not None else None})
        except Exception:
            pass

    def threshold(self):
        """Current stall threshold in seconds."""
        with self._lock:
            if not self._samples:
                return self.min_interval_s
            med = statistics.median(self._samples)
        return max(self.min_interval_s, self.hang_factor * med)

    # -- detection -------------------------------------------------------
    def check(self):
        """One detector pass; returns the report if a stall fired. Called
        from the poll thread, callable directly in tests."""
        with self._lock:
            if self._last_beat is None or self._fired_seq == self._beat_seq:
                return None
            idle = self._clock() - self._last_beat
        thr = self.threshold()
        if idle <= thr:
            return None
        with self._lock:
            if self._fired_seq == self._beat_seq:
                return None
            self._fired_seq = self._beat_seq
        return self._fire(idle, thr)

    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check()
            except Exception:  # the watchdog must outlive its own bugs
                from deepspeed_tpu.utils.logging import logger
                logger.exception("watchdog check failed")

    def _fire(self, idle, thr):
        from deepspeed_tpu.utils.logging import logger
        report = [f"step watchdog: no step progress for {idle:.2f}s "
                  f"(threshold {thr:.2f}s = max(min_interval "
                  f"{self.min_interval_s}s, hang_factor {self.hang_factor} "
                  f"x median step)); dumping stacks",
                  format_all_stacks()]
        try:
            from deepspeed_tpu import telemetry
            if telemetry.enabled():
                # a stall dump without the HBM picture is half a diagnosis —
                # wedged steps are frequently allocation-retry livelocks
                stats = telemetry.sample_memory("watchdog_stall",
                                                idle_s=round(idle, 3))
                if stats:
                    report.append(f"--- hbm snapshot ---\n{stats}")
                report.append("--- telemetry summary ---")
                report.append(telemetry.format_summary())
                telemetry.ledger_add("stall", idle)
            telemetry.record("Fault/hang", 1, kind="counter",
                             idle_s=round(idle, 3),
                             threshold_s=round(thr, 3))
        except Exception:
            pass
        report = "\n".join(report)
        self.fired += 1
        self.last_report = report
        logger.error(report)
        if self.dump_file:
            try:
                with open(self.dump_file, "w") as f:
                    f.write(report)
            except OSError:
                logger.exception(f"watchdog: cannot write {self.dump_file}")
        if self.on_hang is not None:
            try:
                self.on_hang(report)
            except Exception:
                logger.exception("watchdog on_hang callback failed")
        try:
            # a stall is an incident whether or not we abort: leave the
            # classifiable artifact (no-op without a configured destination;
            # if an injected long-sleep already flushed, this is skipped)
            from deepspeed_tpu.telemetry import flightrec
            flightrec.flush_bundle(
                "watchdog_stall",
                detail=f"no step progress for {idle:.2f}s (thr {thr:.2f}s)",
                exit_code=self.exit_code if self.abort else None)
        except Exception:
            pass
        if self.abort:
            logger.error(f"watchdog: aborting process (exit "
                         f"{self.exit_code}) so the elastic agent can "
                         f"restart the gang")
            # flush what we can; _exit skips atexit (the process is wedged —
            # a SystemExit in THIS thread would not unwedge the main thread)
            sys.stderr.flush()
            sys.stdout.flush()
            os._exit(self.exit_code)
        return report
