"""Fault injection — a process-global registry of named fault points.

The resilience layer's testability core (docs/RESILIENCE.md): production
code calls ``maybe_fail("ckpt.publish")`` at the places real faults strike
(checkpoint writers, the comm shim's host path, worker startup, the engine
step loop), and a drill/test arms those points with deterministic triggers
so every recovery path executes on CPU — no TPU preemption required.

Spec grammar (config key ``resilience.faults`` or env ``DS_TPU_FAULTS``;
entries separated by ``;`` or ``,``)::

    point:mode[@stepA[-B]][!action]

    ckpt.write:once@step3            # raise on the first hit at step 3
    ckpt.publish:n2                  # raise on the 2nd hit ever
    comm.collective:p0.25            # each hit fails with prob 0.25 (seeded)
    step.hang:once@step5!sleep2.5    # stall the step loop 2.5s at step 5
    worker.exit:once!exit7           # hard-exit the process with code 7

Modes: ``once`` (first matching hit) · ``always`` · ``n<K>`` (K-th matching
hit, 1-based) · ``p<FLOAT>`` (per-hit probability from a seeded RNG —
``resilience.fault_seed`` / ``DS_TPU_FAULT_SEED``). The optional step
window only matches once the engine has fed ``set_step``.

Actions: ``raise`` (default — raises :class:`InjectedFault`), ``sleep<S>``
(stall then continue; default for ``step.hang``), ``exit[<code>]``
(``os._exit`` — a crash, no cleanup; default for ``worker.exit``, code 1).

Disarmed (the default), ``maybe_fail`` is a constant-time no-op. Every trip
is recorded through telemetry (``Fault/<point>`` counter events) so Chrome
traces show fault→recovery intervals.
"""

import os
import random
import re
import threading
import time

#: Every point the runtime is instrumented with — where it is called:
#: ``ckpt.write``   NativeCheckpointEngine.save, between shard and manifest
#: ``ckpt.publish`` both engines, between a complete tmp dir and the atomic
#:                  os.replace that makes it the live tag (the universal
#:                  checkpoint publish trips the same point)
#: ``comm.collective`` comm.py timed_op, host-level (non-traced) calls
#: ``comm.partition`` comm.py timed_op, same site — models a network
#:                  partition (a DCN slice dropping out of the gang); the
#:                  elastic reshard path treats it as a slice loss
#: ``io.host``      checkpoint host-side npz/file writes (retry-wrapped)
#: ``step.hang``    top of DeepSpeedEngine.step()
#: ``slice.lost``   DeepSpeedEngine.step(), next to step.hang — a whole
#:                  slice dying mid-step (resilience/elastic_reshard.py)
#: ``worker.exit``  comm.init_distributed (every worker's first runtime call)
#: ``replica.lost`` PrefillDecodeFleet.step(), per replica — the serving
#:                  analog of slice.lost: the fleet marks the replica dead
#:                  and re-admits its in-flight requests elsewhere
#: ``replica.stall`` PrefillDecodeFleet.step(), same site — with the raise
#:                  action the replica skips rounds (no heartbeat) until the
#:                  failure detector declares it dead; with sleep it drags
#:                  the round
#: ``transport.drop`` KVPageTransport, BEFORE the page export — a dropped
#:                  handoff transfer is retried (retry_call); exhaustion
#:                  surfaces as HandoffError and the request re-prefills
#:                  on the decode side
#: ``transport.corrupt`` KVPageTransport wire codec, between serialize and
#:                  parse — the raise is converted into a flipped payload
#:                  byte, so the per-page CRC32 check detects it
#:                  (WireCRCError) and the wire leg re-serializes from the
#:                  still-resident export; exhaustion falls back like
#:                  transport.drop
#: ``handoff.bind_fail`` KVPageTransport, before the destination allocator
#:                  bind — pages already left the source, so no retry:
#:                  straight to the re-prefill fallback
KNOWN_POINTS = ("ckpt.write", "ckpt.publish", "comm.collective",
                "comm.partition", "io.host", "step.hang", "slice.lost",
                "worker.exit", "replica.lost", "replica.stall",
                "transport.drop", "transport.corrupt", "handoff.bind_fail")

#: points the elastic reshard path interprets as "a slice is gone" —
#: an :class:`InjectedFault` from any of these is translated into a
#: shrink-to-survivors reshard instead of a crash
SLICE_LOSS_POINTS = ("slice.lost", "comm.partition")

ENV_SPEC = "DS_TPU_FAULTS"
ENV_SEED = "DS_TPU_FAULT_SEED"

#: sleep-action faults at or above this many seconds count as a wedge
#: (an incident, not chaos latency): the injector flushes a "stall"
#: postmortem bundle BEFORE sleeping, so a kill landing mid-stall still
#: leaves evidence (telemetry/flightrec.py; no-op without a configured
#: bundle destination).
STALL_FLUSH_MIN_SLEEP_S = 30.0


class InjectedFault(RuntimeError):
    """The exception an armed ``raise``-action fault point throws."""

    def __init__(self, point, detail=""):
        super().__init__(f"injected fault at {point!r}"
                         + (f": {detail}" if detail else ""))
        self.point = point


_ENTRY_RE = re.compile(
    r"^(?P<point>[a-z_]+\.[a-z_]+)"
    r":(?P<mode>once|always|n\d+|p(?:\d+(?:\.\d+)?|\.\d+))"
    r"(?:@step(?P<lo>\d+)(?:-(?P<hi>\d+))?)?"
    r"(?:!(?P<action>raise|sleep\d+(?:\.\d+)?|exit(?:\d+)?))?$")

_DEFAULT_ACTIONS = {"step.hang": ("sleep", 3600.0), "worker.exit": ("exit", 1)}


class _Rule:
    __slots__ = ("point", "mode", "nth", "prob", "lo", "hi",
                 "action", "arg", "hits", "trips")

    def __init__(self, point, mode, nth, prob, lo, hi, action, arg):
        self.point, self.mode = point, mode
        self.nth, self.prob = nth, prob
        self.lo, self.hi = lo, hi
        self.action, self.arg = action, arg
        self.hits = 0   # window-matching hits seen
        self.trips = 0  # times actually fired

    def describe(self):
        mode = {"nth": f"n{self.nth}", "prob": f"p{self.prob}"}.get(
            self.mode, self.mode)
        win = "" if self.lo is None else (
            f"@step{self.lo}" + (f"-{self.hi}" if self.hi != self.lo else ""))
        act = self.action + ("" if self.arg is None else str(self.arg))
        return f"{self.point}:{mode}{win}!{act}"


def parse_spec(spec):
    """Parse a fault spec string into rules; raises ValueError on bad
    grammar or unknown points (typos must not silently disarm a drill)."""
    rules = []
    for raw in re.split(r"[;,]", spec or ""):
        entry = raw.strip()
        if not entry:
            continue
        m = _ENTRY_RE.match(entry)
        if m is None:
            raise ValueError(
                f"bad fault spec entry {entry!r} — expected "
                f"'point:mode[@stepA[-B]][!action]' (docs/RESILIENCE.md)")
        point = m.group("point")
        if point not in KNOWN_POINTS:
            raise ValueError(f"unknown fault point {point!r}; known: "
                             f"{', '.join(KNOWN_POINTS)}")
        mode_s = m.group("mode")
        nth = prob = None
        if mode_s[0] == "n" and mode_s != "once":
            mode, nth = "nth", int(mode_s[1:])
            if nth < 1:
                raise ValueError(f"{entry!r}: n<K> is 1-based, got {nth}")
        elif mode_s.startswith("p"):
            mode, prob = "prob", float(mode_s[1:])
            if not 0.0 <= prob <= 1.0:
                raise ValueError(f"{entry!r}: probability {prob} not in [0,1]")
        else:
            mode = mode_s  # once | always
        lo = m.group("lo")
        hi = m.group("hi")
        lo = int(lo) if lo is not None else None
        hi = int(hi) if hi is not None else lo
        if lo is not None and hi < lo:
            raise ValueError(f"{entry!r}: empty step window {lo}-{hi}")
        action_s = m.group("action")
        if action_s is None:
            action, arg = _DEFAULT_ACTIONS.get(point, ("raise", None))
        elif action_s.startswith("sleep"):
            action, arg = "sleep", float(action_s[5:])
        elif action_s.startswith("exit"):
            action, arg = "exit", int(action_s[4:] or "1")
        else:
            action, arg = "raise", None
        rules.append(_Rule(point, mode, nth, prob, lo, hi, action, arg))
    return rules


class FaultInjector:
    """Process-global fault registry (module singleton below). Thread-safe:
    the async checkpoint writer trips ``ckpt.publish`` off-thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules = {}       # point -> [_Rule]
        self._rng = random.Random(0)
        self._step = None      # engine-fed; None = unknown
        self._armed = False
        self._env_checked = False

    # -- configuration ---------------------------------------------------
    def configure(self, spec=None, seed=None, reset=True):
        """Arm from a spec string (see module docstring). ``reset=False``
        merges on top of existing rules (how the env spec layers over the
        config spec). Trip counters always restart."""
        with self._lock:
            if reset:
                self._rules = {}
            for rule in parse_spec(spec or ""):
                self._rules.setdefault(rule.point, []).append(rule)
            if seed is not None:
                self._rng = random.Random(seed)
            self._armed = bool(self._rules)
            self._env_checked = True  # explicit config wins over lazy env

    def _check_env(self):
        with self._lock:
            if self._env_checked:
                return
            self._env_checked = True
        spec = os.environ.get(ENV_SPEC)
        if spec:
            seed = int(os.environ.get(ENV_SEED, "0"))
            self.configure(spec, seed=seed, reset=False)

    def reset(self):
        with self._lock:
            self._rules = {}
            self._armed = False
            self._step = None
            self._env_checked = True  # a reset() must stay disarmed

    # -- runtime ---------------------------------------------------------
    @property
    def armed(self):
        return self._armed

    def set_step(self, step):
        self._step = step

    def maybe_fail(self, point, detail=""):
        """The production hook. No-op unless a rule for ``point`` matches;
        otherwise performs the armed action (raise / sleep / exit)."""
        if not self._env_checked:
            self._check_env()
        if not self._armed:
            return
        fire = None
        with self._lock:
            for rule in self._rules.get(point, ()):
                if rule.lo is not None and (
                        self._step is None or
                        not rule.lo <= self._step <= rule.hi):
                    continue
                rule.hits += 1
                if rule.mode == "once" and rule.trips > 0:
                    continue
                if rule.mode == "nth" and rule.hits != rule.nth:
                    continue
                if rule.mode == "prob" and self._rng.random() >= rule.prob:
                    continue
                rule.trips += 1
                fire = rule
                break
        if fire is None:
            return
        self._record_trip(fire, detail)
        if fire.action == "sleep":
            if fire.arg >= STALL_FLUSH_MIN_SLEEP_S:
                # a sleep this long is a wedge, not chaos latency — flush
                # the black box BEFORE stalling so a SIGKILL landing inside
                # the window (the kill-async-save drill) still leaves a
                # classifiable artifact
                self._flush_postmortem("stall", fire, detail)
            time.sleep(fire.arg)
            return
        if fire.action == "exit":
            # os._exit skips atexit/finally — this flush is the only
            # evidence the process will ever leave
            self._flush_postmortem("injected_exit", fire, detail,
                                   exit_code=fire.arg)
            os._exit(fire.arg)
        raise InjectedFault(point, detail or fire.describe())

    @staticmethod
    def _flush_postmortem(reason, rule, detail, exit_code=None):
        try:
            from deepspeed_tpu.telemetry import flightrec
            flightrec.flush_bundle(
                reason, detail=detail or rule.describe(),
                exit_code=exit_code,
                extra={"fault_point": rule.point, "rule": rule.describe()})
        except Exception:
            pass  # forensics must never mask the injected fault itself

    def _record_trip(self, rule, detail):
        from deepspeed_tpu.utils.logging import logger
        logger.warning(f"fault injection: tripping {rule.describe()} "
                       f"(step={self._step}, hit={rule.hits})"
                       + (f" [{detail}]" if detail else ""))
        try:
            from deepspeed_tpu import telemetry
            telemetry.record(f"Fault/{rule.point}", 1, kind="counter",
                             action=rule.action, step=self._step,
                             rule=rule.describe())
        except Exception:
            pass  # telemetry must never mask the injected fault itself

    # -- introspection ---------------------------------------------------
    def trip_count(self, point=None):
        with self._lock:
            rules = (sum(self._rules.values(), []) if point is None
                     else self._rules.get(point, ()))
            return sum(r.trips for r in rules)

    def describe(self):
        with self._lock:
            return [r.describe() for rs in self._rules.values() for r in rs]


_INJECTOR = FaultInjector()


def get_injector():
    return _INJECTOR


def configure(spec=None, seed=None, reset=True):
    _INJECTOR.configure(spec, seed=seed, reset=reset)


def reset():
    _INJECTOR.reset()


def set_step(step):
    _INJECTOR.set_step(step)


def maybe_fail(point, detail=""):
    _INJECTOR.maybe_fail(point, detail=detail)


def armed():
    return _INJECTOR.armed


def trip_count(point=None):
    return _INJECTOR.trip_count(point)
