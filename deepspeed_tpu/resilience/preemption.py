"""Preemption-aware shutdown — SIGTERM/SIGINT → emergency checkpoint.

Preemption is the dominant TPU failure mode: the scheduler sends SIGTERM
with a short grace window. The handler here turns that into a *clean*
hand-off: it only sets a flag (async-signal-safe), the engine checks the
flag at the next step boundary, writes an emergency checkpoint, and exits
with :data:`EXIT_CLEAN_PREEMPTION` — a code the elastic agent recognizes as
"clean preemption" and does NOT count against ``max_restarts``
(docs/RESILIENCE.md exit-code contract).
"""

import signal
import threading

#: exit code meaning "preempted, state saved, relaunch me at no budget cost"
EXIT_CLEAN_PREEMPTION = 83


class PreemptionHandler:
    """Install with :meth:`install`; poll :meth:`requested` at step
    boundaries. ``request()`` arms the flag programmatically (tests, or a
    cloud metadata-watcher thread that sees the preemption notice before
    the signal lands)."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._requested = threading.Event()
        self._prev = {}
        self.signal_received = None
        self.installed = False

    def install(self):
        for sig in self._signals:
            try:
                self._prev[sig] = signal.signal(sig, self._handle)
            except ValueError:
                # signal.signal only works on the main thread — stay inert
                # (request() still works) rather than crash engine init
                from deepspeed_tpu.utils.logging import logger
                logger.warning(
                    "preemption handler: not on the main thread; signal "
                    "handlers not installed (programmatic request() only)")
                self._prev.clear()
                return self
        self.installed = True
        return self

    def uninstall(self):
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev.clear()
        self.installed = False

    def _handle(self, signum, frame):
        # async-signal context: flag only, no I/O, no locks
        self.signal_received = signum
        self._requested.set()

    def request(self):
        self._requested.set()

    def requested(self):
        return self._requested.is_set()

    def clear(self):
        self._requested.clear()
        self.signal_received = None
