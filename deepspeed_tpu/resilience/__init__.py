"""Resilient training runtime — fault injection, verified checkpoints,
preemption-aware save, and a step watchdog (docs/RESILIENCE.md).

Four layers, all testable on CPU:

- :mod:`~deepspeed_tpu.resilience.faults` — process-global registry of
  named fault points (``ckpt.write``, ``ckpt.publish``, ``comm.collective``,
  ``io.host``, ``step.hang``, ``worker.exit``) armed via the ``resilience``
  config section or ``DS_TPU_FAULTS``.
- crash-consistent, checksum-verified checkpoints — implemented in
  ``runtime/checkpoint_engine/native_engine.py`` (tmp + fsync + atomic
  ``os.replace``; SHA-256 manifest; :class:`CorruptCheckpointError` on
  load; the engine quarantines corrupt tags and falls back).
- :mod:`~deepspeed_tpu.resilience.preemption` — SIGTERM/SIGINT →
  emergency checkpoint at the next step boundary, then exit
  :data:`EXIT_CLEAN_PREEMPTION` (doesn't burn elastic restart budget).
- :mod:`~deepspeed_tpu.resilience.watchdog` — heartbeat thread that flags
  stalls, dumps all-thread stacks + the telemetry summary, and optionally
  aborts with :data:`EXIT_WATCHDOG_ABORT` for the elastic agent.
- :mod:`~deepspeed_tpu.resilience.elastic_reshard` — elastic multi-slice
  training: a ``slice.lost``/``comm.partition`` fault shrinks the job to
  the surviving mesh (universal-checkpoint reshard-restore at the exact
  step), and the reverse path re-expands; cross-process, workers report
  :data:`EXIT_RESHARD_SLICE_LOSS` for the elastic agent's shrink/expand
  state machine.

This package imports only the standard library at module scope so the
elastic agent and launcher can use it without pulling in jax.
"""

from deepspeed_tpu.resilience import faults  # noqa: F401
from deepspeed_tpu.resilience.faults import (  # noqa: F401
    FaultInjector, InjectedFault, KNOWN_POINTS, SLICE_LOSS_POINTS,
    maybe_fail, parse_spec)
from deepspeed_tpu.resilience.preemption import (  # noqa: F401
    EXIT_CLEAN_PREEMPTION, PreemptionHandler)
from deepspeed_tpu.resilience.watchdog import (  # noqa: F401
    EXIT_WATCHDOG_ABORT, StepWatchdog, format_all_stacks)
from deepspeed_tpu.resilience.elastic_reshard import (  # noqa: F401
    ElasticReshardController, EXIT_RESHARD_SLICE_LOSS, SliceLostError,
    build_topology_for, is_slice_loss, replan_for_world, run_elastic,
    surviving_devices)


class CorruptCheckpointError(IOError):
    """A checkpoint failed integrity verification (missing/truncated file,
    checksum mismatch, bad manifest, leaf-count drift). Carries ``path``
    (the tag directory) and ``file`` (which member failed).

    Raised by ``NativeCheckpointEngine.load``; ``engine.load_checkpoint``
    reacts by quarantining the tag (rename to ``<tag>.corrupt``) and
    falling back to the newest prior valid tag."""

    def __init__(self, path, file=None, reason=""):
        msg = f"corrupt checkpoint at {path}"
        if file:
            msg += f" (file {file})"
        if reason:
            msg += f": {reason}"
        super().__init__(msg)
        self.path = path
        self.file = file
        self.reason = reason
