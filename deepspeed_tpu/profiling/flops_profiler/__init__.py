from deepspeed_tpu.profiling.flops_profiler.profiler import (FlopsProfiler,
                                                             get_model_profile)

__all__ = ["FlopsProfiler", "get_model_profile"]
