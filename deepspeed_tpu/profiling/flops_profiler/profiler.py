"""Flops profiler — jaxpr/XLA cost analysis instead of monkey-patching.

Reference ``profiling/flops_profiler/profiler.py``: patches
``torch.nn.functional`` and Tensor methods (:839,:857) to count MACs during a
profiled step, plus module hooks for a latency tree. On TPU the program IS an
inspectable artifact: ``jax.make_jaxpr`` gives the op graph for MAC counting
and ``jit(...).lower().compile().cost_analysis()`` gives XLA's own
flops/bytes estimates for the *optimized* program — strictly more accurate
than eager op counting (it sees fusion and rematerialization).

API parity: ``get_model_profile`` (reference :1112) returns
(flops, macs, params); ``FlopsProfiler`` wraps an engine and prints the
profile at ``profile_step`` like the config-driven reference flow.
"""

import numpy as np

import jax

from deepspeed_tpu.utils.logging import log_dist, logger


def _dot_general_macs(eqn):
    """MACs of a dot_general: product of batch, contracting, and free dims."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    batch = int(np.prod([lhs.shape[d] for d in lb])) if lb else 1
    contract = int(np.prod([lhs.shape[d] for d in lc])) if lc else 1
    lhs_free = int(np.prod([lhs.shape[d] for d in range(lhs.ndim)
                            if d not in lc and d not in lb]))
    rhs_free = int(np.prod([rhs.shape[d] for d in range(rhs.ndim)
                            if d not in rc and d not in rb]))
    return batch * contract * lhs_free * rhs_free


def _conv_macs(eqn):
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    # out elements x (kernel spatial x in_channels)
    kernel = int(np.prod(rhs.shape[:-1]))  # spatial dims * in_ch (jax layout varies)
    return int(np.prod(out.shape)) * kernel // max(1, out.shape[-1] or 1)


def count_macs_jaxpr(jaxpr):
    """Walk a (closed) jaxpr counting multiply-accumulates in matmuls/convs,
    descending into sub-jaxprs (scan/while/cond/pjit/remat)."""
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_macs(eqn)
        elif name == "conv_general_dilated":
            total += _conv_macs(eqn)
        elif name == "scan":
            inner = eqn.params["jaxpr"].jaxpr
            total += eqn.params["length"] * count_macs_jaxpr(inner)
        elif name == "while":
            # cost is data-dependent; count one body iteration
            total += count_macs_jaxpr(eqn.params["body_jaxpr"].jaxpr)
        elif name == "cond":
            total += max((count_macs_jaxpr(b.jaxpr)
                          for b in eqn.params["branches"]), default=0)
        else:
            for p in ("jaxpr", "call_jaxpr"):
                if p in eqn.params:
                    sub = eqn.params[p]
                    total += count_macs_jaxpr(getattr(sub, "jaxpr", sub))
    return total


def xla_cost_analysis(fn, *args):
    """XLA's own post-optimization estimate: {"flops":..., "bytes accessed":...}.
    Returns {} when the backend doesn't expose cost analysis."""
    try:
        compiled = jax.jit(fn).lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return dict(ca) if ca else {}
    except Exception as e:  # pragma: no cover - backend dependent
        logger.debug(f"cost_analysis unavailable: {e}")
        return {}


def get_model_profile(model=None, args=None, kwargs=None, fn=None,
                      print_profile=True, detailed=False, as_string=False):
    """(flops, macs, params) of one forward (reference profiler.py:1112).

    Pass either a flax ``model`` + example ``args`` batch, or a pure ``fn``
    with ``args`` tuple."""
    kwargs = kwargs or {}
    if fn is None:
        batch = args
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        fn = lambda p, b: model.apply({"params": p}, b)
        call_args = (params, batch)
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(params))
    else:
        call_args = tuple(args or ())
        n_params = 0
    jaxpr = jax.make_jaxpr(fn)(*call_args)
    macs = count_macs_jaxpr(jaxpr.jaxpr)
    ca = xla_cost_analysis(fn, *call_args)
    flops = int(ca.get("flops", 2 * macs))
    if print_profile:
        log_dist(f"flops profile: fwd_flops={_fmt(flops)} macs={_fmt(macs)} "
                 f"params={_fmt(n_params)}"
                 + (f" hbm_bytes={_fmt(ca['bytes accessed'])}"
                    if "bytes accessed" in ca else ""), ranks=[0])
    if as_string:
        return _fmt(flops), _fmt(macs), _fmt(n_params)
    return flops, macs, n_params


def _fmt(n):
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if n >= div:
            return f"{n/div:.2f}{unit}"
    return str(int(n))


# ---------------------------------------------------------------------------
# Closed-form FLOP formulas for the Pallas kernel entry points (ops/kernels).
# XLA cost analysis cannot see inside a pallas_call, so per-kernel counts come
# from these analytic expressions instead. Conventions: one MAC = 2 FLOPs;
# attention counts QK^T + PV (the two big GEMMs), softmax is ignored as O(n)
# next to the O(n*d) matmuls — matching the reference flops profiler.
# ---------------------------------------------------------------------------
def _flash_mha_flops(batch, heads, q_len, kv_len, head_dim, causal=False):
    """QK^T (2*Sq*Skv*D) + PV (2*Sq*Skv*D) per head; causal masks half the
    score matrix."""
    f = 4.0 * batch * heads * q_len * kv_len * head_dim
    return int(f * (0.5 if causal else 1.0))


def _paged_mha_flops(num_seqs, heads, q_len, kv_len, head_dim):
    """Decode-style attention over paged KV: same two GEMMs per sequence."""
    return int(4.0 * num_seqs * heads * q_len * kv_len * head_dim)


def _sparse_mha_flops(batch, heads, q_len, kv_len, head_dim, density=1.0):
    """Block-sparse attention only computes the live fraction of blocks."""
    return int(4.0 * batch * heads * q_len * kv_len * head_dim * density)


def _moe_ffn_gmm_flops(tokens, d_model, d_ff, topk=1):
    """Grouped GEMM expert FFN: up-proj (2*d_model*d_ff) + down-proj
    (2*d_ff*d_model) per routed token-copy."""
    return int(4.0 * tokens * topk * d_model * d_ff)


def _quantized_matmul_flops(m, n, k):
    """Int8/int4 GEMM still does m*n*k MACs (dequant epilogue is O(m*n))."""
    return int(2.0 * m * n * k)


KERNEL_FLOPS = {
    "flash_mha": _flash_mha_flops,
    "paged_mha": _paged_mha_flops,
    "sparse_mha": _sparse_mha_flops,
    "moe_ffn_gmm": _moe_ffn_gmm_flops,
    "quantized_matmul": _quantized_matmul_flops,
}


def register_kernel_flops(name, formula):
    """Register/override the closed-form FLOP formula for a kernel name (the
    same names ``ops/registry.sharded_kernel_call`` dispatches under)."""
    KERNEL_FLOPS[name] = formula


def kernel_flops(name, **dims):
    """FLOPs for one named Pallas kernel invocation from its dimensions.
    Raises KeyError for unknown kernels so typos fail loudly."""
    return KERNEL_FLOPS[name](**dims)


class FlopsProfiler:
    """Engine-attached profiler (reference FlopsProfiler class + the engine's
    ``flops_profiler`` config flow): at ``profile_step`` it analyzes the
    compiled micro-step and reports flops, MACs, params, achieved TFLOPS."""

    def __init__(self, engine=None, config=None):
        self.engine = engine
        self.config = config or (engine.config.flops_profiler_config
                                 if engine is not None else None)
        self.flops = 0
        self.macs = 0
        self.params = 0
        self.profiled = False

    @property
    def enabled(self):
        return bool(self.config and self.config.enabled)

    def should_profile(self, step):
        return (self.enabled and not self.profiled
                and step >= self.config.profile_step)

    def profile_engine_step(self, batch):
        """Analyze the engine's fused micro-step (fwd+bwd+accumulate) on a
        real batch: jaxpr MAC count + XLA cost analysis of the compiled
        program."""
        eng = self.engine
        eng._ensure_initialized(batch)
        eng._compiled()
        sharded = eng._shard_batch(batch)
        fused = getattr(eng, "_fused_step_fn", None)
        if fused is not None:
            # fused_step: the program that actually runs includes the
            # optimizer apply — profile it, not the unused micro-step
            lr = eng._schedule_fn(eng.global_steps)
            fn = lambda st, b: fused(st, b, lr)
        else:
            fn = eng._micro_step_fn
        jaxpr = jax.make_jaxpr(fn)(eng.state, sharded)
        self.macs = count_macs_jaxpr(jaxpr.jaxpr)
        try:
            lowered = (fused.lower(eng.state, sharded, lr) if fused is not None
                       else fn.lower(eng.state, sharded))
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
        except Exception:
            ca = {}
        self.flops = int((ca or {}).get("flops", 2 * self.macs))
        self.params = sum(
            int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(
                eng.state.params) if hasattr(l, "shape"))
        self.profiled = True
        # feed the MFU numerator: one micro-step's FLOPs times the GAS
        # window is the model work per optimizer step
        from deepspeed_tpu import telemetry
        gas = getattr(eng, "gradient_accumulation_steps_value", 1) or 1
        telemetry.set_model_flops(flops_per_step=self.flops * gas)
        self.print_model_profile(profile_step=eng.global_steps,
                                 output_file=self.config.output_file
                                 if self.config else None)
        return self.flops, self.macs

    def profile(self, fn, *args):
        jaxpr = jax.make_jaxpr(fn)(*args)
        self.macs = count_macs_jaxpr(jaxpr.jaxpr)
        ca = xla_cost_analysis(fn, *args)
        self.flops = int(ca.get("flops", 2 * self.macs))
        self.profiled = True
        return self.flops, self.macs

    def get_total_flops(self, as_string=False):
        return _fmt(self.flops) if as_string else self.flops

    def get_total_macs(self, as_string=False):
        return _fmt(self.macs) if as_string else self.macs

    def get_total_params(self, as_string=False):
        return _fmt(self.params) if as_string else self.params

    def print_model_profile(self, profile_step=1, module_depth=-1,
                            top_modules=1, detailed=True, output_file=None):
        msg = (f"flops profiler @ step {profile_step}: "
               f"flops={_fmt(self.flops)} macs={_fmt(self.macs)} "
               f"params={_fmt(self.params)}")
        if output_file:
            with open(output_file, "a") as f:
                f.write(msg + "\n")
        log_dist(msg, ranks=[0])
