"""Accelerator selection (mirrors reference ``accelerator/real_accelerator.py:51-140``).

The reference probes imports and honors a ``DS_ACCELERATOR`` env override; here
the probe is over JAX platforms. TPU (or the axon tunnel platform) selects
``TPU_Accelerator``; anything else (cpu, gpu) still routes through the same
class since all device access is via JAX regardless of platform — only the
name/capabilities differ.
"""

import os

_accelerator = None


def get_accelerator():
    global _accelerator
    if _accelerator is not None:
        return _accelerator
    override = os.environ.get("DST_ACCELERATOR")
    from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator
    _accelerator = TPU_Accelerator()
    if override:
        _accelerator._name = override
    return _accelerator


def set_accelerator(accel):
    """Injection hook (reference ``real_accelerator.py`` set_accelerator)."""
    global _accelerator
    _accelerator = accel
