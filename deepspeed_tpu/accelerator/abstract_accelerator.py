"""Accelerator abstraction.

Mirrors the reference's ``DeepSpeedAccelerator`` abstract interface
(``accelerator/abstract_accelerator.py:10``: device management, RNG, memory
stats, dtype capabilities, communication backend name, op-builder factory) with
TPU-appropriate semantics: devices are ``jax.Device`` objects, "streams" do not
exist (XLA dispatch is async; synchronization is ``block_until_ready``), and
memory stats come from PJRT ``memory_stats()``.
"""

import abc


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # --- device management (reference abstract_accelerator.py:34-58) ---
    @abc.abstractmethod
    def device_name(self, device_index=None):
        ...

    @abc.abstractmethod
    def device(self, device_index=None):
        ...

    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    def set_device(self, device_index):
        # XLA places data explicitly per-array; a mutable "current device" is
        # advisory only.
        self._current_device = device_index

    def is_available(self):
        return self.device_count() > 0

    # --- RNG (reference :63-87) ---
    @abc.abstractmethod
    def manual_seed(self, seed):
        ...

    def initial_seed(self):
        return getattr(self, "_seed", 0)

    # --- synchronization (streams/events in the reference, :93-110) ---
    def synchronize(self, device_index=None):
        """Block until all dispatched work is done (CUDA stream-sync analog)."""
        import jax
        try:
            (jax.device_put(0) + 0).block_until_ready()
        except Exception:
            pass

    # --- memory (reference :115-163) ---
    @abc.abstractmethod
    def memory_stats(self, device_index=None):
        ...

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("peak_bytes_in_use", 0)

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    # --- dtype capabilities (reference :168-181) ---
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    @abc.abstractmethod
    def supported_dtypes(self):
        ...

    # --- comm backend (reference :201) ---
    def communication_backend_name(self):
        return self._communication_backend_name

    # --- op builder hooks (reference :270-284) ---
    @abc.abstractmethod
    def create_op_builder(self, op_name):
        ...

    @abc.abstractmethod
    def get_op_builder(self, op_name):
        ...
