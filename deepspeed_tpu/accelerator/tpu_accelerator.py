"""TPU accelerator — the north-star seam from the reference's design.

The reference routes *all* device access through ``get_accelerator()``
(``accelerator/cuda_accelerator.py`` for CUDA); this is the TPU implementation
slot the reference left open (SURVEY §2.5). It covers the full 64-method
``DeepSpeedAccelerator`` contract (``/root/reference/accelerator/
abstract_accelerator.py:10``) with TPU-appropriate semantics:

- devices are ``jax.Device`` objects; "streams" do not exist (XLA dispatch is
  async per-device and ordered; synchronization is ``block_until_ready``), so
  the Stream/Event API is a truthful no-op analog whose Events still measure
  host wall-clock around synchronization points;
- graph capture (``create_graph``/``capture_to_graph``/``replay_graph``,
  reference :210-218) maps to ``jax.jit``: capture jits and warms the
  callable, replay executes the cached executable;
- memory stats come from PJRT ``Device.memory_stats()`` (``bytes_in_use``,
  ``peak_bytes_in_use``, ``bytes_limit``); backends that expose none (CPU,
  some tunneled TPU clients) report zeros rather than raising;
- tensor factories return jnp-array constructors; f64/i64 map to f32/i32
  under JAX's default x32 mode (TPUs have no f64 ALUs).
"""

import os
import time

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


def _drain_devices(devices=None):
    """Block until previously-dispatched device work completes.

    ``jax.effects_barrier()`` only waits for ORDERED EFFECTS, not ordinary
    pending async dispatch — so draining means enqueueing a trivial transfer
    behind the queued work on each device (PJRT executes launches in order
    per device) and blocking on it. Used by every synchronize() analog here.
    """
    import jax
    jax.effects_barrier()   # flush any ordered effects too
    for d in (devices if devices is not None else jax.local_devices()):
        try:
            jax.device_put(0, d).block_until_ready()
        except Exception:
            pass


class _NoOpStream:
    """Stream analog (reference :92-107). XLA queues work per-device in
    program order; there is exactly one logical stream. ``synchronize``
    drains it."""

    def __init__(self, device=None):
        self.device = device

    def synchronize(self):
        _drain_devices([self.device] if self.device is not None else None)

    def wait_stream(self, other):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _HostEvent:
    """Event analog (reference :110): records host wall-clock at a
    synchronization point; ``elapsed_time`` matches torch's ms contract."""

    def __init__(self, enable_timing=True, **_):
        self._t = None

    def record(self, stream=None):
        self._t = time.perf_counter()

    def synchronize(self):
        _drain_devices()

    def query(self):
        return self._t is not None

    def elapsed_time(self, end_event):
        if self._t is None or end_event._t is None:
            raise RuntimeError("elapsed_time: both events must be recorded")
        return (end_event._t - self._t) * 1000.0


class _JitGraph:
    """Graph-capture analog (reference :210-218). ``capture(fn, *args)`` jits
    and warms ``fn``; ``replay()`` re-executes with the captured args —
    the cached XLA executable plays the role of the CUDA graph."""

    def __init__(self):
        self._fn = None
        self._args = None
        self._kwargs = None

    def capture(self, fn, *args, **kwargs):
        import jax
        self._fn = jax.jit(fn)
        self._args, self._kwargs = args, kwargs
        out = self._fn(*args, **kwargs)
        jax.block_until_ready(out)
        return out

    def replay(self):
        if self._fn is None:
            raise RuntimeError("replay before capture")
        return self._fn(*self._args, **self._kwargs)


class _GraphCaptureContext:
    def __init__(self, graph):
        self.graph = graph

    def __enter__(self):
        return self.graph

    def __exit__(self, *exc):
        return False


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"
        self._seed = 0
        self._rng_key = None
        self._current_device = 0
        self._annotation_stack = []

    def _devices(self):
        import jax
        return jax.local_devices()

    # --- behavior flags (reference :16-30) ---
    def is_synchronized_device(self):
        return False          # XLA dispatch is asynchronous

    def use_host_timers(self):
        # no device-side event timers over PJRT: timers must bracket
        # block_until_ready on the host (utils/timer.py does)
        return True

    def resolves_data_dependency(self):
        return True           # XLA orders ops by dataflow, not stream order

    def handles_memory_backpressure(self):
        return False          # an HBM OOM is an error, not a stall

    # --- device management ---
    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index=None):
        devs = self._devices()
        return devs[device_index if device_index is not None else self._current_device]

    def device_count(self):
        return len(self._devices())

    def global_device_count(self):
        import jax
        return jax.device_count()

    def current_device(self):
        return self._current_device

    def current_device_name(self):
        return self.device_name(self._current_device)

    def set_device(self, device_index):
        self._current_device = device_index

    def synchronize(self, device_index=None):
        _drain_devices([self.device(device_index)]
                       if device_index is not None else None)

    def is_available(self):
        try:
            return len(self._devices()) > 0
        except Exception:
            return False

    # --- RNG (reference :63-88; functional keys instead of global state) ---
    def random(self):
        import jax
        return jax.random

    def manual_seed(self, seed):
        import jax
        self._seed = int(seed)
        self._rng_key = jax.random.PRNGKey(self._seed)

    def manual_seed_all(self, seed):
        self.manual_seed(seed)

    def initial_seed(self):
        return self._seed

    def prng_key(self):
        import jax
        if self._rng_key is None:
            self._rng_key = jax.random.PRNGKey(self._seed)
        return self._rng_key

    def get_rng_state(self, device_index=None):
        import numpy as np
        return np.asarray(self.prng_key())

    def set_rng_state(self, new_state, device_index=None):
        import jax.numpy as jnp
        self._rng_key = jnp.asarray(new_state)

    def default_generator(self, device_index):
        # functional analog: the generator IS the key stream
        return self.prng_key()

    # --- streams / events (no-op analogs; see module docstring) ---
    def Stream(self, device=None, **kwargs):
        return _NoOpStream(device)

    def stream(self, stream):
        return stream if hasattr(stream, "__enter__") else _NoOpStream()

    def current_stream(self, device_index=None):
        return _NoOpStream(self.device(device_index))

    def default_stream(self, device_index=None):
        return _NoOpStream(self.device(device_index))

    def Event(self, **kwargs):
        return _HostEvent(**kwargs)

    # --- graph capture (jit analogs) ---
    def create_graph(self):
        return _JitGraph()

    def capture_to_graph(self, graph, pool=None, stream=None):
        return _GraphCaptureContext(graph)

    def replay_graph(self, graph):
        return graph.replay()

    # --- memory (PJRT memory_stats; reference :115-163) ---
    def memory_stats(self, device_index=None):
        try:
            dev = self.device(device_index)
            stats = dev.memory_stats()
            if stats:
                return stats
            return self._synthesize_memory_stats(dev)
        except Exception:
            return {}

    # CPU (and some emulated) PJRT backends return no memory_stats; derive
    # bytes_in_use from the live-array set so CPU-mesh tests still get a
    # meaningful occupancy stream and peak watermark. Tagged "synthesized"
    # so consumers can tell it apart from real PJRT numbers.
    _synth_peak = {}

    def _synthesize_memory_stats(self, dev):
        import jax
        in_use = 0
        for a in jax.live_arrays():
            try:
                devs = a.sharding.device_set
            except Exception:
                continue
            if dev in devs:
                # an array sharded over N devices puts ~1/N of its bytes
                # on each
                in_use += a.nbytes // max(len(devs), 1)
        key = id(dev)
        peak = max(self._synth_peak.get(key, 0), in_use)
        self._synth_peak[key] = peak
        return {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
                "bytes_limit": 0, "synthesized": True}

    def _stat(self, key, device_index=None):
        return int(self.memory_stats(device_index).get(key, 0))

    def memory_allocated(self, device_index=None):
        return self._stat("bytes_in_use", device_index)

    def max_memory_allocated(self, device_index=None):
        return self._stat("peak_bytes_in_use", device_index)

    def reset_max_memory_allocated(self, device_index=None):
        pass  # PJRT peak counters are monotonic per-process

    def memory_cached(self, device_index=None):
        # XLA's BFC arena holds its pool internally; in-use is the honest
        # lower bound PJRT exposes
        return self._stat("bytes_in_use", device_index)

    def max_memory_cached(self, device_index=None):
        return self._stat("peak_bytes_in_use", device_index)

    def reset_max_memory_cached(self, device_index=None):
        pass

    def memory_reserved(self, device_index=None):
        stats = self.memory_stats(device_index)
        return int(stats.get("bytes_reserved", stats.get("bytes_in_use", 0)))

    def max_memory_reserved(self, device_index=None):
        return self._stat("peak_bytes_in_use", device_index)

    def reset_peak_memory_stats(self, device_index=None):
        pass

    def total_memory(self, device_index=None):
        return self._stat("bytes_limit", device_index)

    def available_memory(self, device_index=None):
        stats = self.memory_stats(device_index)
        return int(stats.get("bytes_limit", 0)) - int(stats.get("bytes_in_use", 0))

    def empty_cache(self):
        # XLA manages the HBM arena itself; garbage-collect python-side
        # references so their buffers can be freed
        import gc
        gc.collect()

    # --- dtype caps ---
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        # TPUs compute natively in bf16; fp16 works but has no hardware
        # loss-scale advantage. We still support the fp16 engine path.
        return True

    def is_fp8_supported(self):
        import jax.numpy as jnp
        return hasattr(jnp, "float8_e4m3fn")

    def is_triton_supported(self):
        return False

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    def amp(self):
        # bf16 autocast is the engine's dtype policy, not a context manager;
        # no torch.cuda.amp analog exists or is needed
        return None

    # --- profiling ranges (reference :189-193) ---
    def range_push(self, msg):
        import jax
        ctx = jax.profiler.TraceAnnotation(msg)
        ctx.__enter__()
        self._annotation_stack.append(ctx)

    def range_pop(self):
        if self._annotation_stack:
            self._annotation_stack.pop().__exit__(None, None, None)

    def lazy_call(self, callback):
        # XLA dispatch is already asynchronous; run the host callback now
        callback()

    def communication_backend_name(self):
        return self._communication_backend_name

    # --- platform info ---
    def on_tpu(self):
        import jax
        try:
            return jax.devices()[0].platform in ("tpu", "axon")
        except Exception:
            return False

    def device_kind(self):
        import jax
        try:
            return jax.devices()[0].device_kind
        except Exception:
            return "unknown"

    # --- tensor factories (reference :224-254) ---
    def _factory(self, dtype):
        import functools

        import jax.numpy as jnp

        def make(*shape, dtype=dtype):
            if len(shape) == 1 and not isinstance(shape[0], int):
                return jnp.asarray(shape[0], dtype)
            return jnp.zeros(shape, dtype)

        make.dtype = dtype
        return make

    def BFloat16Tensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.bfloat16)

    def ByteTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.uint8)

    def DoubleTensor(self):
        # f64 requires jax_enable_x64 and has no TPU ALUs; f32 is the
        # honest widest float here
        import jax.numpy as jnp
        return self._factory(jnp.float32)

    def FloatTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.float32)

    def HalfTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.float16)

    def IntTensor(self):
        import jax.numpy as jnp
        return self._factory(jnp.int32)

    def LongTensor(self):
        # x32 mode: int64 silently downcasts; int32 is the native width
        import jax.numpy as jnp
        return self._factory(jnp.int32)

    # --- host memory (reference :258-266) ---
    def pin_memory(self, tensor, align_bytes=1):
        # PJRT stages host->device transfers internally; numpy arrays are
        # the host-side representation
        import numpy as np
        return np.ascontiguousarray(tensor)

    def is_pinned(self, tensor):
        import numpy as np
        return isinstance(tensor, np.ndarray) and tensor.flags["C_CONTIGUOUS"]

    def on_accelerator(self, tensor):
        import jax
        if isinstance(tensor, jax.core.Tracer):
            return True
        if not isinstance(tensor, jax.Array):
            return False
        try:
            return all(d.platform != "cpu" for d in tensor.devices())
        except Exception:
            return False

    # --- op builders (reference op_builder factory hooks :270-288) ---
    def op_builder_dir(self):
        return "deepspeed_tpu.ops"

    def create_op_builder(self, op_name):
        builder = self.get_op_builder(op_name)
        return builder() if builder is not None else None

    def get_op_builder(self, op_name):
        from deepspeed_tpu.ops.registry import get_op_builder
        return get_op_builder(op_name)

    def build_extension(self):
        # native C extensions build via g++/ctypes JIT (ops/native), not
        # torch.utils.cpp_extension
        from deepspeed_tpu.ops import native
        return native

    def export_envs(self):
        # env prefixes a launcher must propagate to workers (reference
        # returns e.g. ['NCCL']; these are the TPU/XLA equivalents)
        return ["JAX", "XLA", "LIBTPU", "TPU", "DS_TPU"]
