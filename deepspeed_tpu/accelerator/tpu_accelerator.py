"""TPU accelerator — the north-star seam from the reference's design.

The reference routes *all* device access through ``get_accelerator()``
(``accelerator/cuda_accelerator.py`` for CUDA); this is the TPU implementation
slot the reference left open (SURVEY §2.5). Devices come from ``jax.devices()``;
memory stats from PJRT; the communication backend name is "xla" (collectives are
compiled into programs over the mesh rather than issued by a comm library).
"""

import os

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"
        self._seed = 0
        self._current_device = 0

    def _devices(self):
        import jax
        return jax.local_devices()

    # --- device management ---
    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index=None):
        devs = self._devices()
        return devs[device_index if device_index is not None else self._current_device]

    def device_count(self):
        return len(self._devices())

    def global_device_count(self):
        import jax
        return jax.device_count()

    def current_device(self):
        return self._current_device

    def current_device_name(self):
        return self.device_name(self._current_device)

    # --- RNG ---
    def manual_seed(self, seed):
        self._seed = seed

    def manual_seed_all(self, seed):
        self._seed = seed

    def prng_key(self):
        import jax
        return jax.random.PRNGKey(self._seed)

    # --- memory ---
    def memory_stats(self, device_index=None):
        try:
            dev = self.device(device_index)
            stats = dev.memory_stats()
            return stats or {}
        except Exception:
            return {}

    def empty_cache(self):
        # XLA manages HBM arena itself; garbage-collect python-side references.
        import gc
        gc.collect()

    def reset_peak_memory_stats(self, device_index=None):
        pass  # PJRT exposes no reset; peak is monotonic per-process

    # --- dtype caps ---
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        # TPUs compute natively in bf16; fp16 works but has no hardware
        # loss-scale advantage. We still support the fp16 engine path.
        return True

    def is_triton_supported(self):
        return False

    def supported_dtypes(self):
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.int32]

    def is_fp8_supported(self):
        import jax.numpy as jnp
        return hasattr(jnp, "float8_e4m3fn")

    # --- platform info ---
    def on_tpu(self):
        import jax
        try:
            return jax.devices()[0].platform in ("tpu", "axon")
        except Exception:
            return False

    def device_kind(self):
        import jax
        try:
            return jax.devices()[0].device_kind
        except Exception:
            return "unknown"

    # --- op builders (reference op_builder factory hooks) ---
    def create_op_builder(self, op_name):
        builder = self.get_op_builder(op_name)
        return builder() if builder is not None else None

    def get_op_builder(self, op_name):
        from deepspeed_tpu.ops.registry import get_op_builder
        return get_op_builder(op_name)
