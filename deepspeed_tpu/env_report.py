"""``ds_report`` — environment and op-compatibility report.

Reference ``deepspeed/env_report.py``: prints the installed-ops compatibility
matrix, torch/cuda versions and nvcc availability. The TPU analog reports the
JAX stack, the device platform/mesh, the native (C++) op build status and the
Pallas availability of each registered op.

Run: ``python -m deepspeed_tpu.env_report``
"""

import os
import shutil
import subprocess
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{YELLOW}[NO]{END}"
FAIL = f"{RED}[FAIL]{END}"


def software_report():
    rows = []
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy", "orbax.checkpoint"):
        try:
            m = __import__(mod)
            rows.append((mod, getattr(m, "__version__", "unknown"), OKAY))
        except ImportError:
            rows.append((mod, "-", NO))
    rows.append(("python", sys.version.split()[0], OKAY))
    gxx = shutil.which("g++")
    if gxx:
        try:
            v = subprocess.run(["g++", "--version"], capture_output=True,
                               text=True, timeout=10).stdout.splitlines()[0]
        except Exception:
            v = "unknown"
        rows.append(("g++ (native ops)", v, OKAY))
    else:
        rows.append(("g++ (native ops)", "-", NO))
    return rows


def hardware_report(backend_ok=None, backend_detail=""):
    from deepspeed_tpu.utils.backend_probe import probe_backend
    rows = []
    if backend_ok is None:
        kind, backend_detail = probe_backend()
        backend_ok = kind == "ok"
    if not backend_ok:
        rows.append(("jax devices", backend_detail or "backend unavailable",
                     FAIL))
        return rows
    try:
        import jax
        devs = jax.devices()
        plat = devs[0].platform if devs else "none"
        rows.append(("platform", plat, OKAY))
        rows.append(("device count", str(len(devs)), OKAY))
        rows.append(("devices", ", ".join(str(d) for d in devs[:8])
                     + (" ..." if len(devs) > 8 else ""), OKAY))
        try:
            stats = devs[0].memory_stats()
            if stats:
                rows.append(("hbm bytes_limit",
                             str(stats.get("bytes_limit", "n/a")), OKAY))
        except Exception:
            pass
        rows.append(("process count", str(jax.process_count()), OKAY))
    except Exception as e:
        rows.append(("jax devices", f"error: {e}", FAIL))
    return rows


def ops_report():
    from deepspeed_tpu.ops.registry import available_ops, get_op_builder
    rows = []
    for name in available_ops():
        builder = get_op_builder(name)()
        try:
            compatible = builder.is_compatible()
            impl = "pallas/native" if compatible else "pure-XLA fallback"
            rows.append((name, impl, OKAY if compatible else NO))
        except Exception as e:
            rows.append((name, f"error: {e}", FAIL))
    for native in ("ds_aio", "ds_cpu_adam"):
        from deepspeed_tpu.ops.native import load_native
        lib = load_native(native)
        rows.append((f"native/{native}",
                     "built" if lib is not None else "fallback",
                     OKAY if lib is not None else NO))
    return rows


def _print_table(title, rows):
    print("-" * 70)
    print(title)
    print("-" * 70)
    for name, info, status in rows:
        print(f"{name:.<32} {status} {info}")


def main(hide_operator_status=False, hide_errors_and_warnings=False):
    def clean(rows):
        return [r for r in rows if FAIL not in r[2]] \
            if hide_errors_and_warnings else rows

    # an explicit CPU pin must apply IN PYTHON here too: the probe child
    # honors it (backend_probe), but this process would still init the
    # default (axon/TPU) platform and hang on a held chip
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms and all(p.strip() in ("cpu", "") for p in platforms.split(",")):
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except ImportError:
            pass
    from deepspeed_tpu.utils.backend_probe import probe_backend
    kind, backend_detail = probe_backend()
    backend_ok = kind == "ok"
    if not backend_ok:
        # a wedged accelerator would hang every in-process jax.devices()
        # below (ops compatibility probes included) — degrade to the CPU
        # platform so the report still prints, with a loud banner
        print(f"WARNING: accelerator {backend_detail}; reporting against "
              f"the CPU platform")
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except ImportError:
            pass  # no jax at all: the software table shows the NO row

    print("DeepSpeed-TPU C++/Pallas op report")
    if not hide_operator_status:
        _print_table("op compatibility", clean(ops_report()))
    _print_table("software", clean(software_report()))
    _print_table("hardware", clean(hardware_report(
        backend_ok=backend_ok, backend_detail=backend_detail)))
    return 0


def cli_main():
    sys.exit(main())


if __name__ == "__main__":
    cli_main()
