"""Communication shim — the analog of ``deepspeed/comm/comm.py``.

The reference exposes module-level collectives over a global backend object
(``comm/comm.py:222-520``) wrapping torch.distributed/NCCL. On TPU there are two
communication contexts, and this module serves both under the same verb names:

1. **In-trace** (inside ``jit``/``shard_map``): collectives are ``jax.lax`` ops
   over a named mesh axis and are compiled into the program; these are the hot
   paths and map 1:1 — all_reduce→psum, reduce_scatter→psum_scatter,
   all_gather→all_gather, all_to_all(_single)→all_to_all, send/recv→ppermute.
   Pass ``axis_name`` (str or tuple) instead of the reference's ``group``.

2. **Host-level** (outside jit): process bring-up and occasional scalar syncs.
   ``init_distributed`` mirrors ``comm/comm.py:604`` (env discovery →
   ``jax.distributed.initialize``); ``get_rank``/``get_world_size`` are process
   rank/count; ``barrier`` synchronizes processes.

Every verb is wrapped by ``timed_op`` feeding the comms logger, mirroring
``comm/comm.py:101``.
"""

import functools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.utils import jax_compat  # noqa: F401  installs lax.axis_size on old jax

from deepspeed_tpu.resilience import faults as _faults
from deepspeed_tpu.utils.logging import logger


class ReduceOp:
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "prod"


_comms_logger = None
_initialized = False


def configure(comms_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None):
    """Configure comms logging (reference ``comm/comm.py`` configure)."""
    global _comms_logger
    from deepspeed_tpu.utils.comms_logging import CommsLogger
    if _comms_logger is None:
        _comms_logger = CommsLogger()
    _comms_logger.configure(comms_config=comms_config, enabled=enabled,
                            prof_all=prof_all, prof_ops=prof_ops, verbose=verbose)


def get_comms_logger():
    global _comms_logger
    if _comms_logger is None:
        from deepspeed_tpu.utils.comms_logging import CommsLogger
        _comms_logger = CommsLogger()
    return _comms_logger


def _in_trace(x):
    if isinstance(x, (list, tuple)):
        return any(_in_trace(t) for t in x)
    return isinstance(x, jax.core.Tracer)


def _nbytes(x):
    """Message size in bytes; list verbs (all_to_all, coalesced) sum their
    leaves. Works for concrete arrays AND tracers (aval shape/dtype)."""
    if isinstance(x, (list, tuple)):
        return sum(_nbytes(t) for t in x)
    try:
        return int(x.size) * x.dtype.itemsize
    except Exception:
        return 0


def timed_op(fn):
    """Profiling wrapper (reference ``comm/comm.py:101``).

    Host-level calls are timed with ``block_until_ready`` and fed to the
    comms logger when it is enabled. In-trace calls (inside jit/shard_map)
    compile into the program, so their device latency cannot be observed
    here — but the message size and mesh axis are known at trace time, so
    when telemetry is on each traced collective is recorded (tagged
    ``traced=True``, duration = host trace-emission time) giving per-op
    per-axis byte totals even for fully-jitted training loops."""
    import inspect
    try:
        _axis_default = inspect.signature(fn).parameters["axis_name"].default
    except Exception:
        _axis_default = None

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from deepspeed_tpu import telemetry
        log = _comms_logger
        # quantized collectives pass the true on-the-wire byte count
        # (packed ints + scales); plain collectives omit it
        wire_bytes = kwargs.pop("wire_bytes", None)
        tensor = args[0] if args else kwargs.get("tensor")
        axis = kwargs.get("axis_name", _axis_default)
        tm_on = telemetry.enabled()
        if _in_trace(tensor):
            if not tm_on:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            result = fn(*args, **kwargs)
            telemetry.record_comm(fn.__name__, _nbytes(tensor),
                                  time.perf_counter() - t0, axis=axis,
                                  traced=True, wire_bytes=wire_bytes)
            return result
        # host-level (non-traced) collective: where real comm faults strike.
        # comm.partition models a whole slice dropping off the DCN fabric —
        # the elastic reshard path (resilience/elastic_reshard.py) catches
        # the InjectedFault and shrinks to the survivors instead of dying
        _faults.maybe_fail("comm.partition", detail=fn.__name__)
        _faults.maybe_fail("comm.collective", detail=fn.__name__)
        if (log is None or not log.enabled) and not tm_on:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        result = fn(*args, **kwargs)
        try:
            jax.block_until_ready(result)
        except Exception:
            pass
        elapsed = time.perf_counter() - t0
        nbytes = _nbytes(tensor)
        if log is not None and log.enabled:
            log.append(fn.__name__, kwargs.get("log_name", fn.__name__),
                       elapsed, nbytes)
        if tm_on:
            telemetry.record_comm(fn.__name__, nbytes, elapsed, axis=axis,
                                  wire_bytes=wire_bytes)
        return result

    return wrapper


# ---------------------------------------------------------------------------
# In-trace collectives (jax.lax over mesh axes)
# ---------------------------------------------------------------------------

@timed_op
def all_reduce(tensor, op=ReduceOp.SUM, axis_name="dp", **kwargs):
    """reference ``comm/comm.py:483`` all_reduce."""
    if op == ReduceOp.SUM:
        return lax.psum(tensor, axis_name)
    if op == ReduceOp.AVG:
        return lax.pmean(tensor, axis_name)
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axis_name)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axis_name)
    if op == ReduceOp.PRODUCT:
        return jnp.exp(lax.psum(jnp.log(tensor), axis_name))
    raise ValueError(f"unknown reduce op {op}")


inference_all_reduce = all_reduce  # reference comm.py:500


@timed_op
def all_gather(tensor, axis_name="dp", axis=0, tiled=True, **kwargs):
    """reference ``comm/comm.py:228`` all_gather / :297 all_gather_into_tensor.

    ``tiled=True`` concatenates along ``axis`` (the into_tensor form);
    ``tiled=False`` stacks a new leading axis."""
    return lax.all_gather(tensor, axis_name, axis=axis, tiled=tiled)


all_gather_into_tensor = all_gather


@timed_op
def reduce_scatter(tensor, op=ReduceOp.SUM, axis_name="dp", scatter_dim=0, **kwargs):
    """reference ``comm/comm.py:446`` reduce_scatter / :246 reduce_scatter_fn.

    psum_scatter splits along ``scatter_dim`` across the axis; with
    ``op=AVG`` divides by the axis size."""
    if scatter_dim != 0:
        tensor = jnp.moveaxis(tensor, scatter_dim, 0)
    out = lax.psum_scatter(tensor, axis_name, scatter_dimension=0, tiled=True)
    if scatter_dim != 0:
        out = jnp.moveaxis(out, 0, scatter_dim)
    if op == ReduceOp.AVG:
        out = out / lax.axis_size(axis_name)
    return out


reduce_scatter_tensor = reduce_scatter


@timed_op
def all_to_all_single(tensor, axis_name="sp", split_axis=0, concat_axis=0, tiled=True, **kwargs):
    """reference ``comm/comm.py:331`` all_to_all_single."""
    return lax.all_to_all(tensor, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


@timed_op
def all_to_all(tensors, axis_name="sp", **kwargs):
    """reference ``comm/comm.py:350`` all_to_all (list form)."""
    stacked = jnp.stack(tensors, axis=0)
    out = lax.all_to_all(stacked, axis_name, split_axis=0, concat_axis=0, tiled=False)
    n = lax.axis_size(axis_name)
    return [out[i] for i in range(n)]


@timed_op
def broadcast(tensor, src=0, axis_name="dp", **kwargs):
    """reference ``comm/comm.py:222`` broadcast — keep src's value on all ranks."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, tensor, jnp.zeros_like(tensor))
    return lax.psum(masked, axis_name)


@timed_op
def reduce(tensor, dst=0, op=ReduceOp.SUM, axis_name="dp", **kwargs):
    """reference ``comm/comm.py:433`` reduce — SPMD has no single-destination
    reduce; result is materialized everywhere (dst kept for API parity)."""
    return all_reduce(tensor, op=op, axis_name=axis_name)


def send_recv(tensor, perm, axis_name="pp"):
    """Point-to-point via collective permute (reference ``runtime/pipe/p2p.py:46,67``
    send/recv pairs). ``perm`` is a list of (src, dst) pairs along ``axis_name``."""
    return lax.ppermute(tensor, axis_name, perm)


def send_next(tensor, axis_name="pp"):
    n = lax.axis_size(axis_name)
    return lax.ppermute(tensor, axis_name, [(i, (i + 1) % n) for i in range(n)])


def send_prev(tensor, axis_name="pp"):
    n = lax.axis_size(axis_name)
    return lax.ppermute(tensor, axis_name, [(i, (i - 1) % n) for i in range(n)])


def axis_rank(axis_name):
    return lax.axis_index(axis_name)


@timed_op
def gather(tensor, dst=0, axis_name="dp", axis=0, **kwargs):
    """reference ``comm/comm.py:380`` gather — SPMD materializes the gathered
    result on every device (XLA keeps it live only where used; ``dst`` kept
    for API parity)."""
    return lax.all_gather(tensor, axis_name, axis=axis, tiled=False)


@timed_op
def scatter(tensor, src=0, axis_name="dp", axis=0, **kwargs):
    """reference ``comm/comm.py:391`` scatter — each rank takes its slice of
    src's tensor (broadcast + static slice; XLA DCEs the unused shards)."""
    full = broadcast.__wrapped__(tensor, src=src, axis_name=axis_name) \
        if hasattr(broadcast, "__wrapped__") else broadcast(tensor, src=src,
                                                           axis_name=axis_name)
    idx = lax.axis_index(axis_name)
    n = lax.axis_size(axis_name)
    if full.shape[axis] % n != 0:
        raise ValueError(f"scatter: dim {axis} of size {full.shape[axis]} "
                         f"is not divisible by axis '{axis_name}' size {n}")
    size = full.shape[axis] // n
    return lax.dynamic_slice_in_dim(full, idx * size, size, axis=axis)


def monitored_barrier(group=None, timeout=None, **kwargs):
    """reference ``comm/comm.py:412`` — rank-failure detection is the
    launcher/elastic-agent's job on TPU; behaves as ``barrier``."""
    return barrier(group=group)


def _coalesce_by_dtype(tensors, exchange):
    """One fused exchange per dtype group (mixed buckets must come back in
    their own dtypes — concatenating across dtypes would silently promote).
    ``exchange(flat) -> exchanged flat`` may add leading dims."""
    groups = {}
    for i, t in enumerate(tensors):
        groups.setdefault(jnp.asarray(t).dtype, []).append(i)
    out = [None] * len(tensors)
    for dtype, idxs in groups.items():
        flat = jnp.concatenate([jnp.ravel(tensors[i]) for i in idxs])
        ex = exchange(flat)
        off = 0
        for i in idxs:
            shape = tensors[i].shape
            n = int(np.prod(shape)) if shape else 1
            out[i] = ex[..., off:off + n].reshape(ex.shape[:-1] + tuple(shape))
            off += n
    return out


@timed_op
def all_reduce_coalesced(tensors, op=ReduceOp.SUM, axis_name="dp", **kwargs):
    """reference ``comm/comm.py:512`` — fused exchange for a list of tensors
    (flatten-concat per dtype, one psum each, split)."""
    return _coalesce_by_dtype(
        tensors, lambda flat: all_reduce(flat, op=op, axis_name=axis_name))


@timed_op
def all_gather_coalesced(tensors, axis_name="dp", **kwargs):
    """reference ``comm/comm.py:475`` — gather a list of tensors in one
    exchange per dtype; returns per-tensor [world, ...] stacks."""
    return _coalesce_by_dtype(
        tensors, lambda flat: lax.all_gather(flat, axis_name, axis=0,
                                             tiled=False))


class _ImmediateHandle:
    """Async-handle parity (reference isend/irecv return works): XLA programs
    are scheduled asynchronously by dispatch, so wait() is a no-op."""

    def __init__(self, value=None):
        self.value = value

    def wait(self):
        return self.value

    def is_completed(self):
        return True


def isend(tensor, dst, src=0, axis_name="pp", **kwargs):
    """reference ``comm/comm.py:362``. SPMD point-to-point is a (src, dst)
    permute traced on every device — callers name both endpoints. The permute
    is issued into the XLA program immediately; the handle satisfies
    ``.wait()`` callers. Ranks other than ``dst`` receive zeros."""
    return _ImmediateHandle(send_recv(tensor, [(src, dst)], axis_name))


def irecv(tensor, src, dst=0, axis_name="pp", **kwargs):
    """reference ``comm/comm.py:370`` — same permute viewed from the
    receiver."""
    return _ImmediateHandle(send_recv(tensor, [(src, dst)], axis_name))


# ---------------------------------------------------------------------------
# Host-level process management
# ---------------------------------------------------------------------------

def discover_process_env(environ=None):
    """(coordinator, num_processes, process_id) from the environment —
    the reference's ``mpi_discovery`` (:673) + SLURM/launcher env paths,
    covering every ``launcher/multinode_runner.py`` backend:

    - explicit DST_*/MASTER_ADDR+RANK (ssh/local runners bake the rank),
    - SLURM (``srun``): SLURM_PROCID/SLURM_NTASKS/SLURM_JOB_NODELIST,
    - Open MPI (``mpirun``): OMPI_COMM_WORLD_RANK/SIZE,
    - MPICH/Intel MPI hydra: PMI_RANK/PMI_SIZE,
    - PDSH (rankless): this host's position in the broadcast DS_WORLD_INFO.
    """
    env = os.environ if environ is None else environ
    coordinator = env.get("DST_COORDINATOR_ADDRESS") or env.get("MASTER_ADDR")
    num_proc = int(env.get("DST_NUM_PROCESSES", env.get("WORLD_SIZE", "1")))
    if "DST_PROCESS_ID" in env or "RANK" in env:
        return coordinator, num_proc, int(env.get("DST_PROCESS_ID",
                                                  env.get("RANK", "0")))
    # SLURM discovery (reference comm.py:673 mpi_discovery analog)
    if "SLURM_PROCID" in env:
        num_proc = int(env.get("SLURM_NTASKS", num_proc))
        coordinator = coordinator or env.get(
            "SLURM_JOB_NODELIST", "localhost").split(",")[0]
        return coordinator, num_proc, int(env["SLURM_PROCID"])
    if coordinator is None and "SLURM_JOB_NODELIST" in env:
        return (env["SLURM_JOB_NODELIST"].split(",")[0],
                int(env.get("SLURM_NTASKS", "1")),
                int(env.get("SLURM_PROCID", "0")))
    # mpirun discovery: Open MPI then hydra-family (MPICH/IMPI/MVAPICH)
    if "OMPI_COMM_WORLD_RANK" in env:
        return (coordinator, int(env.get("OMPI_COMM_WORLD_SIZE", num_proc)),
                int(env["OMPI_COMM_WORLD_RANK"]))
    if "PMI_RANK" in env:
        return (coordinator, int(env.get("PMI_SIZE", num_proc)),
                int(env["PMI_RANK"]))
    # PDSH: no scheduler rank — derive it from this node's hostname position
    # in the world info the launcher broadcast
    if "DS_WORLD_INFO" in env:
        import socket
        from deepspeed_tpu.launcher.runner import decode_world_info
        hosts = list(decode_world_info(env["DS_WORLD_INFO"]))
        if len(hosts) > 1:
            hostname = socket.gethostname()
            for h in (hostname, hostname.split(".")[0]):
                if h in hosts:
                    return coordinator, len(hosts), hosts.index(h)
            # defaulting to rank 0 here would make EVERY unmatched node claim
            # rank 0 and hang the coordinator with no diagnostic
            raise RuntimeError(
                f"rank discovery: hostname {hostname!r} not found in the "
                f"launcher's world info {hosts} — use hostfile names matching "
                f"`hostname` (or a scheduler launcher that assigns ranks)")
    return coordinator, num_proc, 0


def init_distributed(dist_backend=None,
                     auto_mpi_discovery=True,
                     distributed_port=29500,
                     verbose=True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank=-1,
                     world_size=-1):
    """Bring up multi-host JAX (reference ``comm/comm.py:604`` init_distributed).

    The reference discovers ranks from MPI/AzureML/SLURM env (:650-771) and
    calls torch.distributed.init_process_group; here the equivalent is
    ``jax.distributed.initialize`` which reads the coordinator address. On a
    single host this is a no-op.
    """
    global _initialized
    if _initialized:
        return
    # worker-startup fault point: lets drills kill a worker exactly where a
    # bad host dies in production (before joining the gang)
    _faults.maybe_fail("worker.exit")
    coordinator, num_proc, proc_id = discover_process_env()
    # the launcher's env contract (launcher/runner.py node_env) carries the port
    distributed_port = int(os.environ.get("MASTER_PORT", distributed_port))
    # explicit arguments override discovery (reference init_distributed
    # rank/world_size params)
    if rank >= 0:
        proc_id = rank
    if world_size > 0:
        num_proc = world_size
    if coordinator is not None and num_proc > 1:
        if verbose:
            logger.info(f"init_distributed: coordinator={coordinator}:{distributed_port} "
                        f"process {proc_id}/{num_proc}")
        # coordinator bring-up races with worker starts across the gang —
        # absorb transient connect failures with the shared backoff policy
        from deepspeed_tpu.utils.retry import retry_call
        retry_call(
            jax.distributed.initialize, retries=3, base_delay=1.0,
            max_delay=15.0, retry_on=(RuntimeError, OSError, ValueError),
            on_retry=lambda a, e, d: logger.warning(
                f"init_distributed attempt {a} failed ({e}); "
                f"retrying in {d:.1f}s"),
            coordinator_address=f"{coordinator}:{distributed_port}",
            num_processes=num_proc,
            process_id=proc_id)
    _initialized = True


def is_initialized():
    return _initialized


def get_rank(group=None):
    return jax.process_index()


def get_world_size(group=None):
    return jax.process_count()


def get_local_rank():
    return int(os.environ.get("DST_LOCAL_RANK", os.environ.get("LOCAL_RANK", "0")))


def barrier(group=None, **kwargs):
    """Host-level process barrier (reference ``comm/comm.py:406``)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu_barrier")


monitored_barrier = barrier


def log_summary(show_straggler=False):
    """Print the comms-log summary (reference ``comm/comm.py`` log_summary).
    When telemetry is enabled its per-axis comm table (which also covers
    traced in-jit collectives) is printed alongside the host-level one."""
    out = get_comms_logger().log_all()
    from deepspeed_tpu import telemetry
    if telemetry.enabled():
        telemetry.log_summary()
    return out
