"""Autotuning experiment scheduler — queue, resources, caps, resume.

Capability analog of reference ``autotuning/scheduler.py`` (ResourceManager
:33, Node :260, Reservation :275): experiments are queued, dispatched onto
free device slots as they become available, run concurrently up to the
resource limit, and their results are persisted so an interrupted tuning
session resumes without re-running finished experiments.

TPU-native differences: experiments are Python callables in-process (engines
are fresh jits, no process relaunch or pdsh fan-out needed), a "slot" is a
chip (or a whole host for multi-host experiments), and wall-clock budgets are
enforced at dispatch time — the reference's ssh/pdsh job control collapses
into a thread pool.
"""

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

# injectable clock (the PR-2 pattern): tests pin tuning-budget/timeout
# behavior by monkeypatching this module alias, never time.* globally
_now = time.time


class Node:
    """A host with ``max_slots`` schedulable device slots (reference :260)."""

    def __init__(self, host, max_slots):
        self.host = host
        self.max_slots = int(max_slots)
        self.idle_slots = list(range(self.max_slots))
        self._lock = threading.Lock()

    def reserve_slots(self, n):
        with self._lock:
            if len(self.idle_slots) < n:
                return None
            take, self.idle_slots = self.idle_slots[:n], self.idle_slots[n:]
            return take

    def restore_slots(self, slots):
        with self._lock:
            self.idle_slots.extend(slots)


class Reservation:
    """Slots held by one running experiment (reference :275)."""

    def __init__(self, node, slots):
        self.node = node
        self.slots = slots

    def restore(self):
        self.node.restore_slots(self.slots)

    @property
    def desc(self):
        return f"{self.node.host}:{','.join(map(str, self.slots))}"


class ResourceManager:
    """Dispatch experiments onto free slots with caps and resume.

    Args:
        hosts: {host: slots} (or a plain int = slots on this host).
        results_dir: metrics.json per experiment lands in
            ``results_dir/<name>/``; existing results are not re-run.
        exp_timeout_s: per-experiment wall-clock cap (best effort in-process:
            the runner thread is abandoned and the result discarded; the
            reference kills the remote job over ssh).
        tuning_budget_s: total tuning wall-clock cap — no NEW experiment is
            dispatched past it (reference autotuner exps max-time behavior).
    """

    def __init__(self, hosts=1, results_dir=None, exp_timeout_s=None,
                 tuning_budget_s=None):
        if isinstance(hosts, int):
            hosts = {"localhost": hosts}
        self.nodes = [Node(h, s) for h, s in hosts.items()]
        self.results_dir = results_dir
        self.exp_timeout_s = exp_timeout_s
        self.tuning_budget_s = tuning_budget_s
        self.experiment_queue: List[dict] = []
        self.finished_experiments: Dict[str, dict] = {}
        self._count = 0

    # ------------------------------------------------------------- queueing
    def schedule_experiments(self, exps):
        """Queue experiment dicts ({'name': ..., 'num_slots': 1, ...}); a
        finished result on disk short-circuits the run (resume semantics,
        reference :59 skip-existing)."""
        for exp in exps:
            exp = dict(exp)
            exp.setdefault("num_slots", 1)
            exp["exp_id"] = self._count
            self._count += 1
            prior = self._load_result(exp["name"])
            if prior is not None:
                logger.info(f"autotuning scheduler: '{exp['name']}' already "
                            "has results; skipping")
                exp["result"] = prior
                exp["resumed"] = True
                self.finished_experiments[exp["name"]] = exp
                continue
            self.experiment_queue.append(exp)

    def _result_path(self, name):
        return None if self.results_dir is None else os.path.join(
            self.results_dir, name, "metrics.json")

    def _load_result(self, name):
        p = self._result_path(name)
        if p is None or not os.path.exists(p):
            return None
        try:
            with open(p) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None  # interrupted write -> re-run

    def _save_result(self, name, result):
        p = self._result_path(name)
        if p is None:
            return
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, p)  # atomic: a crash never leaves half a result

    def _reserve(self, n):
        for node in self.nodes:
            slots = node.reserve_slots(n)
            if slots is not None:
                return Reservation(node, slots)
        return None

    # ------------------------------------------------------------ dispatch
    def run(self, run_fn: Callable[[dict, Reservation], dict]):
        """Drain the queue. ``run_fn(exp, reservation) -> result dict`` (must
        contain the metric the caller will rank by). Returns
        ``finished_experiments`` {name: exp} where exp['result'] holds the
        outcome or exp['error'] the failure."""
        start = _now()
        running: List[dict] = []
        lock = threading.Lock()

        def launch(exp, res):
            done_once = threading.Event()
            claim_lock = threading.Lock()
            claimed = [False]

            def finish(error=None, result=None, elapsed=None):
                # first outcome wins: a timeout mark beats a late success.
                # done_evt is signaled LAST so run() cannot return before the
                # result file and finished_experiments entry exist — and the
                # slot restore / bookkeeping are in finally so a result-save
                # failure can never leak the reservation and hang run().
                with claim_lock:
                    if claimed[0]:
                        return
                    claimed[0] = True
                try:
                    if error is not None:
                        exp["error"] = error
                    if result is not None:
                        exp["result"] = result
                        try:
                            self._save_result(exp["name"], result)
                        except OSError as e:
                            exp["persist_error"] = f"{e}"[:200]
                    if elapsed is not None:
                        exp["elapsed_s"] = round(elapsed, 3)
                finally:
                    res.restore()
                    with lock:
                        self.finished_experiments[exp["name"]] = exp
                    done_once.set()

            def work():
                t0 = _now()
                try:
                    out = run_fn(exp, res)
                    finish(result=out, elapsed=_now() - t0)
                except Exception as e:  # experiment failure, not scheduler
                    finish(error=f"{type(e).__name__}: {e}"[:300])

            t = threading.Thread(target=work, daemon=True,
                                 name=f"exp-{exp['exp_id']}")
            rec = {"exp": exp, "thread": t, "finish": finish,
                   "done_evt": done_once,
                   "deadline": None if self.exp_timeout_s is None
                   else _now() + self.exp_timeout_s}
            t.start()
            running.append(rec)

        def alive():
            # a timed-out experiment counts as done even while its abandoned
            # thread is still running — otherwise the loop would never exit
            return [r for r in running
                    if r["thread"].is_alive() and not r["done_evt"].is_set()]

        while self.experiment_queue or alive():
            if self.experiment_queue:
                if (self.tuning_budget_s is not None
                        and _now() - start > self.tuning_budget_s):
                    for exp in self.experiment_queue:
                        exp["error"] = ("skipped: tuning wall-clock budget "
                                        "exhausted")
                        self.finished_experiments[exp["name"]] = exp
                    logger.warning(
                        f"autotuning scheduler: budget {self.tuning_budget_s}s "
                        f"exhausted; skipping "
                        f"{len(self.experiment_queue)} queued experiments")
                    self.experiment_queue.clear()
                    continue
                exp = self.experiment_queue[0]
                res = self._reserve(exp["num_slots"])
                if res is not None:
                    self.experiment_queue.pop(0)
                    launch(exp, res)
                    continue
            # per-experiment cap: mark + release slots; the runner thread is
            # abandoned (daemon) and its late outcome discarded — the
            # reference kills the remote job over ssh instead (:402 clean_up)
            now = _now()
            for r in alive():
                if r["deadline"] is not None and now > r["deadline"]:
                    r["finish"](error=f"timeout after {self.exp_timeout_s}s")
                    r["deadline"] = None
            time.sleep(0.01)
        return self.finished_experiments

    # ------------------------------------------------------------- results
    def parse_results(self, metric, maximize=True):
        """Best finished experiment by ``result[metric]`` (reference :212)."""
        best = None
        for exp in self.finished_experiments.values():
            r = exp.get("result")
            if not r or metric not in r:
                continue
            if best is None:
                best = exp
            elif maximize and r[metric] > best["result"][metric]:
                best = exp
            elif not maximize and r[metric] < best["result"][metric]:
                best = exp
        return best

    def status(self):
        done = sum(1 for e in self.finished_experiments.values())
        return {"queued": len(self.experiment_queue), "finished": done,
                "idle_slots": sum(len(n.idle_slots) for n in self.nodes)}
