from deepspeed_tpu.autotuning.autotuner import Autotuner, autotune
from deepspeed_tpu.autotuning.scheduler import (Node, Reservation,
                                                ResourceManager)

__all__ = ["Autotuner", "autotune", "ResourceManager", "Node", "Reservation"]
