from deepspeed_tpu.autotuning.autotuner import Autotuner, autotune

__all__ = ["Autotuner", "autotune"]
